"""Benchmark: ResNet-50 training throughput on one TPU chip.

Matches BASELINE.json's flagship config (benchmark/fluid/resnet.py,
ImageNet-shape inputs, Momentum+L2, batch 256 global). The north star is
v5e-16 >= 8xV100; published 8xV100 fp32 ResNet-50 throughput of that era is
~2.9k images/s total, i.e. ~181 images/s per v5e chip at 16 chips. We report
images/sec on ONE chip and vs_baseline = value / 181.25.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}
(+ "backend"/"note" keys when degraded to the CPU smoke path).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_PER_CHIP = 181.25  # 8xV100 fp32 (~2900 img/s) / 16 chips


def _last_real_chip_result():
    """Newest committed BENCH_r*.json value, cited in fallback output."""
    import glob
    files = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")))
    for path in reversed(files):
        try:
            with open(path) as f:
                rec = json.load(f)
            rec = rec.get("parsed", rec)    # driver wraps the JSON line
            if rec.get("value", 0) > 100:   # a real-chip number
                return "%s %.2f %s" % (os.path.basename(path),
                                       rec["value"], rec.get("unit", ""))
        except (OSError, ValueError, AttributeError):
            continue
    return None


def _backend_probe(timeout=120):
    """Probe the default backend in a subprocess: jax init can block
    indefinitely when the TPU transport is wedged (same guard as
    __graft_entry__.dryrun_multichip)."""
    import subprocess
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def init_backend(smoke=False, require_tpu=False, tool="bench"):
    """Shared wedge-avoidance preamble for the bench tools: probe the
    backend in a subprocess (never inline — a wedged transport hangs jax
    init), pin CPU on failure or in smoke mode, honor the require_tpu
    exit-3 contract, and return (on_tpu, backend_label) where
    backend_label is None on TPU and a self-describing provenance string
    on any CPU path."""
    backend = None if smoke else _backend_probe()
    if backend is None:
        if require_tpu and not smoke:
            print("%s: TPU transport unreachable" % tool, file=sys.stderr)
            sys.exit(3)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if backend is None:
        jax.config.update("jax_platforms", "cpu")
    on_tpu = jax.default_backend() == "tpu"
    if require_tpu and not smoke and not on_tpu:
        # a healthy CPU-only backend is still not a chip measurement
        print("%s: backend is %r, not tpu" % (tool, jax.default_backend()),
              file=sys.stderr)
        sys.exit(3)
    if on_tpu:
        return True, None
    if smoke:
        return False, "cpu (smoke mode; transport not probed)"
    if backend is None:
        return False, "cpu-fallback (TPU transport unreachable)"
    return False, "cpu"


def main():
    backend = _backend_probe()
    if backend is None:
        # TPU transport unreachable — degrade to the CPU smoke path so
        # the harness still gets its JSON line instead of hanging
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if backend is None:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import functionalizer
    from paddle_tpu.models import resnet

    batch = int(os.environ.get("BENCH_BATCH", 256))
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu:
        batch = 16  # CPU smoke mode
    # bf16 AMP (fp32 master weights + MXU-native bf16 matmuls) unless
    # explicitly disabled — the TPU-idiomatic training precision
    if os.environ.get("BENCH_AMP", "1") == "1":
        fluid.set_amp(True)
    # NHWC: channels-last activations (lane-aligned BN); filters stay OIHW
    layout = os.environ.get("BENCH_LAYOUT", "NHWC")

    main_prog, startup, feeds, loss, acc, predict = resnet.get_model(
        batch_size=batch, class_dim=1000, depth=50, dataset="imagenet",
        lr=0.1, is_train=True, layout=layout)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    scope = fluid.global_scope()
    state_names = tuple(functionalizer.persistable_names(main_prog))
    # whole-graph AD: one jax.vjp over the forward region (vs per-op
    # stashed vjps). Required for BENCH_REMAT to mean anything — a
    # jax.checkpoint around a program whose backward is already baked in
    # is a no-op (there is no outer differentiation for the policy to
    # act on); with whole-graph AD the save_only_these_names("conv_out")
    # policy genuinely drops BN/activation tails and recomputes them in
    # the backward (ROOFLINE.md remat lever).
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # "conv_out" keeps every conv output (recompute BN/relu tails);
    # "block_out" keeps only residual-block boundaries (recompute block
    # interiors) — the larger projected lever (tools/fused_block_traffic.py)
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "conv_out")
    whole_graph = os.environ.get("BENCH_WHOLEGRAPH", "1") == "1"
    if whole_graph or remat:
        step_fn = functionalizer.build_whole_graph_step_fn(
            main_prog, ("data", "label"), (loss.name,), state_names,
            remat_policy=remat_policy if remat else None)
        if step_fn is None and remat:
            # never mislabel a baseline run as a remat measurement
            raise RuntimeError(
                "BENCH_REMAT=1 but the program is ineligible for "
                "whole-graph AD (remat would silently not engage)")
        if step_fn is None:
            step_fn = functionalizer.build_step_fn(
                main_prog, ("data", "label"), (loss.name,), state_names)
    else:
        step_fn = functionalizer.build_step_fn(
            main_prog, ("data", "label"), (loss.name,), state_names)
    jitted = jax.jit(step_fn, donate_argnums=(0,))

    state = {n: scope.get(n) for n in state_names
             if scope.get(n) is not None}
    rng = np.random.RandomState(0)
    img_shape = (batch, 3, 224, 224) if layout == "NCHW" \
        else (batch, 224, 224, 3)
    iters = 20 if on_tpu else 5
    # BENCH_PREFETCH=<depth>: feed the loop through the device prefetch
    # queue — every batch is freshly generated ON THE HOST and staged by
    # the background thread (reader.prefetch_to_device, PIPELINE.md), so
    # the number includes the real per-step feed path with the pipeline
    # hiding it. Default: pre-staged rotating device batches (the
    # double-buffer reader's steady state; feed cost amortized away).
    prefetch = int(os.environ.get("BENCH_PREFETCH", "0"))
    if prefetch > 0:
        from paddle_tpu import reader as reader_mod

        def host_batches():
            for _ in range(2 + iters):
                yield {"data": rng.randn(*img_shape).astype(np.float32),
                       "label": rng.randint(0, 1000, (batch, 1))
                       .astype(np.int32)}
        feed_it = reader_mod.prefetch_to_device(host_batches, prefetch)()
        next_feed = lambda i: next(feed_it)  # noqa: E731
    else:
        # pre-staged rotating batches
        n_batches = 4
        images = [jax.device_put(rng.randn(*img_shape).astype(np.float32))
                  for _ in range(n_batches)]
        labels = [jax.device_put(rng.randint(0, 1000, (batch, 1))
                                 .astype(np.int32))
                  for _ in range(n_batches)]
        next_feed = lambda i: {"data": images[i % n_batches],  # noqa: E731
                               "label": labels[i % n_batches]}

    # warmup / compile; force a host round-trip — through the axon relay,
    # block_until_ready alone does not reliably fence remote execution
    for i in range(2):
        fetches, state = jitted(state, next_feed(i), np.uint32(i))
    warm_loss = float(np.asarray(fetches[0]))
    assert np.isfinite(warm_loss)

    t0 = time.perf_counter()
    for i in range(iters):
        fetches, state = jitted(state, next_feed(i + 2), np.uint32(i + 2))
    final_loss = float(np.asarray(fetches[0]))  # host transfer = real fence
    dt = time.perf_counter() - t0
    assert np.isfinite(final_loss)

    imgs_per_sec = batch * iters / dt
    # MFU note: ResNet-50 train ~= 12.3 GFLOP/image (2.05 GMAC fwd x2 x3).
    # v5e bf16 peak 197 TFLOP/s. Round-3 profile evidence
    # (tools/profile_step.py on the real chip): the step runs at 97% of
    # HBM peak (797 of 819 GB/s effective, 79 GB/step at batch 256) —
    # the workload is at the memory roofline, not compute-bound. A
    # hand-written pure-JAX bf16 NHWC ResNet-50 train step on the same
    # chip (tools/pure_jax_resnet.py) reaches 2258 img/s (14.1% MFU),
    # i.e. this framework is ~10% FASTER than idiomatic hand-written JAX;
    # the remaining gap to 30%+ MFU requires halving HBM traffic via
    # cross-layer fused conv pipelines (Pallas), not better op lowering.
    tflops = imgs_per_sec * 12.3e9 / 1e12
    if on_tpu:
        note = ""
        if layout == "NHWC" and batch == 256:
            # measured for THIS config (NHWC/256/v5e) in round 3
            note = (" (97% of HBM peak — memory-roofline-bound; pure-JAX"
                    " reference on this chip: 14.1%)")
        print(("MFU note: %.1f TFLOP/s model FLOPs = %.1f%% of bf16 peak"
               % (tflops, tflops / 197.0 * 100.0)) + note)
    result = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_PER_CHIP, 3),
        # feed provenance: staged rows amortize the transfer away,
        # prefetch rows include the real host feed path hidden by the
        # pipeline — the two must never be compared unlabeled
        **({"feed": "prefetch(depth=%d)" % prefetch}
           if prefetch > 0 else {}),
    }
    if not on_tpu:
        # the number above is the CPU smoke path — make that impossible
        # to misread as a TPU regression
        result["backend"] = ("cpu-fallback (TPU transport unreachable)"
                             if backend is None else "cpu")
        prior = _last_real_chip_result()
        if prior:
            result["note"] = "last real-chip result: %s" % prior
    print(json.dumps(result))


if __name__ == "__main__":
    main()
