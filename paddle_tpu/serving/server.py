"""Threaded inference server + client over the native wire protocol.

The serving front: the same length-prefixed typed-wire framing as the
parameter-server transport (distributed/rpc.py over native/wire.py — no
pickle ever touches a socket), carrying four commands:

  infer         {"cmd","model","feeds"{name->ndarray},"deadline_ms"?,
                 "version"?,"priority"?} -> {"ok","fetches"[ndarray...]}
                 or {"error","code"} with code in {"overloaded",
                 "deadline","no_model","bad_request","internal"};
                 an "overloaded" reply carries "shed_priority" — the
                 class the lowest-priority-first policy dropped
  load_model    {"cmd","name","path","version"?,"replicas"?,"devices"?}
                 — hot swap; replicas/devices are the device placement
                 spec (N, 'auto', or explicit device names)
  unload_model  {"cmd","name"} — drain then remove
  stats         {"cmd"} -> the ServingMetrics snapshot (now with
                 per-replica lane stats per model)
  health        {"cmd"} -> per-model SLO state (ok/degraded/breach,
                 burn rates) + lane/thread liveness + last-decode-step
                 age (OBSERVABILITY.md "SLOs & burn rates")
  flight        {"cmd","reason"?,"force"?} -> trigger a flight-recorder
                 post-mortem bundle; reply carries the committed path
  fleet         {"cmd","set_policy"?,"dry_run"?} -> fleet-controller
                 status (per-model state/replicas/paged, recent
                 actions, policies); set_policy maps model -> policy
                 body, dry_run flips rehearsal mode (SERVING.md
                 "Fleet controller")
  shutdown      graceful drain, then the server stops accepting

Admission control is the batcher's bounded queue: a request past
`FLAGS.serving_max_queue` is answered immediately with an "overloaded"
error (shed-not-hang).  Per-request deadlines bound BOTH queue wait and
the reply wait server-side; the client's `infer` reuses the shared
jittered-backoff RetryPolicy (utils/retry.py) to re-offer shed requests
until its deadline — jitter matters for the same reason it does on the
pserver plane: synchronized retries stampede a recovering server.

Graceful drain on shutdown: stop admitting, finish every queued
request, answer it, then exit — chaos-tested (tools/chaos.py FlakyProxy
+ slow-worker injection) in tests/test_serving.py.
"""

import os
import socket
import socketserver
import threading
import time

import numpy as np

from ..distributed.rpc import _recv_msg, _send_msg
from ..flags import FLAGS
from ..native.wire import WireError
from ..obs import tracing as obs_tracing
from .batcher import BatcherClosed, DeadlineExceeded, ServerOverloaded
from .metrics import ServingMetrics
from .model_registry import ModelRegistry

__all__ = ["InferenceServer", "ServingClient", "ServingError",
           "StreamBroken"]

_CLOSE = object()


class ServingError(RuntimeError):
    """Server-side failure reported over the wire (non-typed codes)."""


class StreamBroken(ServingError):
    """An ``infer_stream`` connection died mid-generation.

    ``received`` counts the tokens already yielded — those are REAL
    (the server committed them); ``trace_id``/``backend`` identify the
    stream for re-placement.  Deliberately a ServingError subclass and
    NOT a ConnectionError: a generic reconnect-and-retry wrapper (the
    one-shot verbs' idiom) must never catch a broken stream and
    silently restart it from token 0 — that duplicates committed
    output.  Recovery is a NEW stream: through the federation frontend
    the same trace_id re-pins onto a live backend (affinity re-pin,
    paddle_tpu/federation/frontend.py), or the caller restarts
    explicitly with the received-token prefix in hand."""

    def __init__(self, message, trace_id=None, received=0,
                 backend=None):
        super(StreamBroken, self).__init__(message)
        self.trace_id = trace_id
        self.received = int(received)
        self.backend = backend


def _error_reply(exc):
    if isinstance(exc, ServerOverloaded):
        reply = {"error": str(exc), "code": "overloaded"}
        if getattr(exc, "priority", None) is not None:
            # which priority class was shed (the arrival, or the queued
            # request it evicted) — the client re-raises with it
            reply["shed_priority"] = int(exc.priority)
        return reply
    if isinstance(exc, (DeadlineExceeded, TimeoutError)):
        return {"error": str(exc), "code": "deadline"}
    if isinstance(exc, KeyError):
        return {"error": str(exc.args[0]) if exc.args else str(exc),
                "code": "no_model"}
    if isinstance(exc, (ValueError, TypeError, BatcherClosed)):
        return {"error": str(exc), "code": "bad_request"}
    return {"error": "%s: %s" % (type(exc).__name__, exc),
            "code": "internal"}


class InferenceServer:
    """One serving endpoint over a ModelRegistry.

    `model_root`: optional directory whose immediate subdirectories are
    loaded at start as models (subdir name == model name) — the
    "directory of artifacts -> multi-tenant service" contract."""

    def __init__(self, endpoint="127.0.0.1:0", model_root=None,
                 max_queue=None, deadline_ms=None, workers=None,
                 buckets=None, replicas=None, federation=None,
                 backend_id=None, capacity_mb=None):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        # federation membership (paddle_tpu/federation): a frontend
        # endpoint to lease against — this server registers at start,
        # heartbeats its resident-model/queue payload, and deregisters
        # on shutdown.  None falls back to FLAGS.federation_frontend
        # (empty = standalone, the default).
        self._federation = federation if federation is not None \
            else (FLAGS.federation_frontend or None)
        self._backend_id = backend_id
        self._capacity_mb = capacity_mb
        self._fed_link = None
        self.metrics = ServingMetrics()
        # the unified telemetry surface (OBSERVABILITY.md): this
        # server's counters join the process-wide MetricsRegistry the
        # `metrics` RPC verb and tools/metrics_dump.py render
        from ..obs import registry as obs_registry
        self._obs_registry = obs_registry.default()
        self._obs_registry.attach_serving(self.metrics)
        # the judgment layer (OBSERVABILITY.md "SLOs & burn rates"):
        # a background monitor samples this server's counters into a
        # bounded time-series ring and evaluates declared SLOs
        # (FLAGS.serving_slo / slo.declare) into the ok/degraded/
        # breach state machine the `health` verb renders; breaches arm
        # the flight recorder.  FLAGS.slo_monitor=false opts out.
        self.slo = None
        if FLAGS.slo_monitor:
            from ..obs import slo as obs_slo
            self.slo = obs_slo.SLOMonitor.from_flags(self.metrics)
        # the control plane above the judgment layer (SERVING.md
        # "Fleet controller"): acts on the SLO/queue/occupancy/shed
        # signals through the registry's actuators — replica-set
        # scaling, cold-model paging, pressure degradation.
        # FLAGS.fleet_controller=false (default) keeps it off.
        self.fleet = None
        self._flight_provider = None
        # `replicas`: default placement spec for every model this server
        # loads (int N / 'auto' / explicit device list — SERVING.md
        # multi-chip serving); a load_model RPC can override per model
        self.registry = ModelRegistry(
            metrics=self.metrics, max_queue=max_queue,
            deadline_ms=deadline_ms, workers=workers, replicas=replicas)
        self._default_buckets = buckets
        self._model_root = model_root
        self._stopped = False
        self._draining = False
        self._server = None
        self._thread = None

    # ------------------------------------------------------------------

    def _load_root(self):
        root = self._model_root
        if not root:
            return
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if os.path.isdir(path):
                self.registry.load_model(name, path,
                                         buckets=self._default_buckets)

    def start(self, background=True):
        self._load_root()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        if msg.get("cmd") == "infer_stream":
                            # chunked reply: the stream handler owns the
                            # socket until its final frame (or the
                            # connection dies — which cancels the
                            # stream so its slot frees within one step)
                            outer._handle_infer_stream(msg, self.request)
                            continue
                        try:
                            reply = outer._dispatch(msg)
                        except BaseException as e:
                            reply = _error_reply(e)
                        if reply is _CLOSE:
                            _send_msg(self.request, {"ok": True})
                            break
                        try:
                            _send_msg(self.request, reply)
                        except WireError as e:
                            # oversize outgoing frame: stream still in
                            # sync, surface the actionable message
                            _send_msg(self.request, {"error": str(e),
                                                     "code": "internal"})
                except WireError:
                    pass  # desynced incoming stream: drop the connection
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # socketserver's default listen backlog of 5 makes a client
            # burst stall on SYN retransmits (seconds each) before the
            # request even reaches admission control; admission belongs
            # to the batcher's queue, not the kernel's
            request_queue_size = 128

        self._server = Server(self._addr, Handler)
        self._addr = self._server.server_address
        if self.slo is not None:
            self.slo.name = self.endpoint
            self.slo.start()
            self._obs_registry.attach_slo(self.slo)
        if FLAGS.fleet_controller:
            from .fleet import FleetController
            self.fleet = FleetController.from_flags(
                self.registry, self.metrics, slo=self.slo,
                name=self.endpoint)
            self.fleet.start()
            self._obs_registry.attach_fleet(self.fleet)
        # flight-recorder provider: every post-mortem bundle carries
        # this server's stats + registry/lane liveness + SLO timeline
        # (no-op while FLAGS.flight_dir is unset)
        from ..obs import flightrec
        self._flight_provider = "serving_%s" % \
            self.endpoint.replace(":", "_").replace(".", "-")
        flightrec.add_provider(self._flight_provider,
                               self._flight_snapshot)
        if self._federation:
            self._fed_link = _FederationLink(
                self, self._federation, backend_id=self._backend_id,
                capacity_mb=self._capacity_mb)
            self._fed_link.start()
            if self.fleet is not None:
                # scale/page policy belongs to the global tier once a
                # frontend owns placement (fleet.py delegation) —
                # degrade-before-shed stays local
                self.fleet.delegated_to = self._federation
        if background:
            self._thread = threading.Thread(target=self._serve,
                                            daemon=True)
            self._thread.start()
        else:
            self._serve()
        return self

    @property
    def endpoint(self):
        return "%s:%d" % (self._addr[0], self._addr[1])

    def _serve(self):
        self._server.timeout = 0.2
        with self._server:
            while not self._stopped:
                self._server.handle_request()

    def shutdown(self, drain=True, timeout=30.0):
        """Graceful stop: refuse new work, drain every queued request,
        then stop accepting connections."""
        self._draining = True
        if self._fed_link is not None:
            # de-lease FIRST: the frontend must stop placing before the
            # registry starts retiring lanes
            self._fed_link.stop(deregister=True)
            self._fed_link = None
        if self.fleet is not None:
            # stop acting BEFORE the drain: the controller must not
            # resize/page models the shutdown is retiring
            self.fleet.stop()
            self._obs_registry.detach_fleet(self.fleet)
        self.registry.close_all(drain=drain, timeout=timeout)
        self._stopped = True
        if self.slo is not None:
            self.slo.stop()
            self._obs_registry.detach_slo(self.slo)
        if self._flight_provider is not None:
            from ..obs import flightrec
            flightrec.remove_provider(self._flight_provider)
            self._flight_provider = None
        self._obs_registry.detach_serving(self.metrics)
        try:
            s = socket.create_connection(self._addr, timeout=1)
            s.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------------

    def _health_snapshot(self):
        """The `health` verb payload: per-model SLO state + lane/thread
        liveness + last-decode-step age — the fleet controller's (and
        serving_top's) is-it-actually-serving readout, cheap enough to
        poll every second."""
        h = {"draining": bool(self._draining),
             # drain-vs-dead disambiguation (federation): accepting
             # False + an answering server = draining (streams still
             # finishing), no answer at all = dead — the frontend and
             # serving_top key on this instead of inferring from lease
             # age
             "accepting": not self._draining,
             "models": self.registry.health()}
        if self._federation:
            h["federation"] = {"frontend": self._federation,
                               "lease": (self._fed_link.lease
                                         if self._fed_link is not None
                                         else None)}
        if self.slo is not None:
            h["slo"] = self.slo.state()
            h["slo_monitor"] = {"running": self.slo.running,
                                "interval_s": self.slo.interval_s}
        if self.fleet is not None:
            # controller readout rides health too, so one poll (and
            # every flight bundle's server snapshot) carries it
            h["fleet"] = self.fleet.status()
        from ..obs import flightrec
        rec = flightrec.get_recorder()
        if rec is not None:
            h["flight"] = rec.stats()
        return h

    def _flight_snapshot(self):
        """Flight-recorder provider: what this server looked like at
        dump time (bundle file serving_<endpoint>.json)."""
        snap = {"endpoint": self.endpoint,
                "stats": self.metrics.snapshot(),
                "describe": self.registry.describe(),
                "health": self._health_snapshot()}
        if self.slo is not None:
            snap["slo_timeline"] = self.slo.timeline()
        return snap

    def _dispatch(self, msg):
        cmd = msg.get("cmd")
        if cmd == "infer":
            return self._handle_infer(msg)
        if cmd == "stats":
            return {"ok": True, "stats": self.metrics.snapshot(),
                    "models": self.registry.describe()}
        if cmd == "health":
            return {"ok": True, "health": self._health_snapshot()}
        if cmd == "fleet":
            # controller readout + policy/dry-run administration
            # (SERVING.md "Fleet controller"); reading works with the
            # controller disabled, administering it does not
            if msg.get("set_policy") or msg.get("dry_run") is not None:
                if self.fleet is None:
                    raise ValueError(
                        "fleet controller disabled — start the server "
                        "with FLAGS.fleet_controller=true")
                for model, spec in dict(
                        msg.get("set_policy") or {}).items():
                    self.fleet.set_policy(str(model), str(spec))
                if msg.get("dry_run") is not None:
                    self.fleet.dry_run = bool(msg["dry_run"])
            return {"ok": True,
                    "fleet": (self.fleet.status() if self.fleet
                              is not None else {"enabled": False})}
        if cmd == "flight":
            # manual post-mortem: dump a bundle NOW (cooldown bypassed
            # unless the caller asks otherwise); None = recorder
            # disabled (FLAGS.flight_dir unset) or dump failed
            from ..obs import flightrec
            path = flightrec.trigger(
                str(msg.get("reason") or "manual_rpc"),
                force=bool(msg.get("force", True)),
                endpoint=self.endpoint)
            return {"ok": True, "bundle": path,
                    "enabled": flightrec.get_recorder() is not None}
        if cmd == "metrics":
            # Prometheus-style text across training + serving — ONE
            # exposition (tools/metrics_dump.py renders it verbatim)
            return {"ok": True,
                    "text": self._obs_registry.prometheus_text()}
        if cmd == "trace":
            # span ring readout: a reply-visible trace_id resolves here
            # to its stage span tree (tools/trace_top.py)
            if msg.get("trace_id"):
                spans = obs_tracing.spans_for_trace(msg["trace_id"])
            else:
                spans = obs_tracing.recent_spans(
                    limit=int(msg.get("limit", 2048)),
                    kind=msg.get("kind") or None)
            return {"ok": True, "spans": spans,
                    "tracing": obs_tracing.stats()}
        if cmd == "load_model":
            if self._draining:
                raise BatcherClosed("server is draining")
            if msg.get("fleet_policy") and self.fleet is None:
                # typed rejection BEFORE any build work: a policy that
                # nothing will enforce is an operator error
                raise ValueError(
                    "load_model carried fleet_policy but the fleet "
                    "controller is disabled (FLAGS.fleet_controller)")
            entry = self.registry.load_model(
                msg["name"], msg["path"], version=msg.get("version"),
                buckets=msg.get("buckets") or self._default_buckets,
                replicas=msg.get("replicas"),
                devices=msg.get("devices"),
                decode_slots=msg.get("decode_slots"),
                decode_mode=msg.get("decode_mode"),
                precision=msg.get("precision"),
                ab_weight=msg.get("ab_weight"),
                draft=msg.get("draft"),
                spec_k=msg.get("spec_k"),
                kv_cache_dtype=msg.get("kv_cache_dtype"),
                fuse_steps=msg.get("fuse_steps"))
            if msg.get("fleet_policy"):
                self.fleet.set_policy(entry.name,
                                      str(msg["fleet_policy"]))
            reply = {"ok": True, "name": entry.name,
                     "version": entry.version,
                     "buckets": list(entry.predictor.batch_buckets()),
                     "replicas": len(entry.replicas),
                     "devices": entry.device_labels(),
                     # which numerics lane this version serves
                     # (QUANTIZE.md A/B axis)
                     "precision": entry.precision,
                     # what THIS load/flip cost against the persistent
                     # compile cache: a warm flip reads hits=N, misses=0
                     "compile_cache": dict(entry.compile_cache)}
            sizes = entry.mesh_sizes()
            if any(s > 1 for s in sizes):
                # the RESOLVED mesh shape (SERVING.md "Mesh replicas"):
                # members per replica lane, in route order — what a
                # 'mesh:2' spec actually packed on this host
                reply["mesh"] = sizes
            if entry.is_decode:
                reply["decode"] = True
                reply["decode_slots"] = entry.batcher.n_slots
                reply["max_seq_len"] = entry.predictor.max_seq_len
                reply["eos_id"] = entry.predictor.eos_id
                # the slot-table cache numerics this load serves
                # (QUANTIZE.md "Quantized KV cache")
                reply["kv_cache_dtype"] = str(getattr(
                    entry.predictor, "kv_cache_dtype", "float32"))
                # fused multi-step decode window this load dispatches
                # (SERVING.md "Fused multi-step decode"; 1 = classic)
                reply["fuse_steps"] = int(getattr(
                    entry.batcher, "fuse_steps", 1))
                if getattr(entry.batcher, "spec_k", 0):
                    # speculative lanes armed: depth + draft artifact
                    reply["spec_k"] = entry.batcher.spec_k
                    reply["draft"] = entry.draft_path
            return reply
        if cmd == "unload_model":
            self.registry.unload_model(msg["name"])
            return {"ok": True}
        if cmd == "drain":
            # federation drain (SERVING.md "Federated serving"): stop
            # ACCEPTING without stopping — in-flight requests and
            # decode streams run to completion, new admissions refuse
            # with "overloaded"; `resume` flips the server back into
            # the placement set (tests, rolling maintenance)
            self._draining = not msg.get("resume")
            if self._fed_link is not None:
                # push the accepting flip now, not at the next beat
                self._fed_link.beat_soon()
            return {"ok": True, "accepting": not self._draining,
                    "draining": bool(self._draining)}
        if cmd == "page_model":
            # cluster-wide paging actuator (federation/global_fleet):
            # unload to the artifact path, keep the load spec — the
            # model faults back in on demand or by global decision
            self.registry.page_out(msg["name"])
            return {"ok": True, "paged": msg["name"]}
        if cmd == "resize_model":
            # the global controller re-placing one model's replica
            # budget on THIS host (build-warm-flip, fit-gated)
            entry = self.registry.resize_model(
                msg["name"], int(msg["replicas"]),
                precision=msg.get("precision"))
            return {"ok": True, "name": msg["name"],
                    "replicas": len(entry.replicas)}
        if cmd == "fault_model":
            # explicit fault-in (the global controller placing a cold
            # model on THIS host): replays the persisted lane spec
            self.registry.fault_in(
                msg["name"], trigger=str(msg.get("trigger") or "rpc"))
            return {"ok": True, "name": msg["name"],
                    "fault_in": dict(self.registry.last_fault_in.get(
                        msg["name"]) or {})}
        if cmd == "shutdown":
            # drain BEFORE replying so the client's ok means "all prior
            # requests answered"; the accept loop stops right after
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {"ok": True, "draining": True}
        if cmd == "exit":
            self._stopped = True
            return _CLOSE
        return {"error": "unknown cmd %r" % cmd, "code": "bad_request"}

    def _handle_infer(self, msg):
        name = msg["model"]
        feeds = msg["feeds"]
        if not isinstance(feeds, dict) or not feeds:
            raise ValueError("infer needs a non-empty feeds dict")
        if self._draining:
            raise ServerOverloaded("server is draining — request refused")
        # trace id: carried in on the wire ("trace_id" field) or minted
        # at admission; echoed in the reply either way, so the caller
        # can resolve its latency into the span tree via the `trace`
        # verb / tools/trace_top.py (OBSERVABILITY.md)
        trace_id = str(msg.get("trace_id") or obs_tracing.new_trace_id())
        deadline_ms = msg.get("deadline_ms")
        deadline = None
        wait = 120.0  # never park a handler thread forever
        if deadline_ms is not None:
            deadline = time.monotonic() + float(deadline_ms) / 1000.0
            wait = float(deadline_ms) / 1000.0 + 5.0
        with obs_tracing.trace("serving/rpc", kind="serving",
                               trace_id=trace_id, model=name):
            future = self.registry.submit(
                name, feeds, version=msg.get("version"),
                deadline=deadline,
                priority=int(msg.get("priority", 0)),
                trace_id=trace_id,
                max_new_tokens=msg.get("max_new_tokens"),
                precision=msg.get("precision"))
            try:
                fetches = future.result(timeout=wait)
            except DeadlineExceeded:
                raise
            except TimeoutError:
                raise DeadlineExceeded(
                    "request did not complete within its %.0f ms "
                    "deadline"
                    % (deadline_ms if deadline_ms is not None
                       else wait * 1e3))
        reply = {"ok": True, "trace_id": trace_id,
                 "fetches": [np.ascontiguousarray(a) for a in fetches]}
        if getattr(future, "finish_reason", None):
            # decode model served through the one-shot verb: the whole
            # greedy stream comes back as fetches[0] plus why it ended
            reply["finish_reason"] = str(future.finish_reason)
        if msg.get("debug"):
            # opt-in latency attribution: the server-measured stage
            # timings ride back on the reply, so a client can see where
            # its time went without server access (queue_wait vs
            # compute vs batch_fill)
            reply["debug"] = dict(getattr(future, "obs_info", None)
                                  or {"trace_id": trace_id})
        return reply

    def _handle_infer_stream(self, msg, sock):
        """Chunked streaming generation (`infer_stream` verb): token
        deltas flush to the wire as the decode loop emits them —
        {"chunk": True, "seq": i, "tokens": [...], "trace_id"} frames,
        then exactly one terminal frame ({"ok": True, "done": True,
        "finish_reason", "new_tokens", ...} or {"error", "code",
        "done": True}).  Every frame carries the trace_id.  A dead
        client connection (send failure) CANCELS the stream, so its
        decode slot frees — and zeroes — within one step."""
        trace_id = str(msg.get("trace_id") or obs_tracing.new_trace_id())
        stream = None
        try:
            if self._draining:
                raise ServerOverloaded(
                    "server is draining — request refused")
            tokens = msg.get("tokens")
            if tokens is None:
                raise ValueError(
                    "infer_stream needs a 'tokens' prompt array")
            deadline_ms = msg.get("deadline_ms")
            deadline = None
            if deadline_ms is not None:
                deadline = time.monotonic() + float(deadline_ms) / 1000.0
            stream = self.registry.submit_stream(
                msg["model"], tokens, version=msg.get("version"),
                max_new_tokens=msg.get("max_new_tokens"),
                deadline=deadline,
                priority=int(msg.get("priority", 0)),
                trace_id=trace_id,
                chunk_tokens=msg.get("stream_chunk_tokens"))
        except BaseException as e:
            reply = _error_reply(e)
            reply["done"] = True
            reply["trace_id"] = trace_id
            _send_msg(sock, reply)
            return
        seq = 0
        try:
            for kind, payload in stream.events():
                if kind == "tokens":
                    _send_msg(sock, {"chunk": True, "seq": seq,
                                     "tokens": [int(t) for t in payload],
                                     "trace_id": trace_id})
                    seq += 1
                elif kind == "error":
                    reply = _error_reply(payload)
                    reply["done"] = True
                    reply["trace_id"] = trace_id
                    reply["new_tokens"] = len(stream.tokens)
                    _send_msg(sock, reply)
                else:  # done
                    final = {"ok": True, "done": True,
                             "trace_id": trace_id,
                             "finish_reason": str(payload),
                             "new_tokens": len(stream.tokens)}
                    if msg.get("debug"):
                        final["debug"] = dict(stream.obs_info
                                              or {"trace_id": trace_id})
                    _send_msg(sock, final)
        except (ConnectionError, EOFError, OSError, WireError):
            # client went away mid-stream: evict the request so its
            # slot is reclaimed for waiting traffic (chaos scenario
            # decode-disconnect pins the one-step bound)
            stream.cancel()
            raise


class _FederationLink:
    """Backend-side lease maintenance toward a federation frontend
    (paddle_tpu/federation): register at start, heartbeat every
    ``FLAGS.federation_heartbeat_ms`` carrying the serving payload
    (resident models + est_peak_mb + per-model queue/request counters,
    paged set, accepting flag), deregister on shutdown.  A heartbeat
    answered with code ``no_lease`` means the frontend already expired
    (or restarted past) this lease — the link re-registers on the next
    beat: the rejoin path, never silent serving on a dead lease."""

    def __init__(self, server, frontend, backend_id=None,
                 capacity_mb=None, heartbeat_s=None):
        self.server = server
        self.frontend = str(frontend)
        self.backend_id = backend_id
        self.capacity_mb = (float(FLAGS.federation_capacity_mb)
                            if capacity_mb is None
                            else float(capacity_mb))
        self.heartbeat_s = max(
            (float(FLAGS.federation_heartbeat_ms) / 1000.0
             if heartbeat_s is None else float(heartbeat_s)), 0.02)
        self.lease = None       # the granted {"backend_id","lease_id"}
        self._cli = ServingClient(self.frontend)
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread = None

    # -- payload -------------------------------------------------------

    def _payload(self):
        """(models, paged, load): the lease's serving payload — what
        the frontend places by and the global controller senses by."""
        desc = self.server.registry.describe()
        snap = self.server.metrics.snapshot()
        models, paged = {}, []
        for name, d in desc.items():
            if d.get("paged"):
                paged.append(name)
                continue
            models[name] = {"replicas": int(d.get("replicas") or 1),
                            "decode": bool(d.get("decode"))}
        queue_depth = requests = 0
        for key, m in (snap.get("models") or {}).items():
            qd = int(m.get("queue_depth") or 0)
            rq = int(m.get("requests") or 0)
            queue_depth += qd
            requests += rq
            plain = m.get("model", key)
            info = models.get(plain)
            if info is not None:
                info["queue_depth"] = info.get("queue_depth", 0) + qd
                info["requests"] = info.get("requests", 0) + rq
                if m.get("est_peak_mb") is not None:
                    info["est_peak_mb"] = float(m["est_peak_mb"])
        load = {"queue_depth": queue_depth, "requests": requests}
        return models, paged, load

    # -- the beat ------------------------------------------------------

    def _register(self, models, paged, load):
        host, port = self.server._addr
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"  # wildcard bind: advertise loopback
        reply = self._cli.call({
            "cmd": "register", "host": host, "port": int(port),
            "backend_id": self.backend_id,
            "capacity_mb": self.capacity_mb,
            "models": models, "paged": paged, "load": load})
        self.lease = {"backend_id": reply["backend_id"],
                      "lease_id": reply["lease_id"],
                      "ttl_s": reply.get("ttl_s")}
        self.backend_id = reply["backend_id"]

    def _beat(self):
        models, paged, load = self._payload()
        if self.lease is None:
            self._register(models, paged, load)
            return
        try:
            self._cli.call({
                "cmd": "heartbeat",
                "backend_id": self.lease["backend_id"],
                "lease_id": self.lease["lease_id"],
                "models": models, "paged": paged,
                "accepting": not self.server._draining,
                "load": load})
        except ServingError as e:
            if getattr(e, "code", None) == "no_lease":
                # expired under us (missed beats / frontend restart):
                # rejoin with a fresh lease right away
                self.lease = None
                self._register(models, paged, load)
            else:
                raise

    def beat_soon(self):
        """Wake the loop now (drain flips must not wait out a beat)."""
        self._kick.set()

    def _run(self):
        while not self._stop.is_set():
            self._kick.wait(self.heartbeat_s)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self._beat()
            except Exception:
                # frontend unreachable: drop the socket, retry next
                # beat — the lease expires frontend-side meanwhile,
                # which is exactly the contract
                self._cli.close()

    def start(self):
        try:
            self._beat()  # eager first register — placeable at return
        except Exception:
            self._cli.close()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle-tpu-fedlink-%s" % self.frontend)
        self._thread.start()
        return self

    def stop(self, deregister=False, timeout=2.0):
        self._stop.set()
        self._kick.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None
        if deregister and self.lease is not None:
            try:
                self._cli.call({"cmd": "deregister",
                                "backend_id": self.lease["backend_id"],
                                "lease_id": self.lease["lease_id"]})
            except Exception:
                pass  # frontend gone: the TTL cleans up
        self.lease = None
        self._cli.close()


class ServingClient:
    """Wire client for InferenceServer.  Connections are thread-local
    (same rationale as RPCClient: a blocking round-trip per call, one
    socket per (thread, endpoint)).

    `infer` semantics: with a deadline, shed ("overloaded") replies and
    connection failures are retried under the shared jittered-backoff
    RetryPolicy until the deadline; without one, a shed surfaces
    immediately as ServerOverloaded so the caller owns the policy."""

    def __init__(self, endpoint, deadline_ms=None, retry_policy=None):
        self.endpoint = endpoint
        self.deadline_ms = deadline_ms
        self.last_trace_id = None
        self.last_stream_info = None  # final infer_stream frame metadata
        self._policy = retry_policy
        self._tls = threading.local()

    def _conn(self):
        s = getattr(self._tls, "sock", None)
        if s is None:
            host, port = self.endpoint.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=FLAGS.rpc_deadline)
            self._tls.sock = s
        return s

    def _drop_conn(self):
        s = getattr(self._tls, "sock", None)
        self._tls.sock = None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _call_once(self, msg):
        s = self._conn()
        try:
            _send_msg(s, msg)
            reply = _recv_msg(s)
        except (ConnectionError, EOFError, OSError, WireError):
            self._drop_conn()
            raise
        if "error" in reply:
            code = reply.get("code")
            if code == "overloaded":
                raise ServerOverloaded(reply["error"],
                                       priority=reply.get("shed_priority"))
            if code == "deadline":
                raise DeadlineExceeded(reply["error"])
            err = ServingError("%s (code=%s)" % (reply["error"], code))
            err.code = code  # typed dispatch (federation no_lease etc.)
            raise err
        return reply

    def call(self, msg):
        """One-shot forward of a raw verb dict — NO retry policy: the
        federation frontend's forwarding primitive (spillover policy
        owns the retries, the transport must not)."""
        return self._call_once(dict(msg))

    def _call(self, msg, retry_deadline=None, retry_on=()):
        if retry_deadline is None:
            return self._call_once(msg)
        from ..utils.retry import default_rpc_policy
        policy = self._policy or default_rpc_policy(
            max_attempts=1 << 20, max_delay=0.5)
        return policy.call(
            lambda: self._call_once(msg),
            retry_on=(ConnectionError, OSError, EOFError) + tuple(retry_on),
            on_retry=lambda e, attempt: self._drop_conn()
            if isinstance(e, (ConnectionError, OSError, EOFError))
            else None,
            deadline=retry_deadline)

    def infer_stream(self, model, tokens, max_new_tokens=None,
                     deadline_ms=None, version=None, priority=None,
                     trace_id=None, chunk_tokens=None, debug=False):
        """Streaming generation: returns an iterator yielding token-
        delta lists as the server decodes them (the `infer_stream`
        verb's chunk frames).  The final frame's metadata lands on
        ``self.last_stream_info`` (finish_reason, new_tokens, trace_id,
        + server stage timings with ``debug=True``) when the iterator
        completes.  A mid-stream error surfaces as the typed exception
        (ServerOverloaded / DeadlineExceeded / ServingError) at the
        point of failure — tokens already yielded are real.  Closing
        the iterator early drops the connection, which tells the server
        to evict the request from its decode slot.

        The streaming reply uses a dedicated connection (frames would
        desync the request/reply socket), torn down when the stream
        ends or the iterator is closed."""
        msg = {"cmd": "infer_stream", "model": model,
               "tokens": np.ascontiguousarray(
                   np.asarray(tokens, np.int32))}
        if max_new_tokens is not None:
            msg["max_new_tokens"] = int(max_new_tokens)
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        if version is not None:
            msg["version"] = version
        if priority is not None:
            msg["priority"] = int(priority)
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        if chunk_tokens is not None:
            msg["stream_chunk_tokens"] = int(chunk_tokens)
        if debug:
            msg["debug"] = True
        self.last_stream_info = None

        def _gen():
            host, port = self.endpoint.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=FLAGS.rpc_deadline)
            finished = False
            received = 0  # tokens already yielded — committed output
            try:
                try:
                    _send_msg(s, msg)
                except (ConnectionError, EOFError, OSError,
                        WireError) as e:
                    raise StreamBroken(
                        "stream to %s broke before placement: %s"
                        % (self.endpoint, e),
                        trace_id=msg.get("trace_id"), received=0)
                while True:
                    try:
                        reply = _recv_msg(s)
                    except (ConnectionError, EOFError, OSError,
                            WireError) as e:
                        # the connection died MID-STREAM.  This must
                        # never look like a retryable transport error:
                        # a reconnect would restart the stream from
                        # token 0 and duplicate the `received` tokens
                        # already committed.  Typed StreamBroken makes
                        # generic (ConnectionError, OSError) retry
                        # loops pass it through; re-placement is the
                        # federation frontend's affinity re-pin.
                        finished = True
                        self.last_stream_info = {
                            "code": "stream_broken",
                            "new_tokens": received,
                            "trace_id": msg.get("trace_id")}
                        raise StreamBroken(
                            "stream to %s broke after %d token(s): %s"
                            % (self.endpoint, received, e),
                            trace_id=msg.get("trace_id"),
                            received=received)
                    if "error" in reply:
                        finished = True
                        self.last_stream_info = {
                            k: reply[k] for k in
                            ("trace_id", "new_tokens", "code",
                             "backend")
                            if k in reply}
                        self.last_trace_id = reply.get("trace_id")
                        code = reply.get("code")
                        if code == "overloaded":
                            raise ServerOverloaded(
                                reply["error"],
                                priority=reply.get("shed_priority"))
                        if code == "deadline":
                            raise DeadlineExceeded(reply["error"])
                        if code == "stream_broken":
                            # frontend-relayed backend death: same
                            # typed surface as a direct break
                            raise StreamBroken(
                                reply["error"],
                                trace_id=reply.get("trace_id"),
                                received=received,
                                backend=reply.get("backend"))
                        raise ServingError("%s (code=%s)"
                                           % (reply["error"], code))
                    if reply.get("chunk"):
                        toks = [int(t) for t in reply["tokens"]]
                        received += len(toks)
                        yield toks
                        continue
                    finished = True
                    self.last_stream_info = {
                        k: v for k, v in reply.items() if k != "ok"}
                    self.last_trace_id = reply.get("trace_id")
                    return
            finally:
                # early close (or any exit): this connection never
                # carries another request — a dropped socket is also
                # the eviction signal for an abandoned stream
                try:
                    s.close()
                except OSError:
                    pass
                if not finished:
                    pass  # server notices the dead socket on next flush

        return _gen()

    def infer(self, model, feeds, deadline_ms=None, version=None,
              retry_sheds=None, priority=None, debug=False,
              trace_id=None, max_new_tokens=None, precision=None):
        """Run one request.  Returns the fetch list; with
        ``debug=True`` returns ``(fetches, info)`` where ``info`` is
        the server-measured latency attribution (trace_id,
        queue_wait_ms, compute_ms, batch_fill, replica ...) — the
        client-side half of OBSERVABILITY.md's latency story.
        ``trace_id`` pins a caller-minted id (propagated end to end and
        echoed back); the reply's id is also kept on
        ``self.last_trace_id`` for the plain return shape."""
        deadline_ms = self.deadline_ms if deadline_ms is None \
            else deadline_ms
        msg = {"cmd": "infer", "model": model,
               "feeds": {k: np.ascontiguousarray(np.asarray(v))
                         for k, v in feeds.items()}}
        if version is not None:
            msg["version"] = version
        if precision is not None:
            # pin the request to one numerics lane ('fp32' / 'int8');
            # without it the server's A/B weights route (QUANTIZE.md)
            msg["precision"] = str(precision)
        if max_new_tokens is not None:
            # decode models through the one-shot verb: the whole greedy
            # stream returns as fetches[0]
            msg["max_new_tokens"] = int(max_new_tokens)
        if priority is not None:
            # forwarded to admission control: larger = more important;
            # under overload the server sheds lowest-priority-first
            msg["priority"] = int(priority)
        if debug:
            msg["debug"] = True
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        retry_deadline = None
        retry_on = ()
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
            retry_deadline = time.monotonic() + float(deadline_ms) / 1000.0
            if retry_sheds is None or retry_sheds:
                retry_on = (ServerOverloaded,)
        elif retry_sheds:
            raise ValueError("retry_sheds needs a deadline_ms to bound it")
        reply = self._call(msg, retry_deadline=retry_deadline,
                           retry_on=retry_on)
        self.last_trace_id = reply.get("trace_id")
        fetches = list(reply["fetches"])
        if debug:
            return fetches, dict(reply.get("debug") or {})
        return fetches

    def load_model(self, name, path, version=None, buckets=None,
                   replicas=None, devices=None, decode_slots=None,
                   decode_mode=None, precision=None, ab_weight=None,
                   draft=None, spec_k=None, kv_cache_dtype=None,
                   fuse_steps=None, fleet_policy=None):
        msg = {"cmd": "load_model", "name": name, "path": path}
        if fleet_policy is not None:
            # per-model fleet policy body riding the load (SERVING.md
            # "Fleet controller"), e.g. 'max_replicas=4,page_ttl_s=600'
            msg["fleet_policy"] = str(fleet_policy)
        if kv_cache_dtype is not None:
            # decode artifacts: slot-table cache numerics for this
            # load — 'fp32'/'float32' or 'int8' (QUANTIZE.md)
            msg["kv_cache_dtype"] = str(kv_cache_dtype)
        if draft is not None:
            # speculative decoding: draft artifact path (SERVING.md);
            # the server pairs one draft replica per target replica
            msg["draft"] = str(draft)
        if spec_k is not None:
            msg["spec_k"] = int(spec_k)
        if fuse_steps is not None:
            # fused multi-step decode window per dispatch (SERVING.md
            # "Fused multi-step decode"; 1 keeps the classic loop)
            msg["fuse_steps"] = int(fuse_steps)
        if version is not None:
            msg["version"] = version
        if precision is not None:
            # lane override; normally auto-detected from the artifact
            msg["precision"] = str(precision)
        if ab_weight is not None:
            # this lane's share of default-routed traffic (A/B canary)
            msg["ab_weight"] = float(ab_weight)
        if buckets is not None:
            msg["buckets"] = [int(b) for b in buckets]
        if replicas is not None:
            # placement spec: int N, 'auto', or 'cpu:0,cpu:1' string
            msg["replicas"] = replicas if isinstance(replicas, str) \
                else int(replicas)
        if devices is not None:
            msg["devices"] = [str(d) for d in devices]
        if decode_slots is not None:
            msg["decode_slots"] = int(decode_slots)
        if decode_mode is not None:
            # "static" = the static-batch baseline (bench lanes only)
            msg["decode_mode"] = str(decode_mode)
        return self._call(msg)

    def unload_model(self, name):
        return self._call({"cmd": "unload_model", "name": name})

    def drain(self, resume=False):
        """Flip the server out of (or with ``resume=True`` back into)
        the accepting state: in-flight work finishes, new admissions
        refuse — the federation drain verb (SERVING.md)."""
        return self._call({"cmd": "drain", "resume": bool(resume)})

    def page_model(self, name):
        """Page one model out to its artifact path (load spec kept —
        it faults back in on demand)."""
        return self._call({"cmd": "page_model", "name": name})

    def fault_model(self, name, trigger="rpc"):
        """Fault one paged model back in on this server (the global
        controller's cross-host placement actuator)."""
        return self._call({"cmd": "fault_model", "name": name,
                           "trigger": str(trigger)})

    def stats(self):
        return self._call({"cmd": "stats"})

    def health(self):
        """Per-model SLO state + lane liveness (the `health` verb's
        payload): {"draining", "models": {...}, "slo": {...},
        "flight": {...}} — see SERVING.md."""
        return self._call({"cmd": "health"})["health"]

    def fleet(self, set_policy=None, dry_run=None):
        """Fleet-controller readout/administration (the `fleet` verb):
        returns the controller status dict ({"enabled": False} when
        the server runs without one).  `set_policy` maps model name ->
        policy body ('min_replicas=1,max_replicas=4,page_ttl_s=600');
        `dry_run` flips rehearsal mode.  Both require the controller
        to be enabled server-side."""
        msg = {"cmd": "fleet"}
        if set_policy:
            msg["set_policy"] = {str(k): str(v)
                                 for k, v in dict(set_policy).items()}
        if dry_run is not None:
            msg["dry_run"] = bool(dry_run)
        return self._call(msg)["fleet"]

    def set_fleet_policy(self, model, spec):
        """Declare one model's fleet policy body on the server."""
        return self.fleet(set_policy={model: spec})

    def flight(self, reason="manual_rpc", force=True):
        """Trigger a flight-recorder bundle on the server; returns the
        committed bundle path, or None while the recorder is disabled
        (server-side FLAGS.flight_dir unset)."""
        return self._call({"cmd": "flight", "reason": str(reason),
                           "force": bool(force)}).get("bundle")

    def metrics_text(self):
        """The server's unified Prometheus-style exposition."""
        return self._call({"cmd": "metrics"})["text"]

    def trace(self, trace_id=None, limit=2048, kind=None):
        """Span-ring readout: all spans of one trace_id, or the most
        recent `limit` (optionally filtered by kind)."""
        msg = {"cmd": "trace", "limit": int(limit)}
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        if kind is not None:
            msg["kind"] = str(kind)
        return self._call(msg)

    def shutdown_server(self, drain=True):
        try:
            return self._call({"cmd": "shutdown", "drain": bool(drain)})
        except (ConnectionError, OSError, EOFError):
            return None

    def close(self):
        self._drop_conn()
