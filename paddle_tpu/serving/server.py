"""Threaded inference server + client over the native wire protocol.

The serving front: the same length-prefixed typed-wire framing as the
parameter-server transport (distributed/rpc.py over native/wire.py — no
pickle ever touches a socket), carrying four commands:

  infer         {"cmd","model","feeds"{name->ndarray},"deadline_ms"?,
                 "version"?,"priority"?} -> {"ok","fetches"[ndarray...]}
                 or {"error","code"} with code in {"overloaded",
                 "deadline","no_model","bad_request","internal"};
                 an "overloaded" reply carries "shed_priority" — the
                 class the lowest-priority-first policy dropped
  load_model    {"cmd","name","path","version"?,"replicas"?,"devices"?}
                 — hot swap; replicas/devices are the device placement
                 spec (N, 'auto', or explicit device names)
  unload_model  {"cmd","name"} — drain then remove
  stats         {"cmd"} -> the ServingMetrics snapshot (now with
                 per-replica lane stats per model)
  health        {"cmd"} -> per-model SLO state (ok/degraded/breach,
                 burn rates) + lane/thread liveness + last-decode-step
                 age (OBSERVABILITY.md "SLOs & burn rates")
  flight        {"cmd","reason"?,"force"?} -> trigger a flight-recorder
                 post-mortem bundle; reply carries the committed path
  fleet         {"cmd","set_policy"?,"dry_run"?} -> fleet-controller
                 status (per-model state/replicas/paged, recent
                 actions, policies); set_policy maps model -> policy
                 body, dry_run flips rehearsal mode (SERVING.md
                 "Fleet controller")
  shutdown      graceful drain, then the server stops accepting

Admission control is the batcher's bounded queue: a request past
`FLAGS.serving_max_queue` is answered immediately with an "overloaded"
error (shed-not-hang).  Per-request deadlines bound BOTH queue wait and
the reply wait server-side; the client's `infer` reuses the shared
jittered-backoff RetryPolicy (utils/retry.py) to re-offer shed requests
until its deadline — jitter matters for the same reason it does on the
pserver plane: synchronized retries stampede a recovering server.

Graceful drain on shutdown: stop admitting, finish every queued
request, answer it, then exit — chaos-tested (tools/chaos.py FlakyProxy
+ slow-worker injection) in tests/test_serving.py.
"""

import os
import socket
import socketserver
import threading
import time

import numpy as np

from ..distributed.rpc import _recv_msg, _send_msg
from ..flags import FLAGS
from ..native.wire import WireError
from ..obs import tracing as obs_tracing
from .batcher import BatcherClosed, DeadlineExceeded, ServerOverloaded
from .metrics import ServingMetrics
from .model_registry import ModelRegistry

__all__ = ["InferenceServer", "ServingClient", "ServingError"]

_CLOSE = object()


class ServingError(RuntimeError):
    """Server-side failure reported over the wire (non-typed codes)."""


def _error_reply(exc):
    if isinstance(exc, ServerOverloaded):
        reply = {"error": str(exc), "code": "overloaded"}
        if getattr(exc, "priority", None) is not None:
            # which priority class was shed (the arrival, or the queued
            # request it evicted) — the client re-raises with it
            reply["shed_priority"] = int(exc.priority)
        return reply
    if isinstance(exc, (DeadlineExceeded, TimeoutError)):
        return {"error": str(exc), "code": "deadline"}
    if isinstance(exc, KeyError):
        return {"error": str(exc.args[0]) if exc.args else str(exc),
                "code": "no_model"}
    if isinstance(exc, (ValueError, TypeError, BatcherClosed)):
        return {"error": str(exc), "code": "bad_request"}
    return {"error": "%s: %s" % (type(exc).__name__, exc),
            "code": "internal"}


class InferenceServer:
    """One serving endpoint over a ModelRegistry.

    `model_root`: optional directory whose immediate subdirectories are
    loaded at start as models (subdir name == model name) — the
    "directory of artifacts -> multi-tenant service" contract."""

    def __init__(self, endpoint="127.0.0.1:0", model_root=None,
                 max_queue=None, deadline_ms=None, workers=None,
                 buckets=None, replicas=None):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.metrics = ServingMetrics()
        # the unified telemetry surface (OBSERVABILITY.md): this
        # server's counters join the process-wide MetricsRegistry the
        # `metrics` RPC verb and tools/metrics_dump.py render
        from ..obs import registry as obs_registry
        self._obs_registry = obs_registry.default()
        self._obs_registry.attach_serving(self.metrics)
        # the judgment layer (OBSERVABILITY.md "SLOs & burn rates"):
        # a background monitor samples this server's counters into a
        # bounded time-series ring and evaluates declared SLOs
        # (FLAGS.serving_slo / slo.declare) into the ok/degraded/
        # breach state machine the `health` verb renders; breaches arm
        # the flight recorder.  FLAGS.slo_monitor=false opts out.
        self.slo = None
        if FLAGS.slo_monitor:
            from ..obs import slo as obs_slo
            self.slo = obs_slo.SLOMonitor.from_flags(self.metrics)
        # the control plane above the judgment layer (SERVING.md
        # "Fleet controller"): acts on the SLO/queue/occupancy/shed
        # signals through the registry's actuators — replica-set
        # scaling, cold-model paging, pressure degradation.
        # FLAGS.fleet_controller=false (default) keeps it off.
        self.fleet = None
        self._flight_provider = None
        # `replicas`: default placement spec for every model this server
        # loads (int N / 'auto' / explicit device list — SERVING.md
        # multi-chip serving); a load_model RPC can override per model
        self.registry = ModelRegistry(
            metrics=self.metrics, max_queue=max_queue,
            deadline_ms=deadline_ms, workers=workers, replicas=replicas)
        self._default_buckets = buckets
        self._model_root = model_root
        self._stopped = False
        self._draining = False
        self._server = None
        self._thread = None

    # ------------------------------------------------------------------

    def _load_root(self):
        root = self._model_root
        if not root:
            return
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if os.path.isdir(path):
                self.registry.load_model(name, path,
                                         buckets=self._default_buckets)

    def start(self, background=True):
        self._load_root()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        if msg.get("cmd") == "infer_stream":
                            # chunked reply: the stream handler owns the
                            # socket until its final frame (or the
                            # connection dies — which cancels the
                            # stream so its slot frees within one step)
                            outer._handle_infer_stream(msg, self.request)
                            continue
                        try:
                            reply = outer._dispatch(msg)
                        except BaseException as e:
                            reply = _error_reply(e)
                        if reply is _CLOSE:
                            _send_msg(self.request, {"ok": True})
                            break
                        try:
                            _send_msg(self.request, reply)
                        except WireError as e:
                            # oversize outgoing frame: stream still in
                            # sync, surface the actionable message
                            _send_msg(self.request, {"error": str(e),
                                                     "code": "internal"})
                except WireError:
                    pass  # desynced incoming stream: drop the connection
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            # socketserver's default listen backlog of 5 makes a client
            # burst stall on SYN retransmits (seconds each) before the
            # request even reaches admission control; admission belongs
            # to the batcher's queue, not the kernel's
            request_queue_size = 128

        self._server = Server(self._addr, Handler)
        self._addr = self._server.server_address
        if self.slo is not None:
            self.slo.name = self.endpoint
            self.slo.start()
            self._obs_registry.attach_slo(self.slo)
        if FLAGS.fleet_controller:
            from .fleet import FleetController
            self.fleet = FleetController.from_flags(
                self.registry, self.metrics, slo=self.slo,
                name=self.endpoint)
            self.fleet.start()
            self._obs_registry.attach_fleet(self.fleet)
        # flight-recorder provider: every post-mortem bundle carries
        # this server's stats + registry/lane liveness + SLO timeline
        # (no-op while FLAGS.flight_dir is unset)
        from ..obs import flightrec
        self._flight_provider = "serving_%s" % \
            self.endpoint.replace(":", "_").replace(".", "-")
        flightrec.add_provider(self._flight_provider,
                               self._flight_snapshot)
        if background:
            self._thread = threading.Thread(target=self._serve,
                                            daemon=True)
            self._thread.start()
        else:
            self._serve()
        return self

    @property
    def endpoint(self):
        return "%s:%d" % (self._addr[0], self._addr[1])

    def _serve(self):
        self._server.timeout = 0.2
        with self._server:
            while not self._stopped:
                self._server.handle_request()

    def shutdown(self, drain=True, timeout=30.0):
        """Graceful stop: refuse new work, drain every queued request,
        then stop accepting connections."""
        self._draining = True
        if self.fleet is not None:
            # stop acting BEFORE the drain: the controller must not
            # resize/page models the shutdown is retiring
            self.fleet.stop()
            self._obs_registry.detach_fleet(self.fleet)
        self.registry.close_all(drain=drain, timeout=timeout)
        self._stopped = True
        if self.slo is not None:
            self.slo.stop()
            self._obs_registry.detach_slo(self.slo)
        if self._flight_provider is not None:
            from ..obs import flightrec
            flightrec.remove_provider(self._flight_provider)
            self._flight_provider = None
        self._obs_registry.detach_serving(self.metrics)
        try:
            s = socket.create_connection(self._addr, timeout=1)
            s.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ------------------------------------------------------------------

    def _health_snapshot(self):
        """The `health` verb payload: per-model SLO state + lane/thread
        liveness + last-decode-step age — the fleet controller's (and
        serving_top's) is-it-actually-serving readout, cheap enough to
        poll every second."""
        h = {"draining": bool(self._draining),
             "models": self.registry.health()}
        if self.slo is not None:
            h["slo"] = self.slo.state()
            h["slo_monitor"] = {"running": self.slo.running,
                                "interval_s": self.slo.interval_s}
        if self.fleet is not None:
            # controller readout rides health too, so one poll (and
            # every flight bundle's server snapshot) carries it
            h["fleet"] = self.fleet.status()
        from ..obs import flightrec
        rec = flightrec.get_recorder()
        if rec is not None:
            h["flight"] = rec.stats()
        return h

    def _flight_snapshot(self):
        """Flight-recorder provider: what this server looked like at
        dump time (bundle file serving_<endpoint>.json)."""
        snap = {"endpoint": self.endpoint,
                "stats": self.metrics.snapshot(),
                "describe": self.registry.describe(),
                "health": self._health_snapshot()}
        if self.slo is not None:
            snap["slo_timeline"] = self.slo.timeline()
        return snap

    def _dispatch(self, msg):
        cmd = msg.get("cmd")
        if cmd == "infer":
            return self._handle_infer(msg)
        if cmd == "stats":
            return {"ok": True, "stats": self.metrics.snapshot(),
                    "models": self.registry.describe()}
        if cmd == "health":
            return {"ok": True, "health": self._health_snapshot()}
        if cmd == "fleet":
            # controller readout + policy/dry-run administration
            # (SERVING.md "Fleet controller"); reading works with the
            # controller disabled, administering it does not
            if msg.get("set_policy") or msg.get("dry_run") is not None:
                if self.fleet is None:
                    raise ValueError(
                        "fleet controller disabled — start the server "
                        "with FLAGS.fleet_controller=true")
                for model, spec in dict(
                        msg.get("set_policy") or {}).items():
                    self.fleet.set_policy(str(model), str(spec))
                if msg.get("dry_run") is not None:
                    self.fleet.dry_run = bool(msg["dry_run"])
            return {"ok": True,
                    "fleet": (self.fleet.status() if self.fleet
                              is not None else {"enabled": False})}
        if cmd == "flight":
            # manual post-mortem: dump a bundle NOW (cooldown bypassed
            # unless the caller asks otherwise); None = recorder
            # disabled (FLAGS.flight_dir unset) or dump failed
            from ..obs import flightrec
            path = flightrec.trigger(
                str(msg.get("reason") or "manual_rpc"),
                force=bool(msg.get("force", True)),
                endpoint=self.endpoint)
            return {"ok": True, "bundle": path,
                    "enabled": flightrec.get_recorder() is not None}
        if cmd == "metrics":
            # Prometheus-style text across training + serving — ONE
            # exposition (tools/metrics_dump.py renders it verbatim)
            return {"ok": True,
                    "text": self._obs_registry.prometheus_text()}
        if cmd == "trace":
            # span ring readout: a reply-visible trace_id resolves here
            # to its stage span tree (tools/trace_top.py)
            if msg.get("trace_id"):
                spans = obs_tracing.spans_for_trace(msg["trace_id"])
            else:
                spans = obs_tracing.recent_spans(
                    limit=int(msg.get("limit", 2048)),
                    kind=msg.get("kind") or None)
            return {"ok": True, "spans": spans,
                    "tracing": obs_tracing.stats()}
        if cmd == "load_model":
            if self._draining:
                raise BatcherClosed("server is draining")
            if msg.get("fleet_policy") and self.fleet is None:
                # typed rejection BEFORE any build work: a policy that
                # nothing will enforce is an operator error
                raise ValueError(
                    "load_model carried fleet_policy but the fleet "
                    "controller is disabled (FLAGS.fleet_controller)")
            entry = self.registry.load_model(
                msg["name"], msg["path"], version=msg.get("version"),
                buckets=msg.get("buckets") or self._default_buckets,
                replicas=msg.get("replicas"),
                devices=msg.get("devices"),
                decode_slots=msg.get("decode_slots"),
                decode_mode=msg.get("decode_mode"),
                precision=msg.get("precision"),
                ab_weight=msg.get("ab_weight"),
                draft=msg.get("draft"),
                spec_k=msg.get("spec_k"),
                kv_cache_dtype=msg.get("kv_cache_dtype"),
                fuse_steps=msg.get("fuse_steps"))
            if msg.get("fleet_policy"):
                self.fleet.set_policy(entry.name,
                                      str(msg["fleet_policy"]))
            reply = {"ok": True, "name": entry.name,
                     "version": entry.version,
                     "buckets": list(entry.predictor.batch_buckets()),
                     "replicas": len(entry.replicas),
                     "devices": entry.device_labels(),
                     # which numerics lane this version serves
                     # (QUANTIZE.md A/B axis)
                     "precision": entry.precision,
                     # what THIS load/flip cost against the persistent
                     # compile cache: a warm flip reads hits=N, misses=0
                     "compile_cache": dict(entry.compile_cache)}
            if entry.is_decode:
                reply["decode"] = True
                reply["decode_slots"] = entry.batcher.n_slots
                reply["max_seq_len"] = entry.predictor.max_seq_len
                reply["eos_id"] = entry.predictor.eos_id
                # the slot-table cache numerics this load serves
                # (QUANTIZE.md "Quantized KV cache")
                reply["kv_cache_dtype"] = str(getattr(
                    entry.predictor, "kv_cache_dtype", "float32"))
                # fused multi-step decode window this load dispatches
                # (SERVING.md "Fused multi-step decode"; 1 = classic)
                reply["fuse_steps"] = int(getattr(
                    entry.batcher, "fuse_steps", 1))
                if getattr(entry.batcher, "spec_k", 0):
                    # speculative lanes armed: depth + draft artifact
                    reply["spec_k"] = entry.batcher.spec_k
                    reply["draft"] = entry.draft_path
            return reply
        if cmd == "unload_model":
            self.registry.unload_model(msg["name"])
            return {"ok": True}
        if cmd == "shutdown":
            # drain BEFORE replying so the client's ok means "all prior
            # requests answered"; the accept loop stops right after
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {"ok": True, "draining": True}
        if cmd == "exit":
            self._stopped = True
            return _CLOSE
        return {"error": "unknown cmd %r" % cmd, "code": "bad_request"}

    def _handle_infer(self, msg):
        name = msg["model"]
        feeds = msg["feeds"]
        if not isinstance(feeds, dict) or not feeds:
            raise ValueError("infer needs a non-empty feeds dict")
        if self._draining:
            raise ServerOverloaded("server is draining — request refused")
        # trace id: carried in on the wire ("trace_id" field) or minted
        # at admission; echoed in the reply either way, so the caller
        # can resolve its latency into the span tree via the `trace`
        # verb / tools/trace_top.py (OBSERVABILITY.md)
        trace_id = str(msg.get("trace_id") or obs_tracing.new_trace_id())
        deadline_ms = msg.get("deadline_ms")
        deadline = None
        wait = 120.0  # never park a handler thread forever
        if deadline_ms is not None:
            deadline = time.monotonic() + float(deadline_ms) / 1000.0
            wait = float(deadline_ms) / 1000.0 + 5.0
        with obs_tracing.trace("serving/rpc", kind="serving",
                               trace_id=trace_id, model=name):
            future = self.registry.submit(
                name, feeds, version=msg.get("version"),
                deadline=deadline,
                priority=int(msg.get("priority", 0)),
                trace_id=trace_id,
                max_new_tokens=msg.get("max_new_tokens"),
                precision=msg.get("precision"))
            try:
                fetches = future.result(timeout=wait)
            except DeadlineExceeded:
                raise
            except TimeoutError:
                raise DeadlineExceeded(
                    "request did not complete within its %.0f ms "
                    "deadline"
                    % (deadline_ms if deadline_ms is not None
                       else wait * 1e3))
        reply = {"ok": True, "trace_id": trace_id,
                 "fetches": [np.ascontiguousarray(a) for a in fetches]}
        if getattr(future, "finish_reason", None):
            # decode model served through the one-shot verb: the whole
            # greedy stream comes back as fetches[0] plus why it ended
            reply["finish_reason"] = str(future.finish_reason)
        if msg.get("debug"):
            # opt-in latency attribution: the server-measured stage
            # timings ride back on the reply, so a client can see where
            # its time went without server access (queue_wait vs
            # compute vs batch_fill)
            reply["debug"] = dict(getattr(future, "obs_info", None)
                                  or {"trace_id": trace_id})
        return reply

    def _handle_infer_stream(self, msg, sock):
        """Chunked streaming generation (`infer_stream` verb): token
        deltas flush to the wire as the decode loop emits them —
        {"chunk": True, "seq": i, "tokens": [...], "trace_id"} frames,
        then exactly one terminal frame ({"ok": True, "done": True,
        "finish_reason", "new_tokens", ...} or {"error", "code",
        "done": True}).  Every frame carries the trace_id.  A dead
        client connection (send failure) CANCELS the stream, so its
        decode slot frees — and zeroes — within one step."""
        trace_id = str(msg.get("trace_id") or obs_tracing.new_trace_id())
        stream = None
        try:
            if self._draining:
                raise ServerOverloaded(
                    "server is draining — request refused")
            tokens = msg.get("tokens")
            if tokens is None:
                raise ValueError(
                    "infer_stream needs a 'tokens' prompt array")
            deadline_ms = msg.get("deadline_ms")
            deadline = None
            if deadline_ms is not None:
                deadline = time.monotonic() + float(deadline_ms) / 1000.0
            stream = self.registry.submit_stream(
                msg["model"], tokens, version=msg.get("version"),
                max_new_tokens=msg.get("max_new_tokens"),
                deadline=deadline,
                priority=int(msg.get("priority", 0)),
                trace_id=trace_id,
                chunk_tokens=msg.get("stream_chunk_tokens"))
        except BaseException as e:
            reply = _error_reply(e)
            reply["done"] = True
            reply["trace_id"] = trace_id
            _send_msg(sock, reply)
            return
        seq = 0
        try:
            for kind, payload in stream.events():
                if kind == "tokens":
                    _send_msg(sock, {"chunk": True, "seq": seq,
                                     "tokens": [int(t) for t in payload],
                                     "trace_id": trace_id})
                    seq += 1
                elif kind == "error":
                    reply = _error_reply(payload)
                    reply["done"] = True
                    reply["trace_id"] = trace_id
                    reply["new_tokens"] = len(stream.tokens)
                    _send_msg(sock, reply)
                else:  # done
                    final = {"ok": True, "done": True,
                             "trace_id": trace_id,
                             "finish_reason": str(payload),
                             "new_tokens": len(stream.tokens)}
                    if msg.get("debug"):
                        final["debug"] = dict(stream.obs_info
                                              or {"trace_id": trace_id})
                    _send_msg(sock, final)
        except (ConnectionError, EOFError, OSError, WireError):
            # client went away mid-stream: evict the request so its
            # slot is reclaimed for waiting traffic (chaos scenario
            # decode-disconnect pins the one-step bound)
            stream.cancel()
            raise


class ServingClient:
    """Wire client for InferenceServer.  Connections are thread-local
    (same rationale as RPCClient: a blocking round-trip per call, one
    socket per (thread, endpoint)).

    `infer` semantics: with a deadline, shed ("overloaded") replies and
    connection failures are retried under the shared jittered-backoff
    RetryPolicy until the deadline; without one, a shed surfaces
    immediately as ServerOverloaded so the caller owns the policy."""

    def __init__(self, endpoint, deadline_ms=None, retry_policy=None):
        self.endpoint = endpoint
        self.deadline_ms = deadline_ms
        self.last_trace_id = None
        self.last_stream_info = None  # final infer_stream frame metadata
        self._policy = retry_policy
        self._tls = threading.local()

    def _conn(self):
        s = getattr(self._tls, "sock", None)
        if s is None:
            host, port = self.endpoint.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=FLAGS.rpc_deadline)
            self._tls.sock = s
        return s

    def _drop_conn(self):
        s = getattr(self._tls, "sock", None)
        self._tls.sock = None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _call_once(self, msg):
        s = self._conn()
        try:
            _send_msg(s, msg)
            reply = _recv_msg(s)
        except (ConnectionError, EOFError, OSError, WireError):
            self._drop_conn()
            raise
        if "error" in reply:
            code = reply.get("code")
            if code == "overloaded":
                raise ServerOverloaded(reply["error"],
                                       priority=reply.get("shed_priority"))
            if code == "deadline":
                raise DeadlineExceeded(reply["error"])
            raise ServingError("%s (code=%s)" % (reply["error"], code))
        return reply

    def _call(self, msg, retry_deadline=None, retry_on=()):
        if retry_deadline is None:
            return self._call_once(msg)
        from ..utils.retry import default_rpc_policy
        policy = self._policy or default_rpc_policy(
            max_attempts=1 << 20, max_delay=0.5)
        return policy.call(
            lambda: self._call_once(msg),
            retry_on=(ConnectionError, OSError, EOFError) + tuple(retry_on),
            on_retry=lambda e, attempt: self._drop_conn()
            if isinstance(e, (ConnectionError, OSError, EOFError))
            else None,
            deadline=retry_deadline)

    def infer_stream(self, model, tokens, max_new_tokens=None,
                     deadline_ms=None, version=None, priority=None,
                     trace_id=None, chunk_tokens=None, debug=False):
        """Streaming generation: returns an iterator yielding token-
        delta lists as the server decodes them (the `infer_stream`
        verb's chunk frames).  The final frame's metadata lands on
        ``self.last_stream_info`` (finish_reason, new_tokens, trace_id,
        + server stage timings with ``debug=True``) when the iterator
        completes.  A mid-stream error surfaces as the typed exception
        (ServerOverloaded / DeadlineExceeded / ServingError) at the
        point of failure — tokens already yielded are real.  Closing
        the iterator early drops the connection, which tells the server
        to evict the request from its decode slot.

        The streaming reply uses a dedicated connection (frames would
        desync the request/reply socket), torn down when the stream
        ends or the iterator is closed."""
        msg = {"cmd": "infer_stream", "model": model,
               "tokens": np.ascontiguousarray(
                   np.asarray(tokens, np.int32))}
        if max_new_tokens is not None:
            msg["max_new_tokens"] = int(max_new_tokens)
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        if version is not None:
            msg["version"] = version
        if priority is not None:
            msg["priority"] = int(priority)
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        if chunk_tokens is not None:
            msg["stream_chunk_tokens"] = int(chunk_tokens)
        if debug:
            msg["debug"] = True
        self.last_stream_info = None

        def _gen():
            host, port = self.endpoint.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=FLAGS.rpc_deadline)
            finished = False
            try:
                _send_msg(s, msg)
                while True:
                    reply = _recv_msg(s)
                    if "error" in reply:
                        finished = True
                        self.last_stream_info = {
                            k: reply[k] for k in
                            ("trace_id", "new_tokens", "code")
                            if k in reply}
                        self.last_trace_id = reply.get("trace_id")
                        code = reply.get("code")
                        if code == "overloaded":
                            raise ServerOverloaded(
                                reply["error"],
                                priority=reply.get("shed_priority"))
                        if code == "deadline":
                            raise DeadlineExceeded(reply["error"])
                        raise ServingError("%s (code=%s)"
                                           % (reply["error"], code))
                    if reply.get("chunk"):
                        yield [int(t) for t in reply["tokens"]]
                        continue
                    finished = True
                    self.last_stream_info = {
                        k: v for k, v in reply.items() if k != "ok"}
                    self.last_trace_id = reply.get("trace_id")
                    return
            finally:
                # early close (or any exit): this connection never
                # carries another request — a dropped socket is also
                # the eviction signal for an abandoned stream
                try:
                    s.close()
                except OSError:
                    pass
                if not finished:
                    pass  # server notices the dead socket on next flush

        return _gen()

    def infer(self, model, feeds, deadline_ms=None, version=None,
              retry_sheds=None, priority=None, debug=False,
              trace_id=None, max_new_tokens=None, precision=None):
        """Run one request.  Returns the fetch list; with
        ``debug=True`` returns ``(fetches, info)`` where ``info`` is
        the server-measured latency attribution (trace_id,
        queue_wait_ms, compute_ms, batch_fill, replica ...) — the
        client-side half of OBSERVABILITY.md's latency story.
        ``trace_id`` pins a caller-minted id (propagated end to end and
        echoed back); the reply's id is also kept on
        ``self.last_trace_id`` for the plain return shape."""
        deadline_ms = self.deadline_ms if deadline_ms is None \
            else deadline_ms
        msg = {"cmd": "infer", "model": model,
               "feeds": {k: np.ascontiguousarray(np.asarray(v))
                         for k, v in feeds.items()}}
        if version is not None:
            msg["version"] = version
        if precision is not None:
            # pin the request to one numerics lane ('fp32' / 'int8');
            # without it the server's A/B weights route (QUANTIZE.md)
            msg["precision"] = str(precision)
        if max_new_tokens is not None:
            # decode models through the one-shot verb: the whole greedy
            # stream returns as fetches[0]
            msg["max_new_tokens"] = int(max_new_tokens)
        if priority is not None:
            # forwarded to admission control: larger = more important;
            # under overload the server sheds lowest-priority-first
            msg["priority"] = int(priority)
        if debug:
            msg["debug"] = True
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        retry_deadline = None
        retry_on = ()
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
            retry_deadline = time.monotonic() + float(deadline_ms) / 1000.0
            if retry_sheds is None or retry_sheds:
                retry_on = (ServerOverloaded,)
        elif retry_sheds:
            raise ValueError("retry_sheds needs a deadline_ms to bound it")
        reply = self._call(msg, retry_deadline=retry_deadline,
                           retry_on=retry_on)
        self.last_trace_id = reply.get("trace_id")
        fetches = list(reply["fetches"])
        if debug:
            return fetches, dict(reply.get("debug") or {})
        return fetches

    def load_model(self, name, path, version=None, buckets=None,
                   replicas=None, devices=None, decode_slots=None,
                   decode_mode=None, precision=None, ab_weight=None,
                   draft=None, spec_k=None, kv_cache_dtype=None,
                   fuse_steps=None, fleet_policy=None):
        msg = {"cmd": "load_model", "name": name, "path": path}
        if fleet_policy is not None:
            # per-model fleet policy body riding the load (SERVING.md
            # "Fleet controller"), e.g. 'max_replicas=4,page_ttl_s=600'
            msg["fleet_policy"] = str(fleet_policy)
        if kv_cache_dtype is not None:
            # decode artifacts: slot-table cache numerics for this
            # load — 'fp32'/'float32' or 'int8' (QUANTIZE.md)
            msg["kv_cache_dtype"] = str(kv_cache_dtype)
        if draft is not None:
            # speculative decoding: draft artifact path (SERVING.md);
            # the server pairs one draft replica per target replica
            msg["draft"] = str(draft)
        if spec_k is not None:
            msg["spec_k"] = int(spec_k)
        if fuse_steps is not None:
            # fused multi-step decode window per dispatch (SERVING.md
            # "Fused multi-step decode"; 1 keeps the classic loop)
            msg["fuse_steps"] = int(fuse_steps)
        if version is not None:
            msg["version"] = version
        if precision is not None:
            # lane override; normally auto-detected from the artifact
            msg["precision"] = str(precision)
        if ab_weight is not None:
            # this lane's share of default-routed traffic (A/B canary)
            msg["ab_weight"] = float(ab_weight)
        if buckets is not None:
            msg["buckets"] = [int(b) for b in buckets]
        if replicas is not None:
            # placement spec: int N, 'auto', or 'cpu:0,cpu:1' string
            msg["replicas"] = replicas if isinstance(replicas, str) \
                else int(replicas)
        if devices is not None:
            msg["devices"] = [str(d) for d in devices]
        if decode_slots is not None:
            msg["decode_slots"] = int(decode_slots)
        if decode_mode is not None:
            # "static" = the static-batch baseline (bench lanes only)
            msg["decode_mode"] = str(decode_mode)
        return self._call(msg)

    def unload_model(self, name):
        return self._call({"cmd": "unload_model", "name": name})

    def stats(self):
        return self._call({"cmd": "stats"})

    def health(self):
        """Per-model SLO state + lane liveness (the `health` verb's
        payload): {"draining", "models": {...}, "slo": {...},
        "flight": {...}} — see SERVING.md."""
        return self._call({"cmd": "health"})["health"]

    def fleet(self, set_policy=None, dry_run=None):
        """Fleet-controller readout/administration (the `fleet` verb):
        returns the controller status dict ({"enabled": False} when
        the server runs without one).  `set_policy` maps model name ->
        policy body ('min_replicas=1,max_replicas=4,page_ttl_s=600');
        `dry_run` flips rehearsal mode.  Both require the controller
        to be enabled server-side."""
        msg = {"cmd": "fleet"}
        if set_policy:
            msg["set_policy"] = {str(k): str(v)
                                 for k, v in dict(set_policy).items()}
        if dry_run is not None:
            msg["dry_run"] = bool(dry_run)
        return self._call(msg)["fleet"]

    def set_fleet_policy(self, model, spec):
        """Declare one model's fleet policy body on the server."""
        return self.fleet(set_policy={model: spec})

    def flight(self, reason="manual_rpc", force=True):
        """Trigger a flight-recorder bundle on the server; returns the
        committed bundle path, or None while the recorder is disabled
        (server-side FLAGS.flight_dir unset)."""
        return self._call({"cmd": "flight", "reason": str(reason),
                           "force": bool(force)}).get("bundle")

    def metrics_text(self):
        """The server's unified Prometheus-style exposition."""
        return self._call({"cmd": "metrics"})["text"]

    def trace(self, trace_id=None, limit=2048, kind=None):
        """Span-ring readout: all spans of one trace_id, or the most
        recent `limit` (optionally filtered by kind)."""
        msg = {"cmd": "trace", "limit": int(limit)}
        if trace_id is not None:
            msg["trace_id"] = str(trace_id)
        if kind is not None:
            msg["kind"] = str(kind)
        return self._call(msg)

    def shutdown_server(self, drain=True):
        try:
            return self._call({"cmd": "shutdown", "drain": bool(drain)})
        except (ConnectionError, OSError, EOFError):
            return None

    def close(self):
        self._drop_conn()
