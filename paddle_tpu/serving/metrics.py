"""Serving metrics: per-model counters and reservoir histograms.

Reference analogue: the serving-side telemetry TensorFlow Serving exposes
per servable (request count, latency percentiles, batch padding ratio) —
the numbers an operator needs to size batch buckets and admission limits.
Everything here is a plain in-process structure whose `snapshot()` is
wire-encodable (str keys, numbers, lists), so the same dict travels over
the `stats` RPC, lands in `tools/serving_top.py`, and rides bench lane
JSON untouched.

Histogram design: fixed-capacity reservoir sampling (Vitter's algorithm
R) — O(1) memory however long the server runs, percentiles over an
unbiased sample of the whole stream.  QPS is reported two ways: lifetime
average and a sliding recent window (completion timestamps ring), since
an idle-then-bursty server makes the lifetime number meaningless.
"""

import collections
import random
import threading
import time

__all__ = ["Counter", "ReservoirHistogram", "ModelMetrics",
           "ServingMetrics"]


class Counter:
    """Monotonic counter; `add` returns the new total."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        return self._value


class ReservoirHistogram:
    """Fixed-memory histogram over an unbounded stream: keeps a uniform
    random sample of `capacity` observations (reservoir sampling), plus
    exact count/sum/min/max.  Percentiles interpolate over the sorted
    reservoir — accurate to the sample, never unbounded in memory."""

    def __init__(self, capacity=512, seed=0):
        self.capacity = int(capacity)
        self._samples = []
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def record(self, value):
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            if len(self._samples) < self.capacity:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self._count)
                if j < self.capacity:
                    self._samples[j] = v

    @property
    def count(self):
        return self._count

    def percentile(self, q):
        """Linear-interpolated percentile (q in [0,100]) over the
        reservoir; None when empty."""
        with self._lock:
            s = sorted(self._samples)
        if not s:
            return None
        if len(s) == 1:
            return s[0]
        pos = (len(s) - 1) * (float(q) / 100.0)
        lo = int(pos)
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def summary(self):
        with self._lock:
            n, total = self._count, self._sum
            mn, mx = self._min, self._max
        out = {"count": n}
        if n:
            out.update({
                "mean": total / n, "min": mn, "max": mx,
                "p50": self.percentile(50), "p95": self.percentile(95),
                "p99": self.percentile(99),
            })
        return out


class ModelMetrics:
    """One served model's telemetry: request/response/shed counters, a
    latency + queue-wait histogram, and dispatch geometry (how full each
    micro-batch ran).  The batcher installs `queue_depth_fn` so depth is
    read live at snapshot time rather than sampled."""

    QPS_WINDOW_SECS = 60.0

    def __init__(self, name, precision="fp32"):
        self.name = name
        # the numerics lane these counters meter (QUANTIZE.md): an int8
        # A/B sibling of the same model name gets its OWN ModelMetrics,
        # so per-precision QPS/latency/compile-cache rows never blur
        self.precision = str(precision or "fp32")
        self.requests = Counter()        # accepted submits
        self.responses = Counter()       # futures resolved with a result
        self.errors = Counter()          # futures resolved with an error
        self.shed = Counter()            # rejected at admission
        self.deadline_expired = Counter()  # dropped overdue pre-dispatch
        self.dispatches = Counter()      # micro-batches executed
        self.coalesced = Counter()       # requests carried by dispatches
        self.batch_slots = Counter()     # real rows dispatched
        self.padded_slots = Counter()    # pad rows added to reach bucket
        self.latency_ms = ReservoirHistogram()
        self.queue_wait_ms = ReservoirHistogram()
        # persistent-compile-cache telemetry for THIS model's loads /
        # hot swaps (the registry attributes the process-global
        # compile_cache counter delta of each build+warm here)
        self.compile_cache_hits = Counter()
        self.compile_cache_misses = Counter()
        self.compile_ms = Counter()
        # generation telemetry (SERVING.md continuous batching): one
        # stream = one autoregressive request; tokens are the decode
        # throughput unit, TTFT the decode latency unit
        self.streams = Counter()         # streaming requests admitted
        self.prefills = Counter()        # prefill phases run
        self.decode_tokens = Counter()   # generated tokens emitted
        self.decode_steps = Counter()    # whole-slot-table step launches
        self.ttft_ms = ReservoirHistogram()  # time to first token
        # fused multi-step decode (SERVING.md "Fused multi-step
        # decode"): one dispatch now carries up to fuse_steps tokens
        # per slot — dispatches and the tokens-per-dispatch histogram
        # are the direct readout of the host-amortization win (TPD ~1
        # at N=1, ~N·occupancy when fused; serving_top's TPD column)
        self.decode_dispatches = Counter()  # device dispatches issued
        self.tokens_per_dispatch = ReservoirHistogram()
        # speculative decoding (SERVING.md): drafts/accepts telemetry —
        # the accept rate IS the speedup dial (tokens per verify step =
        # 1 + accepted/round), and with a same-weights draft it doubles
        # as a bit-exactness probe (any verify-vs-step numeric drift
        # shows up as a rejected draft before it shows up anywhere else)
        self.spec_rounds = Counter()     # draft->verify rounds run
        self.draft_tokens = Counter()    # draft proposals offered
        self.accepted_tokens = Counter()  # proposals accepted by verify
        self.spec_degraded = Counter()   # lanes fallen back target-only
        self.accept_rate = ReservoirHistogram()  # per-round accept frac
        # fleet paging (SERVING.md "Fleet controller"): how many times
        # this model faulted back in from a paged-out spec, and how
        # long each rebuild (reload + warm, all lanes) took — the
        # cold-start tax the warm compile cache is supposed to shrink
        self.fault_ins = Counter()
        self.fault_in_ms = ReservoirHistogram()
        self._token_stamps = collections.deque()  # (t, n) recent window
        self.queue_depth_fn = None
        # installed by the batcher: live per-replica lane snapshot
        # (device id, in-flight, lane queue, batches/rows executed)
        self.replica_stats_fn = None
        # installed by the decode batcher: live (occupied, total) slot
        # count across this model's lanes — the occupancy gauge
        self.slot_occupancy_fn = None
        # installed by the decode batcher: (kv_cache_dtype, measured
        # cache bytes across lanes) — the quantized-KV-cache axis the
        # bench A/B and serving_top read (QUANTIZE.md)
        self.kv_cache_fn = None
        self._shed_by_priority = {}      # priority class -> shed count
        # static resource estimates (ANALYSIS.md): set once per load /
        # hot swap by the registry's note_resource — the placement-by-
        # cost signal the fleet controller scrapes (model_est_peak_mb /
        # model_est_flops Prometheus gauges)
        self.est_peak_mb = None
        self.est_flops = None
        self._started = time.monotonic()
        # (t, latency_ms) completion stamps: one deque feeds BOTH the
        # recent-QPS window and the SLO monitor's interval-windowed
        # p95 (obs/slo.py) — the lifetime reservoir would blur a fresh
        # regression under hours of healthy history
        self._completions = collections.deque()
        self._ttft_stamps = collections.deque()  # (t, ttft_ms) recent
        self._lock = threading.Lock()

    def note_shed(self, priority=0):
        """One admission shed of the given priority class (lowest-
        priority-first overload policy — SERVING.md)."""
        self.shed.add()
        with self._lock:
            key = int(priority)
            self._shed_by_priority[key] = \
                self._shed_by_priority.get(key, 0) + 1

    def note_completion(self, latency_ms, queue_wait_ms=None):
        self.responses.add()
        self.latency_ms.record(latency_ms)
        if queue_wait_ms is not None:
            self.queue_wait_ms.record(queue_wait_ms)
        now = time.monotonic()
        with self._lock:
            self._completions.append((now, float(latency_ms)))
            horizon = now - self.QPS_WINDOW_SECS
            while self._completions and \
                    self._completions[0][0] < horizon:
                self._completions.popleft()

    def note_compile(self, delta):
        """Attribute one load/hot-swap's compile-cache counter delta
        (compile_cache.stats_delta) to this model."""
        self.compile_cache_hits.add(int(delta.get("hits", 0)))
        self.compile_cache_misses.add(int(delta.get("misses", 0)))
        self.compile_ms.add(int(round(delta.get("compile_ms", 0.0))))

    def note_resource(self, est_peak_mb, est_flops):
        """Record this lane's static resource estimate (the admission
        fit check's numbers — registry load_model calls this once per
        load; a hot swap overwrites with the new artifact's)."""
        self.est_peak_mb = float(est_peak_mb)
        self.est_flops = int(est_flops)

    def note_spec(self, proposed, accepted):
        """One speculative round: `proposed` draft tokens offered to
        the verify step, `accepted` of them greedily accepted."""
        self.spec_rounds.add()
        if proposed:
            self.draft_tokens.add(int(proposed))
            self.accepted_tokens.add(int(accepted))
            self.accept_rate.record(accepted / proposed)

    def note_fault_in(self, ms):
        """One fault-in completed: the paged model is resident again
        after `ms` of reload+warm across its lane set."""
        self.fault_ins.add()
        self.fault_in_ms.record(ms)

    def note_prefill(self, ttft_ms):
        """One prefill completed: the request's first token exists —
        the TTFT instant (time_to_first_token satellite metric)."""
        self.prefills.add()
        self.ttft_ms.record(ttft_ms)
        now = time.monotonic()
        with self._lock:
            self._ttft_stamps.append((now, float(ttft_ms)))
            horizon = now - self.QPS_WINDOW_SECS
            while self._ttft_stamps and \
                    self._ttft_stamps[0][0] < horizon:
                self._ttft_stamps.popleft()

    def note_decode_dispatch(self, tokens):
        """One decode dispatch completed, having emitted `tokens`
        stream tokens across its slots (0 counts too — an all-
        cancelled window is still a dispatch the host paid for)."""
        self.decode_dispatches.add()
        self.tokens_per_dispatch.record(float(tokens))

    def note_tokens(self, n):
        """`n` generated tokens emitted (across whatever slots the step
        served); feeds both the lifetime counter and the recent
        tokens/sec window."""
        self.decode_tokens.add(n)
        now = time.monotonic()
        with self._lock:
            self._token_stamps.append((now, int(n)))
            horizon = now - self.QPS_WINDOW_SECS
            while self._token_stamps and \
                    self._token_stamps[0][0] < horizon:
                self._token_stamps.popleft()

    def tokens_per_sec(self):
        """Recent-window aggregate generation rate — the continuous-
        batching acceptance number (>= 2x static batching on the mixed-
        length lane)."""
        now = time.monotonic()
        with self._lock:
            horizon = now - self.QPS_WINDOW_SECS
            while self._token_stamps and \
                    self._token_stamps[0][0] < horizon:
                self._token_stamps.popleft()
            total = sum(n for _, n in self._token_stamps)
            if not total:
                return 0.0
            span = min(self.QPS_WINDOW_SECS, now - self._started)
        return total / max(span, 1e-9)

    def note_dispatch(self, n_requests, real_rows, padded_rows):
        self.dispatches.add()
        self.coalesced.add(n_requests)
        self.batch_slots.add(real_rows)
        self.padded_slots.add(padded_rows)

    def recent_qps(self):
        now = time.monotonic()
        with self._lock:
            horizon = now - self.QPS_WINDOW_SECS
            while self._completions and \
                    self._completions[0][0] < horizon:
                self._completions.popleft()
            n = len(self._completions)
            if not n:
                return 0.0
            span = min(self.QPS_WINDOW_SECS, now - self._started)
        return n / max(span, 1e-9)

    @staticmethod
    def _window_p95(stamps, window_s):
        now = time.monotonic()
        horizon = now - max(float(window_s), 1e-3)
        vals = sorted(v for t, v in stamps if t >= horizon)
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        pos = (len(vals) - 1) * 0.95
        lo = int(pos)
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def recent_latency_p95(self, window_s):
        """p95 latency over completions in the last `window_s` seconds
        (None with no traffic) — the SLO monitor's interval SLI; the
        window is capped by QPS_WINDOW_SECS of retained stamps."""
        with self._lock:
            stamps = list(self._completions)
        return self._window_p95(stamps, window_s)

    def recent_ttft_p95(self, window_s):
        """p95 time-to-first-token over prefills in the last
        `window_s` seconds (None for one-shot models / no streams)."""
        with self._lock:
            stamps = list(self._ttft_stamps)
        return self._window_p95(stamps, window_s)

    def snapshot(self):
        uptime = time.monotonic() - self._started
        dispatches = self.dispatches.value
        slots = self.batch_slots.value
        padded = self.padded_slots.value
        snap = {
            "model": self.name,
            "precision": self.precision,
            "uptime_sec": round(uptime, 3),
            "requests": self.requests.value,
            "responses": self.responses.value,
            "errors": self.errors.value,
            "shed": self.shed.value,
            "deadline_expired": self.deadline_expired.value,
            "dispatches": dispatches,
            "qps_recent": round(self.recent_qps(), 3),
            "qps_lifetime": round(self.responses.value / max(uptime, 1e-9),
                                  3),
            # requests per dispatch: > 1 means cross-request coalescing
            # is actually happening (the acceptance criterion's number)
            "batch_fill": round(self.coalesced.value / dispatches, 3)
            if dispatches else 0.0,
            # real rows / (real + pad) rows: how much of each bucket the
            # traffic filled — the TPU-utilization lever
            "bucket_fill_ratio": round(slots / (slots + padded), 3)
            if (slots + padded) else 0.0,
            "latency_ms": self.latency_ms.summary(),
            "queue_wait_ms": self.queue_wait_ms.summary(),
            # did this model's boots/flips reuse stored executables or
            # pay fresh compiles? (serving_top's CCH/CCM column)
            "compile_cache": {
                "hits": self.compile_cache_hits.value,
                "misses": self.compile_cache_misses.value,
                "compile_ms": self.compile_ms.value,
            },
        }
        if self.fault_ins.value:
            # fleet paging telemetry: count + rebuild-time summary
            # (flat keys — serving_top/bench read them unchanged)
            snap["fault_ins"] = self.fault_ins.value
            snap["fault_in_ms"] = self.fault_in_ms.summary()
        if self.est_peak_mb is not None:
            # static resource estimate (set at load by the admission
            # fit check) — flat keys so Prometheus/serving_top pick
            # them up with zero schema plumbing
            snap["est_peak_mb"] = round(self.est_peak_mb, 3)
            snap["est_flops"] = int(self.est_flops or 0)
        if self.streams.value or self.slot_occupancy_fn is not None:
            # generation telemetry, flat keys so the Prometheus render
            # and serving_top pick them up with zero schema plumbing
            snap["streams"] = self.streams.value
            snap["prefills"] = self.prefills.value
            snap["decode_tokens"] = self.decode_tokens.value
            snap["decode_steps"] = self.decode_steps.value
            snap["decode_dispatches"] = self.decode_dispatches.value
            snap["tokens_per_dispatch"] = \
                self.tokens_per_dispatch.summary()
            snap["tokens_per_sec"] = round(self.tokens_per_sec(), 3)
            snap["ttft_ms"] = self.ttft_ms.summary()
            if self.slot_occupancy_fn is not None:
                try:
                    occupied, total = self.slot_occupancy_fn()
                    snap["slot_occupancy"] = round(
                        occupied / total, 3) if total else 0.0
                    snap["decode_slots"] = int(total)
                    snap["decode_slots_busy"] = int(occupied)
                except Exception:
                    snap["slot_occupancy"] = -1.0
            if self.kv_cache_fn is not None:
                try:
                    kv_dtype, kv_bytes = self.kv_cache_fn()
                    snap["kv_cache_dtype"] = str(kv_dtype)
                    snap["kv_cache_bytes"] = int(kv_bytes)
                except Exception:
                    pass
        if self.spec_rounds.value or self.spec_degraded.value:
            # speculative decoding telemetry (serving_top's ACC%
            # column, Prometheus spec_* families)
            proposed = self.draft_tokens.value
            snap["spec_rounds"] = self.spec_rounds.value
            snap["draft_tokens"] = proposed
            snap["accepted_tokens"] = self.accepted_tokens.value
            snap["spec_degraded"] = self.spec_degraded.value
            snap["spec_accept_rate"] = round(
                self.accepted_tokens.value / proposed, 4) \
                if proposed else 0.0
            snap["accept_rate"] = self.accept_rate.summary()
        if self.queue_depth_fn is not None:
            try:
                snap["queue_depth"] = int(self.queue_depth_fn())
            except Exception:
                snap["queue_depth"] = -1
        with self._lock:
            if self._shed_by_priority:
                # str keys: the snapshot must stay wire-encodable
                snap["shed_by_priority"] = {
                    str(k): v
                    for k, v in sorted(self._shed_by_priority.items())}
        if self.replica_stats_fn is not None:
            try:
                snap["replicas"] = list(self.replica_stats_fn())
            except Exception:
                snap["replicas"] = []
        return snap


class ServingMetrics:
    """The server-wide registry: one ModelMetrics per model name (shared
    across that model's versions — a hot swap does not reset counters)."""

    def __init__(self):
        self._models = {}
        self._lock = threading.Lock()
        self._started = time.monotonic()

    def model(self, name, precision=None):
        """One ModelMetrics per (name, precision lane).  The fp32 lane
        keeps the bare-name key (and so the pre-quantization wire
        schema); other lanes key as ``name@precision`` — two lanes of
        one model render as two rows in stats/serving_top/Prometheus."""
        key = name if precision in (None, "fp32") \
            else "%s@%s" % (name, precision)
        with self._lock:
            m = self._models.get(key)
            if m is None:
                m = self._models[key] = ModelMetrics(
                    name, precision=precision or "fp32")
            return m

    def drop(self, name):
        with self._lock:
            self._models.pop(name, None)
            for key in [k for k in self._models
                        if k.startswith(name + "@")]:
                self._models.pop(key, None)

    def snapshot(self):
        with self._lock:
            models = dict(self._models)
        out = {
            "uptime_sec": round(time.monotonic() - self._started, 3),
            "models": {name: m.snapshot() for name, m in models.items()},
        }
        try:
            # process-wide store counters (hits/misses/compile_ms/...):
            # the cold-start-vs-warm-boot story at a glance in `stats`
            from .. import compile_cache
            out["compile_cache"] = compile_cache.stats()
        except Exception:
            pass
        return out
