"""Fleet controller: SLO-driven autoscaling, cold-model paging, and
pressure degradation for the multi-model zoo.

PR 13 landed the *judgment* layer (obs/slo.py burn-rate states, the
`health` RPC's lane liveness, the flight recorder) but nothing ACTED on
those signals: replica counts, lane weights, and which models are
resident were all static operator choices.  This module is the control
plane above the registry — a per-server background loop
(``FLAGS.fleet_controller`` / ``fleet_eval_interval_ms``) that each
tick reads the per-model sensors and closes the loop through three
actuators the serving stack already guarantees safe:

* **scale** — grow/shrink a model's replica set within its declared
  ``[min_replicas, max_replicas]`` policy via
  ``ModelRegistry.resize_model``, which replays the model's persisted
  load spec at the new placement through ``load_model`` — i.e. every
  resize rides the build-warm-flip hot-swap discipline (SERVING.md),
  so scaling is zero-drop by construction, and the ANALYSIS.md
  resource fit check gates every grow before any build work;
* **page** — a model idle past ``page_ttl_s`` unloads to its artifact
  path (``ModelRegistry.page_out`` keeps the load spec + A/B weights)
  and faults back in on the next request — or from here on rising
  burn — with the COMPILE_CACHE.md store making fault-in a reload,
  not a recompile; time-to-fault-in is measured and pinned
  (``fault_in_ms`` gauge, ``fleet_fault_in`` event);
* **degrade** — under sustained burn, shift default-traffic
  ``ab_weight`` toward the int8 lane (when a quantized peer exists —
  QUANTIZE.md) *before* admission starts shedding; restore the saved
  weights only after ``restore_evals`` consecutive clean ticks
  (hysteresis — the weight must not flap with the burn).

Every action is emitted as a structured obs event carrying the
triggering signal, per-mechanism cooldowns bound the actuation rate,
and ``dry_run`` logs each decision (``fleet_decision`` events) without
touching the registry.

The decision core is a PURE function — ``decide(sensors, policy,
state, now)`` maps one model's sensor snapshot + controller state to a
list of :class:`FleetAction` — so the policy is testable from seeded
snapshots without a live server (tests/test_fleet.py).

Policy grammar (``FLAGS.fleet_policy`` / the ``fleet`` RPC's
``set_policy``): the serving_slo spec syntax —
``[model:]key=val,key=val;...`` with ``*`` (or no prefix) as the
default applied to every model without its own declaration.
"""

import collections
import threading
import time

__all__ = ["FleetPolicy", "FleetAction", "ModelSensors",
           "FleetController", "parse_fleet_spec", "decide",
           "FLEET_ACTIVE", "FLEET_DEGRADED", "FLEET_PAGED"]

# fleet_state gauge codes (obs/registry.py fleet families)
FLEET_ACTIVE = "active"
FLEET_DEGRADED = "degraded"
FLEET_PAGED = "paged"
_STATE_CODE = {FLEET_ACTIVE: 0, FLEET_DEGRADED: 1, FLEET_PAGED: 2}

# SLO health states the sensors carry (obs/slo.py)
_SLO_DEGRADED = "degraded"
_SLO_BREACH = "breach"

_POLICY_INTS = ("min_replicas", "max_replicas", "scale_up_queue",
                "restore_evals")
_POLICY_FLOATS = ("page_ttl_s", "scale_down_idle_s", "degrade_weight",
                  "scale_cooldown_s", "page_cooldown_s",
                  "degrade_cooldown_s")
_POLICY_KEYS = _POLICY_INTS + _POLICY_FLOATS


class FleetPolicy(object):
    """One model's declared scaling/paging/degradation envelope.  The
    controller never acts outside it: ``max_replicas=1`` (default)
    disables scaling, ``page_ttl_s=0`` disables paging, and a model
    with no policy at all (and no ``*`` default) is observe-only."""

    __slots__ = ("min_replicas", "max_replicas", "page_ttl_s",
                 "scale_up_queue", "scale_down_idle_s",
                 "degrade_weight", "restore_evals", "scale_cooldown_s",
                 "page_cooldown_s", "degrade_cooldown_s")

    def __init__(self, min_replicas=1, max_replicas=1, page_ttl_s=0.0,
                 scale_up_queue=4, scale_down_idle_s=30.0,
                 degrade_weight=0.9, restore_evals=3,
                 scale_cooldown_s=15.0, page_cooldown_s=30.0,
                 degrade_cooldown_s=10.0):
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.page_ttl_s = max(float(page_ttl_s), 0.0)
        self.scale_up_queue = max(int(scale_up_queue), 1)
        self.scale_down_idle_s = max(float(scale_down_idle_s), 0.0)
        self.degrade_weight = min(max(float(degrade_weight), 0.0), 1.0)
        self.restore_evals = max(int(restore_evals), 1)
        self.scale_cooldown_s = max(float(scale_cooldown_s), 0.0)
        self.page_cooldown_s = max(float(page_cooldown_s), 0.0)
        self.degrade_cooldown_s = max(float(degrade_cooldown_s), 0.0)

    def to_dict(self):
        return {k: getattr(self, k) for k in _POLICY_KEYS}

    def __repr__(self):
        return "FleetPolicy(%s)" % ", ".join(
            "%s=%s" % (k, v) for k, v in sorted(self.to_dict().items()))


def parse_fleet_spec(spec):
    """Parse ``FLAGS.fleet_policy`` into {model_or_*: FleetPolicy} —
    the serving_slo grammar: ``[model:]key=val,key=val;...``."""
    out = {}
    if not spec:
        return out
    for decl in str(spec).split(";"):
        decl = decl.strip()
        if not decl:
            continue
        model, body = "*", decl
        head, sep, rest = decl.partition(":")
        if sep and "=" not in head:
            model, body = (head.strip() or "*"), rest
        kwargs = {}
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            key, eq, val = part.partition("=")
            key = key.strip()
            if not eq or key not in _POLICY_KEYS:
                raise ValueError(
                    "bad fleet policy entry %r (model %r) — keys are %s"
                    % (part, model, ", ".join(_POLICY_KEYS)))
            kwargs[key] = int(float(val)) if key in _POLICY_INTS \
                else float(val)
        out[model] = FleetPolicy(**kwargs)
    return out


class ModelSensors(object):
    """One model's sensor snapshot for one evaluation tick — plain
    data, so seeded instances drive ``decide()`` in tests without a
    live server."""

    __slots__ = ("model", "replicas", "paged", "queue_depth",
                 "occupancy", "slo_state", "burn_fast",
                 "requests_delta", "shed_delta", "idle_s",
                 "has_int8_peer", "ab", "decode")

    def __init__(self, model, replicas=1, paged=False, queue_depth=0,
                 occupancy=None, slo_state=None, burn_fast=None,
                 requests_delta=0, shed_delta=0, idle_s=0.0,
                 has_int8_peer=False, ab=None, decode=False):
        self.model = str(model)
        self.replicas = int(replicas)
        self.paged = bool(paged)
        self.queue_depth = int(queue_depth)
        self.occupancy = occupancy
        self.slo_state = slo_state
        self.burn_fast = burn_fast
        self.requests_delta = int(requests_delta)
        self.shed_delta = int(shed_delta)
        self.idle_s = float(idle_s)
        self.has_int8_peer = bool(has_int8_peer)
        self.ab = dict(ab or {})
        self.decode = bool(decode)

    def to_dict(self):
        d = {"model": self.model, "replicas": self.replicas,
             "paged": self.paged, "queue_depth": self.queue_depth,
             "requests_delta": self.requests_delta,
             "shed_delta": self.shed_delta,
             "idle_s": round(self.idle_s, 3)}
        if self.slo_state is not None:
            d["slo_state"] = self.slo_state
        if self.burn_fast is not None:
            d["burn_fast"] = round(self.burn_fast, 3)
        if self.occupancy is not None:
            d["occupancy"] = round(self.occupancy, 3)
        if self.has_int8_peer:
            d["has_int8_peer"] = True
            if self.ab:
                d["ab"] = dict(self.ab)
        return d


class FleetAction(object):
    """One decided actuation: what to do, to which model, with which
    parameters, and the SENSOR SIGNAL that triggered it (the signal
    rides the emitted event — acceptance: every action is evented with
    its triggering signal)."""

    __slots__ = ("kind", "model", "params", "signal")

    def __init__(self, kind, model, params=None, signal=None):
        self.kind = str(kind)
        self.model = str(model)
        self.params = dict(params or {})
        self.signal = dict(signal or {})

    def to_dict(self):
        return {"kind": self.kind, "model": self.model,
                "params": dict(self.params),
                "signal": dict(self.signal)}

    def __repr__(self):
        return "FleetAction(%s, %s, %s)" % (self.kind, self.model,
                                            self.params)


def _cool(state, key, now, cooldown_s):
    """True when the mechanism's cooldown has elapsed (or never
    fired)."""
    last = state.get(key)
    return last is None or (now - last) >= cooldown_s


def decide(sensors, policy, state, now):
    """The pure decision core: one model's sensors + controller state
    -> ordered FleetAction list.  ``state`` is read-only here — the
    controller stamps cooldowns/streaks only after an action actually
    executes.  Ordering is the execution order, and encodes
    degrade-before-shed: under breach the cheap capacity (the int8
    lane) is engaged before (or alongside) the expensive one (a new
    replica set), and always before admission starts shedding.
    """
    acts = []
    if policy is None or sensors is None:
        return acts
    s = sensors
    if s.paged:
        # paged models act on DEMAND only: traffic/sheds arriving (the
        # registry's request path usually faults in first — this
        # covers the rising-burn / shed-while-paged case)
        if (s.requests_delta > 0 or s.shed_delta > 0
                or s.slo_state in (_SLO_DEGRADED, _SLO_BREACH)):
            acts.append(FleetAction(
                "fault_in", s.model,
                signal=dict(s.to_dict(), trigger="demand")))
        return acts
    pressure = s.slo_state in (_SLO_DEGRADED, _SLO_BREACH) or (
        s.queue_depth >= policy.scale_up_queue * max(s.replicas, 1))
    if (s.slo_state == _SLO_BREACH and s.has_int8_peer
            and not state.get("degraded")
            and s.ab.get("int8", 0.0) < policy.degrade_weight
            and _cool(state, "last_degrade_t", now,
                      policy.degrade_cooldown_s)):
        acts.append(FleetAction(
            "degrade", s.model,
            params={"weight": policy.degrade_weight,
                    "saved_ab": dict(s.ab)},
            signal=dict(s.to_dict(), trigger="sustained_burn")))
    if (pressure and s.replicas < policy.max_replicas
            and _cool(state, "last_scale_t", now,
                      policy.scale_cooldown_s)):
        acts.append(FleetAction(
            "scale_up", s.model,
            params={"replicas": s.replicas + 1},
            signal=dict(s.to_dict(),
                        trigger="slo" if s.slo_state in
                        (_SLO_DEGRADED, _SLO_BREACH) else "queue")))
    if (state.get("degraded") and s.slo_state not in
            (_SLO_DEGRADED, _SLO_BREACH)
            and state.get("clean_streak", 0) >= policy.restore_evals):
        acts.append(FleetAction(
            "restore", s.model,
            params={"ab": dict(state.get("saved_ab") or {})},
            signal=dict(s.to_dict(), trigger="recovered",
                        clean_streak=state.get("clean_streak", 0))))
    if pressure:
        return acts
    # idle-side actions: paging supersedes shrinking (the whole model
    # leaves the device — no point resizing what is about to unload)
    if (policy.page_ttl_s > 0 and s.idle_s >= policy.page_ttl_s
            and not state.get("degraded")
            and _cool(state, "last_page_t", now, policy.page_cooldown_s)):
        acts.append(FleetAction(
            "page_out", s.model,
            signal=dict(s.to_dict(), trigger="idle_ttl",
                        ttl_s=policy.page_ttl_s)))
        return acts
    if (s.replicas > policy.min_replicas
            and s.idle_s >= policy.scale_down_idle_s
            and _cool(state, "last_scale_t", now,
                      policy.scale_cooldown_s)):
        acts.append(FleetAction(
            "scale_down", s.model,
            params={"replicas": s.replicas - 1},
            signal=dict(s.to_dict(), trigger="idle")))
    return acts


class FleetController(object):
    """The per-server control loop: senses (registry + metrics + SLO
    monitor), decides (the pure ``decide``), and actuates through the
    registry — on a daemon thread every ``interval_s``, or stepped by
    hand via ``tick()`` (tests, synthetic drivers).

    ``dry_run`` logs every decision as a ``fleet_decision`` event and
    changes NOTHING (and stamps no cooldowns — a rehearsal keeps
    re-announcing what it would do)."""

    ACTIONS_KEPT = 64

    def __init__(self, registry, metrics, slo=None, policies=None,
                 interval_s=None, dry_run=None, name="server"):
        from ..flags import FLAGS
        self.registry = registry
        self.metrics = metrics
        self.slo = slo
        self.name = str(name)
        self.interval_s = (float(FLAGS.fleet_eval_interval_ms) / 1000.0
                           if interval_s is None else float(interval_s))
        self.interval_s = max(self.interval_s, 0.01)
        self.dry_run = (bool(FLAGS.fleet_dry_run) if dry_run is None
                        else bool(dry_run))
        # federation endpoint owning replica/paging decisions for this
        # server (set by InferenceServer.start when federated): the
        # global tier counts replicas CLUSTER-wide, so the per-server
        # controller must not fight it over the same knobs — scale and
        # page actions are logged as delegated instead of executed;
        # degrade/restore (the int8 pressure valve) and demand fault-in
        # stay local, they are per-server by nature
        self.delegated_to = None
        self._lock = threading.Lock()
        self._policies = dict(policies or {})  # model (or '*') -> policy
        self._state = {}           # model -> controller bookkeeping
        self._last_sensors = {}    # model -> ModelSensors (last tick)
        self._actions = collections.deque(maxlen=self.ACTIONS_KEPT)
        self._stop = threading.Event()
        self._thread = None
        self._ticks = 0

    @classmethod
    def from_flags(cls, registry, metrics, slo=None, name="server"):
        from ..flags import FLAGS
        return cls(registry, metrics, slo=slo,
                   policies=parse_fleet_spec(FLAGS.fleet_policy),
                   name=name)

    # -- policies ------------------------------------------------------

    def set_policy(self, model, policy=None, **kwargs):
        """Declare (or replace) one model's policy: a FleetPolicy, a
        spec-body string ('min_replicas=1,max_replicas=4,...'), or
        kwargs."""
        if isinstance(policy, str):
            parsed = parse_fleet_spec(policy)
            policy = parsed.get("*") or parsed.get(str(model))
            if policy is None:
                raise ValueError("fleet policy spec %r declared no "
                                 "usable body" % model)
        if policy is None:
            policy = FleetPolicy(**kwargs)
        with self._lock:
            self._policies[str(model)] = policy
        return policy

    def policy_for(self, model):
        with self._lock:
            return (self._policies.get(str(model))
                    or self._policies.get("*"))

    # -- sensing -------------------------------------------------------

    def _lane_keys(self, lanes, model):
        return [k for k in lanes
                if k == model or k.startswith(model + "@")]

    def _collect_sensors_locked(self, now):
        """One ModelSensors per model (live or paged), aggregated
        across its precision lanes (caller holds self._lock — the
        `_locked` suffix is the lint-checked convention)."""
        desc = self.registry.describe()
        paged = self.registry.paged_models()
        slo_state = self.slo.state() if self.slo is not None else {}
        with self.metrics._lock:
            lanes = dict(self.metrics._models)
        out = {}
        for model in sorted(set(desc) | set(paged)):
            d = desc.get(model) or {}
            is_paged = bool(d.get("paged")) or (
                model in paged and "latest" not in d)
            requests = shed = queue_depth = 0
            occ_busy = occ_total = 0
            for key in self._lane_keys(lanes, model):
                mm = lanes[key]
                requests += mm.requests.value
                shed += mm.shed.value
                if mm.queue_depth_fn is not None:
                    try:
                        queue_depth += int(mm.queue_depth_fn())
                    except Exception:
                        pass
                if mm.slot_occupancy_fn is not None:
                    try:
                        busy, total = mm.slot_occupancy_fn()
                        occ_busy += int(busy)
                        occ_total += int(total)
                    except Exception:
                        pass
            st = self._state.setdefault(
                model, {"requests": requests, "shed": shed,
                        "last_traffic_t": now, "clean_streak": 0,
                        "degraded": False, "saved_ab": None})
            req_delta = max(requests - st.get("requests", 0), 0)
            shed_delta = max(shed - st.get("shed", 0), 0)
            st["requests"], st["shed"] = requests, shed
            if req_delta > 0 or shed_delta > 0:
                st["last_traffic_t"] = now
            idle_s = max(now - st.get("last_traffic_t", now), 0.0)
            worst, burn_fast = None, None
            for key in self._lane_keys(slo_state, model):
                info = slo_state.get(key) or {}
                lane_st = info.get("state")
                if lane_st is not None:
                    order = {None: -1, "ok": 0, _SLO_DEGRADED: 1,
                             _SLO_BREACH: 2}
                    if order.get(lane_st, 0) > order.get(worst, -1):
                        worst = lane_st
                for b in (info.get("burn") or {}).values():
                    f = b.get("fast")
                    if f is not None and (burn_fast is None
                                          or f > burn_fast):
                        burn_fast = f
            precisions = d.get("precisions") or {}
            out[model] = ModelSensors(
                model,
                replicas=int(d.get("replicas", 0) or 0),
                paged=is_paged,
                queue_depth=queue_depth,
                occupancy=(occ_busy / occ_total) if occ_total else None,
                slo_state=worst,
                burn_fast=burn_fast,
                requests_delta=req_delta,
                shed_delta=shed_delta,
                idle_s=idle_s,
                has_int8_peer="int8" in precisions,
                ab=d.get("ab_weights") or {},
                decode=bool(d.get("decode")))
        return out

    # -- actuation -----------------------------------------------------

    def _execute(self, action, now):
        """Run one decided action against the registry; returns an
        error string (None on success).  Events for scale/page/fault
        actions are emitted by the registry actuators themselves (they
        carry the measured facts); degrade/restore emit here."""
        from ..obs import events as obs_events
        reg = self.registry
        with self._lock:
            st = self._state.setdefault(action.model, {})
        if action.kind in ("scale_up", "scale_down"):
            reg.resize_model(action.model, action.params["replicas"],
                             signal=action.signal)
            st["last_scale_t"] = now
        elif action.kind == "page_out":
            reg.page_out(action.model, signal=action.signal)
            st["last_page_t"] = now
        elif action.kind == "fault_in":
            reg.fault_in(action.model, trigger="controller",
                         signal=action.signal)
            st["last_page_t"] = now
        elif action.kind == "degrade":
            st["saved_ab"] = dict(action.params.get("saved_ab") or {})
            reg.set_ab_weights(
                action.model, {"int8": action.params["weight"]})
            st["degraded"] = True
            st["last_degrade_t"] = now
            fields = dict(action.signal)
            fields.update(model=action.model,
                          weight=action.params["weight"])
            obs_events.emit("fleet_degraded", **fields)
        elif action.kind == "restore":
            reg.set_ab_weights(action.model,
                               dict(action.params.get("ab") or {}))
            st["degraded"] = False
            st["clean_streak"] = 0
            st["last_degrade_t"] = now
            fields = dict(action.signal)
            fields.update(model=action.model,
                          ab=dict(action.params.get("ab") or {}))
            obs_events.emit("fleet_restored", **fields)
        else:
            return "unknown action kind %r" % action.kind
        return None

    def tick(self):
        """One sense -> decide -> act pass.  Returns the list of
        (action, error_or_None) pairs it processed (dry-run decisions
        return error "dry_run")."""
        from ..analysis import ResourceFitError
        from ..obs import events as obs_events
        now = time.monotonic()
        processed = []
        with self._lock:
            self._ticks += 1
            sensors = self._collect_sensors_locked(now)
            self._last_sensors = sensors
            # drop state for models that left entirely (unloaded, not
            # paged) so a re-load starts fresh
            for gone in [m for m in self._state if m not in sensors]:
                self._state.pop(gone, None)
            plan = []
            for model, s in sensors.items():
                policy = (self._policies.get(model)
                          or self._policies.get("*"))
                st = self._state.setdefault(model, {})
                if s.slo_state in (_SLO_DEGRADED, _SLO_BREACH):
                    st["clean_streak"] = 0
                else:
                    st["clean_streak"] = st.get("clean_streak", 0) + 1
                plan.extend(
                    (a, policy) for a in decide(s, policy,
                                                dict(st), now))
            dry = self.dry_run
        # actuate OUTSIDE the lock: a resize is a full build+warm+flip
        # and status()/export() reads must not serialize behind it
        for action, _policy in plan:
            if (self.delegated_to
                    and action.kind in ("scale_up", "scale_down",
                                        "page_out")):
                # a federation frontend owns this knob cluster-wide:
                # record the local signal, leave actuation to the
                # global tier (SERVING.md "Federated serving")
                fields = dict(action.signal)
                fields.update(model=action.model, action=action.kind,
                              delegated=str(self.delegated_to))
                obs_events.emit("fleet_decision", **fields)
                processed.append(
                    (action, "delegated:%s" % self.delegated_to))
                continue
            if dry:
                fields = dict(action.signal)
                fields.update(model=action.model, action=action.kind,
                              dry_run=True)
                obs_events.emit("fleet_decision", **fields)
                processed.append((action, "dry_run"))
                continue
            try:
                err = self._execute(action, now)
            except ResourceFitError as e:
                # the fit check gated a grow: event it, stamp the
                # cooldown so the controller does not hammer the gate
                err = "fit_rejected: %s" % e
                fields = dict(action.signal)
                fields.update(model=action.model, error=str(e))
                obs_events.emit("fleet_scale_rejected", **fields)
                with self._lock:
                    self._state.setdefault(action.model, {})[
                        "last_scale_t"] = now
            except Exception as e:  # one bad actuation never stops the loop
                err = "%s: %s" % (type(e).__name__, e)
            processed.append((action, err))
        if processed:
            with self._lock:
                for action, err in processed:
                    rec = action.to_dict()
                    rec["age_s"] = 0.0
                    rec["t_mono"] = now
                    if err:
                        rec["error"] = err
                    self._actions.append(rec)
        return processed

    # -- thread lifecycle ----------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle-tpu-fleet-%s" % self.name)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # the control plane must never take down the serving
                # process; a broken tick retries next interval
                pass

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    @property
    def running(self):
        t = self._thread
        return bool(t is not None and t.is_alive())

    # -- readouts ------------------------------------------------------

    def _model_state(self, model, sensors):
        st = self._state.get(model) or {}
        if sensors is not None and sensors.paged:
            return FLEET_PAGED
        if st.get("degraded"):
            return FLEET_DEGRADED
        return FLEET_ACTIVE

    def status(self):
        """Wire-encodable controller readout (the `fleet` RPC payload
        and serving_top's --json "fleet" key)."""
        now = time.monotonic()
        fault = dict(getattr(self.registry, "last_fault_in", {}) or {})
        with self._lock:
            models = {}
            for model, s in sorted(self._last_sensors.items()):
                st = self._state.get(model) or {}
                info = {"state": self._model_state(model, s),
                        "replicas": s.replicas,
                        "paged": s.paged,
                        "queue_depth": s.queue_depth,
                        "idle_s": round(s.idle_s, 3),
                        "degraded": bool(st.get("degraded"))}
                if s.slo_state is not None:
                    info["slo_state"] = s.slo_state
                fi = fault.get(model)
                if fi:
                    info["fault_in_ms"] = fi.get("ms")
                    info["fault_in_trigger"] = fi.get("trigger")
                models[model] = info
            actions = []
            for rec in list(self._actions):
                r = {k: v for k, v in rec.items() if k != "t_mono"}
                r["age_s"] = round(max(now - rec["t_mono"], 0.0), 3)
                actions.append(r)
            return {"enabled": True, "dry_run": self.dry_run,
                    "running": self.running,
                    "interval_s": self.interval_s,
                    "ticks": self._ticks,
                    "policies": {k: p.to_dict() for k, p in
                                 sorted(self._policies.items())},
                    "models": models,
                    "actions": actions}

    def export(self):
        """Prometheus samples for the registry render:
        [(metric, labels, value, type)] — fleet_replicas, fleet_state
        (0 active / 1 degraded / 2 paged), fault_in_ms (last measured
        fault-in, absent until one happened)."""
        fault = dict(getattr(self.registry, "last_fault_in", {}) or {})
        with self._lock:
            rows = []
            for model, s in sorted(self._last_sensors.items()):
                labels = {"model": model}
                rows.append(("fleet_replicas", dict(labels),
                             0 if s.paged else s.replicas, "gauge"))
                rows.append(("fleet_state", dict(labels),
                             _STATE_CODE[self._model_state(model, s)],
                             "gauge"))
                fi = fault.get(model)
                if fi and fi.get("ms") is not None:
                    rows.append(("fault_in_ms", dict(labels),
                                 round(float(fi["ms"]), 3), "gauge"))
            return rows
