"""Cross-request dynamic micro-batcher.

The serving front's core mechanism: many callers each submit a small
(often batch-1) request; TPU executables want the biggest batch bucket
they were compiled for (the MLPerf TPU-pod study's lesson — batch
geometry IS the utilization lever).  The batcher closes that gap by
coalescing waiting requests into one padded bucket dispatch:

  * bounded queue per model (admission control: a submit past
    `max_queue` is shed with `ServerOverloaded`, never parked on an
    unbounded backlog — shed-not-hang);
  * a dispatch worker takes the head request, then greedily pulls
    compatible queued requests until the largest bucket is full or a
    `FLAGS.serving_batch_deadline_ms` window expires;
  * batch-major feeds (the program-var -1 leading-dim markers the AOT
    meta records and the live Predictor now exposes the same way) are
    concatenated; fixed-shape side feeds must be byte-identical to
    coalesce and ride through whole;
  * the underlying predictor pads the merged batch up to its bucket and
    un-pads batch-major fetches (that parity is the predictor's existing
    contract); the batcher scatters per-request row slices back to each
    caller's Future.

Compatibility grouping: requests only coalesce when their feed names,
trailing shapes, dtypes, and side-feed bytes agree — everything else
dispatches as its own group, correct but uncoalesced.

Chaos: `set_dispatch_delay(secs)` (or env
`PADDLE_TPU_SERVING_CHAOS="dispatch_delay=<secs>"`) injects a slow-worker
stall inside dispatch — the overload scenarios in tools/chaos.py and
tests/test_serving.py drive admission control with it.
"""

import binascii
import collections
import os
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..flags import FLAGS

__all__ = ["DynamicBatcher", "ServerOverloaded", "DeadlineExceeded",
           "BatcherClosed", "set_dispatch_delay"]

_CHAOS_ENV = "PADDLE_TPU_SERVING_CHAOS"


class ServerOverloaded(RuntimeError):
    """Admission control shed: the model's request queue is full.
    Explicit and immediate — the client can back off and retry
    (utils/retry.py jitter) instead of waiting on a hidden backlog."""


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before its dispatch completed."""


class BatcherClosed(RuntimeError):
    """Submit on a draining/retired batcher (e.g. mid hot-swap retire)."""


_dispatch_delay = 0.0


def set_dispatch_delay(secs):
    """Chaos hook: every subsequent dispatch sleeps `secs` first —
    the in-process slow-worker fault (0 clears)."""
    global _dispatch_delay
    _dispatch_delay = float(secs)


def _chaos_delay():
    if _dispatch_delay:
        return _dispatch_delay
    spec = os.environ.get(_CHAOS_ENV)
    if spec:
        for part in spec.split(","):
            name, _, val = part.partition("=")
            if name.strip() == "dispatch_delay":
                try:
                    return float(val)
                except ValueError:
                    pass
    return 0.0


class _Request:
    __slots__ = ("feeds", "batch", "future", "group_key", "enqueued",
                 "deadline")

    def __init__(self, feeds, batch, group_key, deadline):
        self.feeds = feeds
        self.batch = batch
        self.group_key = group_key
        self.deadline = deadline
        self.future = Future()
        self.enqueued = time.monotonic()


class DynamicBatcher:
    """Micro-batcher over one predictor (a `Predictor` or
    `AotPredictor` — anything with `.run(dict)->list` plus the serving
    introspection quartet: `batch_buckets`, `feed_specs`,
    `batched_feed_names`, `fetch_batched_flags`)."""

    def __init__(self, predictor, max_queue=None, deadline_ms=None,
                 workers=None, metrics=None, max_batch=None):
        self.predictor = predictor
        self.max_queue = int(FLAGS.serving_max_queue
                             if max_queue is None else max_queue)
        self.deadline_s = (FLAGS.serving_batch_deadline_ms
                           if deadline_ms is None else
                           float(deadline_ms)) / 1000.0
        self.metrics = metrics
        self.buckets = tuple(predictor.batch_buckets())
        if max_batch is not None:
            self.max_batch = int(max_batch)
        elif self.buckets:
            self.max_batch = self.buckets[-1]
        else:
            self.max_batch = 64  # unbucketed predictor: a sane coalesce cap
        self._batched_feeds = frozenset(predictor.batched_feed_names())
        self._fetch_flags = predictor.fetch_batched_flags()
        self._cv = threading.Condition()
        self._pending = collections.deque()
        self._inflight = 0
        self._closing = False
        self._stopped = False
        if metrics is not None:
            metrics.queue_depth_fn = lambda: len(self._pending)
        n_workers = int(FLAGS.serving_workers if workers is None
                        else workers)
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name="paddle-tpu-serving-batcher-%d" % i)
            for i in range(max(n_workers, 1))]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    # submit side
    # ------------------------------------------------------------------

    def _build_request(self, feeds, deadline):
        named = {k: np.asarray(v) for k, v in feeds.items()}
        batch = None
        key_parts = []
        for name in sorted(named):
            arr = named[name]
            if name in self._batched_feeds and arr.ndim >= 1:
                b = arr.shape[0]
                if batch is None:
                    batch = b
                elif b != batch:
                    raise ValueError(
                        "inconsistent request batch: feed %r has leading "
                        "dim %d, another batch-major feed has %d"
                        % (name, b, batch))
                key_parts.append((name, arr.shape[1:], str(arr.dtype)))
            else:
                # side feeds must be byte-identical to share a dispatch
                key_parts.append((name, arr.shape, str(arr.dtype),
                                  binascii.crc32(
                                      np.ascontiguousarray(arr).tobytes())))
        if batch is not None and batch > self.max_batch:
            raise ValueError(
                "request batch %d exceeds the largest servable bucket %d "
                "(buckets %s) — split the request"
                % (batch, self.max_batch, self.buckets or "(none)"))
        return _Request(named, batch, tuple(key_parts), deadline)

    def submit(self, feeds, deadline=None):
        """Enqueue one request (dict name->array).  Returns a Future
        resolving to the fetch list (this request's rows only).
        `deadline` is an absolute time.monotonic() instant or None.
        Raises ServerOverloaded / BatcherClosed / ValueError
        synchronously — admission decisions are immediate."""
        req = self._build_request(feeds, deadline)
        with self._cv:
            if self._closing:
                raise BatcherClosed("model batcher is draining/retired")
            if len(self._pending) >= self.max_queue:
                if self.metrics is not None:
                    self.metrics.shed.add()
                raise ServerOverloaded(
                    "request queue full (%d waiting, max_queue=%d) — "
                    "request shed; back off and retry"
                    % (len(self._pending), self.max_queue))
            self._pending.append(req)
            if self.metrics is not None:
                self.metrics.requests.add()
            self._cv.notify()
        return req.future

    def queue_depth(self):
        return len(self._pending)

    # ------------------------------------------------------------------
    # dispatch side
    # ------------------------------------------------------------------

    def _bucket_cap(self, total):
        for cap in self.buckets:
            if total <= cap:
                return cap
        return total

    def _take_group(self):
        """Pop the head request plus every compatible queued request up
        to the largest bucket, waiting up to the coalescing deadline for
        stragglers.  Returns None only at shutdown."""
        with self._cv:
            while not self._pending:
                if self._stopped or self._closing:
                    return None
                self._cv.wait(0.1)
            head = self._pending.popleft()
            group = [head]
            if head.batch is None:
                # no batch-major feed: nothing to coalesce on
                self._inflight += 1
                return group
            total = head.batch
            window = time.monotonic() + self.deadline_s
            while total < self.max_batch:
                took = False
                for i, r in enumerate(self._pending):
                    if r.group_key == head.group_key and \
                            total + r.batch <= self.max_batch:
                        del self._pending[i]
                        group.append(r)
                        total += r.batch
                        took = True
                        break
                if took:
                    continue
                if self._pending:
                    # only incompatible (or non-fitting) requests wait —
                    # dispatch now rather than head-of-line block them
                    break
                remaining = window - time.monotonic()
                if remaining <= 0 or self._stopped or self._closing:
                    break
                self._cv.wait(min(remaining, 0.05))
            self._inflight += 1
            return group

    def _merge_feeds(self, group):
        first = group[0]
        if len(group) == 1:
            return dict(first.feeds)
        merged = {}
        for name, arr in first.feeds.items():
            if name in self._batched_feeds and arr.ndim >= 1:
                merged[name] = np.concatenate(
                    [r.feeds[name] for r in group], axis=0)
            else:
                merged[name] = arr  # group key proved byte-equality
        return merged

    def _scatter(self, group, fetches, total):
        flags = self._fetch_flags
        offset = 0
        now = time.monotonic()
        for r in group:
            outs = []
            for i, a in enumerate(fetches):
                if flags is not None:
                    batched = i < len(flags) and flags[i]
                else:  # pre-marker AOT artifact: shape heuristic
                    batched = a.ndim >= 1 and a.shape[0] == total
                if batched and r.batch is not None:
                    outs.append(a[offset:offset + r.batch])
                else:
                    outs.append(a)
            offset += r.batch or 0
            if not r.future.set_running_or_notify_cancel():
                continue  # caller cancelled while queued
            r.future.set_result(outs)
            if self.metrics is not None:
                self.metrics.note_completion(
                    latency_ms=(now - r.enqueued) * 1000.0)

    def _dispatch(self, group):
        delay = _chaos_delay()
        if delay:
            time.sleep(delay)
        now = time.monotonic()
        live = []
        for r in group:
            if r.deadline is not None and now > r.deadline:
                if self.metrics is not None:
                    self.metrics.deadline_expired.add()
                    self.metrics.errors.add()
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExceeded(
                        "deadline passed after %.1f ms in queue"
                        % ((now - r.enqueued) * 1000.0)))
            else:
                live.append(r)
        if not live:
            return
        feeds = self._merge_feeds(live)
        total = sum(r.batch or 0 for r in live)
        fetches = self.predictor.run(feeds)
        if self.metrics is not None:
            cap = self._bucket_cap(total) if total else 0
            self.metrics.note_dispatch(
                n_requests=len(live), real_rows=total,
                padded_rows=max(cap - total, 0))
        self._scatter(live, fetches, total)

    def _worker(self):
        while True:
            group = self._take_group()
            if group is None:
                return
            try:
                self._dispatch(group)
            except BaseException as e:
                for r in group:
                    if not r.future.done() and \
                            r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
                if self.metrics is not None:
                    self.metrics.errors.add(len(group))
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def drain(self, timeout=None):
        """Block until every queued and in-flight request has resolved."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._cv.notify_all()
            while self._pending or self._inflight:
                rem = None if deadline is None else \
                    max(deadline - time.monotonic(), 0.0)
                if rem == 0.0:
                    raise TimeoutError(
                        "batcher still has %d queued + %d in-flight "
                        "requests after %.1fs"
                        % (len(self._pending), self._inflight, timeout))
                self._cv.wait(0.05 if rem is None else min(rem, 0.05))

    def close(self, drain=True, timeout=30.0):
        """Stop accepting; optionally finish everything queued first
        (the graceful-drain half of a hot swap or shutdown), then stop
        the workers.  With drain=False, queued requests fail with
        BatcherClosed."""
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        if drain:
            self.drain(timeout)
        with self._cv:
            self._stopped = True
            leftovers = list(self._pending)
            self._pending.clear()
            self._cv.notify_all()
        for r in leftovers:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    BatcherClosed("server shut down before dispatch"))
            if self.metrics is not None:
                self.metrics.errors.add()
        for t in self._threads:
            t.join(timeout=5.0)
