"""Cross-request dynamic micro-batcher with per-replica dispatch lanes.

The serving front's core mechanism: many callers each submit a small
(often batch-1) request; TPU executables want the biggest batch bucket
they were compiled for (the MLPerf TPU-pod study's lesson — batch
geometry IS the utilization lever).  The batcher closes that gap by
coalescing waiting requests into one padded bucket dispatch, and — the
multi-chip half — fans the coalesced groups out across N device-placed
model replicas so all chips on the host serve one model name:

  * bounded queue per model (admission control: a submit past
    `max_queue` is shed with `ServerOverloaded`, never parked on an
    unbounded backlog — shed-not-hang). Requests carry a `priority`
    class: under overload the queue sheds lowest-priority-first (an
    arriving request evicts the lowest strictly-lower-priority queued
    request rather than being refused), and the ServerOverloaded a shed
    request receives names the priority class that was dropped;
  * a router thread takes the head request, greedily pulls compatible
    queued requests until the largest bucket is full or a
    `FLAGS.serving_batch_deadline_ms` window expires, then hands the
    group to the LEAST-LOADED replica lane (fewest in-flight batches,
    then shortest lane queue) — the replica-per-accelerator pattern of
    the Clipper/TF-Serving lineage, with the reference
    ParallelExecutor's shape (one program, N device-resident copies,
    work fanned out by the runtime) applied to serving;
  * each lane is a bounded deque in front of one replica predictor plus
    its own dispatch worker(s), so two replicas can be mid-`dispatch`
    concurrently — the PR 4 pipeline lesson (keep the device busy,
    drain asynchronously) turned into cross-chip parallelism. When
    every lane is full the router holds the group (sticky back-
    pressure): the admission queue fills and new submits shed, so
    overload still sheds at the front instead of hiding in per-lane
    backlogs;
  * batch-major feeds (the program-var -1 leading-dim markers the AOT
    meta records and the live Predictor exposes the same way) are
    concatenated; fixed-shape side feeds must be byte-identical to
    coalesce and ride through whole;
  * the underlying predictor pads the merged batch up to its bucket and
    un-pads batch-major fetches (that parity is the predictor's existing
    contract and holds identically on every replica — replies are
    bit-exact vs a direct Predictor.run regardless of which lane served
    them); the lane worker scatters per-request row slices back to each
    caller's Future.

Compatibility grouping: requests only coalesce when their feed names,
trailing shapes, dtypes, and side-feed bytes agree — everything else
dispatches as its own group, correct but uncoalesced.

Chaos: `set_dispatch_delay(secs)` (or env
`PADDLE_TPU_SERVING_CHAOS="dispatch_delay=<secs>"`) injects a slow-worker
stall inside every lane's dispatch — the overload scenarios in
tools/chaos.py and tests/test_serving.py drive admission control with
it, and tools/bench_serving.py reuses it as the deterministic per-
dispatch device-cost stand-in for the replica-scaling lanes.
"""

import binascii
import collections
import os
import queue as queue_mod
import threading
import time
from concurrent.futures import Future

import numpy as np

from ..flags import FLAGS
from ..obs import events as obs_events
from ..obs import tracing as obs_tracing
from ..parallel.mesh import MeshMemberLost

__all__ = ["DynamicBatcher", "DecodeBatcher", "DecodeStream",
           "ServerOverloaded", "DeadlineExceeded", "BatcherClosed",
           "set_dispatch_delay", "set_draft_delay", "set_host_delay"]

_CHAOS_ENV = "PADDLE_TPU_SERVING_CHAOS"


class ServerOverloaded(RuntimeError):
    """Admission control shed: the model's request queue is full.
    Explicit and immediate — the client can back off and retry
    (utils/retry.py jitter) instead of waiting on a hidden backlog.
    `priority` names the class of the request that was shed (the
    arriving one, or a lower-priority queued request it evicted)."""

    def __init__(self, message, priority=None):
        super().__init__(message)
        self.priority = priority


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before its dispatch completed."""


class BatcherClosed(RuntimeError):
    """Submit on a draining/retired batcher (e.g. mid hot-swap retire)."""


_dispatch_delay = 0.0


def set_dispatch_delay(secs):
    """Chaos hook: every subsequent dispatch sleeps `secs` first —
    the in-process slow-worker fault (0 clears).  The sleep happens in
    the lane worker thread with the GIL released, so concurrent
    replica lanes overlap their stalls — which is also what makes it
    the deterministic stand-in for per-dispatch device time in
    bench_serving's replica-scaling lanes."""
    global _dispatch_delay
    _dispatch_delay = float(secs)


def _chaos_delay(key="dispatch_delay", direct=None):
    if direct is None:
        direct = _dispatch_delay
    if direct:
        return direct
    spec = os.environ.get(_CHAOS_ENV)
    if spec:
        for part in spec.split(","):
            name, _, val = part.partition("=")
            if name.strip() == key:
                try:
                    return float(val)
                except ValueError:
                    pass
    return 0.0


_draft_delay = 0.0


def set_draft_delay(secs):
    """Per-DRAFT-step stand-in cost for speculative decode lanes (the
    companion of set_dispatch_delay, which prices the target/verify
    step): every draft decode step sleeps `secs` first, GIL released.
    bench_serving --draft_cost_ms rides this — with the int8 twin as
    the draft, ~0.3x the target step cost is the honest BENCH_r11
    weight-bytes ratio (0 clears)."""
    global _draft_delay
    _draft_delay = float(secs)


def _draft_chaos_delay():
    return _chaos_delay(key="draft_delay", direct=_draft_delay)


_host_delay = 0.0


def set_host_delay(secs):
    """Per-DISPATCH host-side cost stand-in (SERVING.md "Fused
    multi-step decode"): every decode dispatch sleeps `secs` once
    before launching, GIL released — the deterministic model of the
    host round-trip (Python scheduling + launch + sync) that fused
    decode amortizes.  At N=1 a stream pays host+step per token; at
    fuse_steps=N it pays host once per N tokens — bench_serving
    --host_cost_ms rides this to show the dispatch-amortization win
    at real step costs (0 clears)."""
    global _host_delay
    _host_delay = float(secs)


def _host_chaos_delay():
    return _chaos_delay(key="host_cost", direct=_host_delay)


def _predictor_device_label(predictor):
    from ..inference.predictor import _device_label
    return _device_label(getattr(predictor, "device", None))


def _guarded(fn, model_name_fn, thread_kind):
    """Wrap a batcher thread main: an exception escaping the loop is a
    dead router/lane — a request-eating wedge that used to die silently
    as a daemon thread.  Now it lands a `server_thread_death` event and
    arms the flight recorder (obs/flightrec.py) before re-raising, so
    the post-mortem bundle holds the stack that killed it."""
    def _run(*args):
        try:
            fn(*args)
        except BaseException as e:
            name = threading.current_thread().name
            obs_events.emit("server_thread_death",
                            model=model_name_fn(), thread=name,
                            thread_kind=thread_kind,
                            error="%s: %s" % (type(e).__name__, e))
            from ..obs import flightrec
            flightrec.trigger("thread_death", thread=name,
                              thread_kind=thread_kind,
                              model=model_name_fn() or "",
                              error="%s: %s" % (type(e).__name__, e))
            raise
    return _run


class _Request:
    __slots__ = ("feeds", "batch", "future", "group_key", "enqueued",
                 "deadline", "priority", "trace_id", "t_taken",
                 "t_grouped")

    def __init__(self, feeds, batch, group_key, deadline, priority,
                 trace_id=None):
        self.feeds = feeds
        self.batch = batch
        self.group_key = group_key
        self.deadline = deadline
        self.priority = priority
        self.future = Future()
        self.enqueued = time.monotonic()
        # observability (OBSERVABILITY.md): the request's trace id plus
        # the monotonic stamps the stage spans are cut from — contiguous
        # by construction, so queue_wait + coalesce + lane_wait +
        # dispatch + compute + scatter sums to the root span exactly
        self.trace_id = trace_id or obs_tracing.new_trace_id()
        self.t_taken = None     # router popped/pulled it off the queue
        self.t_grouped = None   # its dispatch group closed coalescing


class _Lane:
    """One replica's execution lane: a bounded ready deque feeding this
    replica's dispatch worker(s), plus the load counters the router's
    least-loaded choice reads (in-flight batches first, then queue
    length)."""

    __slots__ = ("index", "predictor", "device", "ready", "inflight",
                 "batches", "rows", "last_t", "dead", "tp",
                 "disp_ewma")

    def __init__(self, index, predictor):
        self.index = index
        self.predictor = predictor
        self.device = _predictor_device_label(predictor)
        self.ready = collections.deque()
        self.inflight = 0   # groups a worker is currently dispatching
        self.batches = 0    # micro-batches this replica executed
        self.rows = 0       # real rows it served
        self.last_t = None  # monotonic end of this lane's last dispatch
        # tensor-parallel lane (SERVING.md "Tensor-parallel compute"):
        # the replica runs the partitioned program, so dispatch time
        # tracks per-member (~1/mesh) HBM traffic, not the whole model
        self.tp = bool(getattr(predictor, "tp_active", False))
        self.disp_ewma = None  # EWMA seconds per dispatch (run only)
        # set to the error string when a mesh member died under this
        # lane (SERVING.md "Mesh replicas"): the router skips it, its
        # workers exit, sibling lanes keep serving
        self.dead = None

    def load(self):
        return (self.inflight, len(self.ready), self.index)

    @property
    def mesh(self):
        """Members behind this lane: 1 for a plain device, N for a
        mesh-group replica ('a+b' device label)."""
        return self.device.count("+") + 1 if self.device else 1


class DynamicBatcher:
    """Micro-batcher over one or more replica predictors (each a
    `Predictor` or `AotPredictor` — anything with `.run(dict)->list`
    plus the serving introspection quartet: `batch_buckets`,
    `feed_specs`, `batched_feed_names`, `fetch_batched_flags`).

    `replicas`: optional list of device-placed predictors sharing one
    model's weights (the registry builds them via `clone_to`); the
    batcher runs one execution lane per replica and routes each
    coalesced group to the least-loaded lane.  Without it, the single
    `predictor` forms the only lane — the pre-multichip behavior."""

    def __init__(self, predictor, max_queue=None, deadline_ms=None,
                 workers=None, metrics=None, max_batch=None,
                 replicas=None, lane_depth=None):
        preds = list(replicas) if replicas else [predictor]
        self.predictor = predictor if predictor is not None else preds[0]
        self.max_queue = int(FLAGS.serving_max_queue
                             if max_queue is None else max_queue)
        self.deadline_s = (FLAGS.serving_batch_deadline_ms
                           if deadline_ms is None else
                           float(deadline_ms)) / 1000.0
        self.lane_depth = max(int(FLAGS.serving_lane_depth
                                  if lane_depth is None else lane_depth),
                              1)
        self.metrics = metrics
        self.buckets = tuple(self.predictor.batch_buckets())
        if max_batch is not None:
            self.max_batch = int(max_batch)
        elif self.buckets:
            self.max_batch = self.buckets[-1]
        else:
            self.max_batch = 64  # unbucketed predictor: a sane coalesce cap
        self._batched_feeds = frozenset(
            self.predictor.batched_feed_names())
        self._fetch_flags = self.predictor.fetch_batched_flags()
        self._cv = threading.Condition()
        self._pending = collections.deque()
        self._lanes = [_Lane(i, p) for i, p in enumerate(preds)]
        self._carrying = False  # router holds a taken-but-unrouted group
        self._closing = False
        self._stopped = False
        if metrics is not None:
            metrics.queue_depth_fn = lambda: len(self._pending)
            metrics.replica_stats_fn = self.replica_stats
        n_workers = max(int(FLAGS.serving_workers if workers is None
                            else workers), 1)
        self._router = threading.Thread(
            target=_guarded(self._route, lambda: self._model_name,
                            "router"),
            daemon=True, name="paddle-tpu-serving-router")
        self._lane_threads = {lane.index: [] for lane in self._lanes}
        self._threads = []
        for lane in self._lanes:
            for i in range(n_workers):
                t = threading.Thread(
                    target=_guarded(self._worker,
                                    lambda: self._model_name, "lane"),
                    args=(lane,), daemon=True,
                    name="paddle-tpu-serving-lane%d-%d"
                         % (lane.index, i))
                self._threads.append(t)
                self._lane_threads[lane.index].append(t)
        self._router.start()
        for t in self._threads:
            t.start()

    @property
    def num_replicas(self):
        return len(self._lanes)

    # ------------------------------------------------------------------
    # submit side (admission control)
    # ------------------------------------------------------------------

    @property
    def _model_name(self):
        return self.metrics.name if self.metrics is not None else None

    def _build_request(self, feeds, deadline, priority, trace_id=None):
        named = {k: np.asarray(v) for k, v in feeds.items()}
        batch = None
        key_parts = []
        for name in sorted(named):
            arr = named[name]
            if name in self._batched_feeds and arr.ndim >= 1:
                b = arr.shape[0]
                if batch is None:
                    batch = b
                elif b != batch:
                    raise ValueError(
                        "inconsistent request batch: feed %r has leading "
                        "dim %d, another batch-major feed has %d"
                        % (name, b, batch))
                key_parts.append((name, arr.shape[1:], str(arr.dtype)))
            else:
                # side feeds must be byte-identical to share a dispatch
                key_parts.append((name, arr.shape, str(arr.dtype),
                                  binascii.crc32(
                                      np.ascontiguousarray(arr).tobytes())))
        if batch is not None and batch > self.max_batch:
            raise ValueError(
                "request batch %d exceeds the largest servable bucket %d "
                "(buckets %s) — split the request"
                % (batch, self.max_batch, self.buckets or "(none)"))
        return _Request(named, batch, tuple(key_parts), deadline,
                        int(priority), trace_id=trace_id)

    def submit(self, feeds, deadline=None, priority=0, trace_id=None):
        """Enqueue one request (dict name->array).  Returns a Future
        resolving to the fetch list (this request's rows only).
        `deadline` is an absolute time.monotonic() instant or None.
        `priority`: larger = more important; under overload the queue
        sheds lowest-priority-first.  Raises ServerOverloaded /
        BatcherClosed / ValueError synchronously — admission decisions
        are immediate.  `trace_id` carries a caller-minted id (the wire
        `"trace_id"` field); one is minted here otherwise, and the
        returned future exposes it (plus the server-measured stage
        timings) as ``future.obs_info`` once resolved."""
        req = self._build_request(feeds, deadline, priority,
                                  trace_id=trace_id)
        evicted = None
        with self._cv:
            if self._closing:
                raise BatcherClosed("model batcher is draining/retired")
            if len(self._pending) >= self.max_queue:
                # priority shed: evict the lowest strictly-lower-priority
                # queued request (earliest such) in favor of this one;
                # with no lower class queued, the arrival itself sheds
                victim = None
                for r in self._pending:
                    if r.priority < req.priority and \
                            (victim is None
                             or r.priority < victim.priority):
                        victim = r
                if victim is None:
                    if self.metrics is not None:
                        self.metrics.note_shed(priority=req.priority)
                    # a shed happens BEFORE lane routing, so no replica
                    # owns it; the lane-occupancy context says whether
                    # the lanes were saturated or just the queue
                    obs_events.emit("shed", model=self._model_name,
                                    priority=req.priority,
                                    trace_id=req.trace_id,
                                    queue=len(self._pending),
                                    inflight=self._inflight_total())
                    raise ServerOverloaded(
                        "request queue full (%d waiting, max_queue=%d) — "
                        "priority-%d request shed; back off and retry"
                        % (len(self._pending), self.max_queue,
                           req.priority),
                        priority=req.priority)
                self._pending.remove(victim)
                evicted = victim
            self._pending.append(req)
            if self.metrics is not None:
                self.metrics.requests.add()
            # notify_all, not notify: the router AND the lane workers
            # share this condition — a single notify could wake a lane
            # worker (predicate false) and leave the router sleeping
            # out its 0.1s poll, which the new queue_wait span exposed
            # as a ~100ms floor on idle-server latency
            self._cv.notify_all()
        req.future.trace_id = req.trace_id
        if evicted is not None:
            if self.metrics is not None:
                self.metrics.note_shed(priority=evicted.priority)
            obs_events.emit("shed", model=self._model_name,
                            priority=evicted.priority,
                            trace_id=evicted.trace_id, evicted=True,
                            by_priority=req.priority,
                            inflight=self._inflight_total())
            if evicted.future.set_running_or_notify_cancel():
                evicted.future.set_exception(ServerOverloaded(
                    "priority-%d request shed from a full queue by a "
                    "priority-%d arrival (lowest-priority-first "
                    "overload policy)"
                    % (evicted.priority, req.priority),
                    priority=evicted.priority))
        return req.future

    def queue_depth(self):
        return len(self._pending)

    def replica_stats(self):
        """Per-replica lane snapshot (device id, in-flight batches,
        lane queue depth, batches/rows executed) — the skew-visibility
        numbers `stats` and serving_top surface.  `mesh` is the member
        count behind the lane (1 = plain device); `dead` carries the
        mesh-member-loss error when the lane died; `tp` marks a
        tensor-parallel lane and `dispatch_ms` its EWMA device time
        per dispatch (None until the first one)."""
        with self._cv:
            return [{"replica": l.index, "device": l.device,
                     "mesh": l.mesh, "dead": l.dead, "tp": l.tp,
                     "dispatch_ms": round(l.disp_ewma * 1000.0, 3)
                     if l.disp_ewma is not None else None,
                     "inflight": l.inflight, "queue": len(l.ready),
                     "batches": l.batches, "rows": l.rows}
                    for l in self._lanes]

    def lane_liveness(self):
        """Thread-level health of this batcher (the `health` RPC verb's
        per-model section): is the router alive, is each lane's worker
        set alive, and how long since each lane last finished a
        dispatch (None = never dispatched yet)."""
        now = time.monotonic()
        with self._cv:
            lanes = []
            for l in self._lanes:
                threads = self._lane_threads.get(l.index, [])
                lanes.append({
                    "replica": l.index, "device": l.device,
                    "mesh": l.mesh, "dead": l.dead,
                    "alive": sum(1 for t in threads if t.is_alive()),
                    "workers": len(threads),
                    "inflight": l.inflight, "queue": len(l.ready),
                    "last_dispatch_age_s":
                        round(now - l.last_t, 3)
                        if l.last_t is not None else None})
            return {"kind": "batch",
                    "router_alive": self._router.is_alive(),
                    "queue_depth": len(self._pending),
                    "closing": self._closing, "lanes": lanes}

    def _inflight_total(self):
        return sum(l.inflight + len(l.ready) for l in self._lanes)

    # ------------------------------------------------------------------
    # coalescing front-end + least-loaded router
    # ------------------------------------------------------------------

    def _bucket_cap(self, total):
        for cap in self.buckets:
            if total <= cap:
                return cap
        return total

    def _take_group(self):
        """Pop the head request plus every compatible queued request up
        to the largest bucket, waiting up to the coalescing deadline for
        stragglers.  Returns None only at shutdown.  Marks the router as
        carrying the group so drain() sees it between queue and lane."""
        with self._cv:
            while not self._pending:
                if self._stopped or self._closing:
                    return None
                self._cv.wait(0.1)
            head = self._pending.popleft()
            head.t_taken = time.monotonic()
            group = [head]
            if head.batch is None:
                # no batch-major feed: nothing to coalesce on
                self._carrying = True
                return group
            total = head.batch
            window = time.monotonic() + self.deadline_s
            while total < self.max_batch:
                took = False
                for i, r in enumerate(self._pending):
                    if r.group_key == head.group_key and \
                            total + r.batch <= self.max_batch:
                        del self._pending[i]
                        r.t_taken = time.monotonic()
                        group.append(r)
                        total += r.batch
                        took = True
                        break
                if took:
                    continue
                if self._pending:
                    # only incompatible (or non-fitting) requests wait —
                    # dispatch now rather than head-of-line block them
                    break
                remaining = window - time.monotonic()
                if remaining <= 0 or self._stopped or self._closing:
                    break
                self._cv.wait(min(remaining, 0.05))
            self._carrying = True
            return group

    def _assign(self, group):
        """Hand `group` to the least-loaded LIVE lane: fewest in-flight
        batches, then shortest lane queue, then lowest index.  When
        every lane's queue is at `lane_depth` the router WAITS here
        (sticky back-pressure) — the admission queue upstream fills and
        sheds, rather than any lane queue growing unboundedly.  Lanes
        killed by mesh-member loss are skipped; with EVERY lane dead
        the group fails typed (MeshMemberLost) instead of parking
        forever.  Returns False only on hard stop (group unrouted)."""
        while True:
            with self._cv:
                if self._stopped:
                    self._carrying = False
                    return False
                live = [l for l in self._lanes if l.dead is None]
                if live:
                    lane = min(live, key=_Lane.load)
                    if len(lane.ready) < self.lane_depth:
                        lane.ready.append(group)
                        self._carrying = False
                        self._cv.notify_all()
                        return True
                    self._cv.wait(0.05)
                    continue
                dead_msg = self._lanes[0].dead
                self._carrying = False
                self._cv.notify_all()
            err = MeshMemberLost(
                "every replica lane is dead (%s)" % dead_msg)
            for r in group:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(err)
            if self.metrics is not None:
                self.metrics.errors.add(len(group))
            return True

    def _route(self):
        while True:
            group = self._take_group()
            if group is None:
                return
            t_grouped = time.monotonic()
            for r in group:
                r.t_grouped = t_grouped
            if not self._assign(group):
                # hard stop with a group in hand: fail it explicitly
                for r in group:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(BatcherClosed(
                            "server shut down before dispatch"))
                    if self.metrics is not None:
                        self.metrics.errors.add()
                return

    # ------------------------------------------------------------------
    # lane dispatch side
    # ------------------------------------------------------------------

    def _merge_feeds(self, group):
        first = group[0]
        if len(group) == 1:
            return dict(first.feeds)
        merged = {}
        for name, arr in first.feeds.items():
            if name in self._batched_feeds and arr.ndim >= 1:
                merged[name] = np.concatenate(
                    [r.feeds[name] for r in group], axis=0)
            else:
                merged[name] = arr  # group key proved byte-equality
        return merged

    def _emit_request_spans(self, r, lane, t_start, t_run, t_run_end,
                            now, n_live, total):
        """Land one request's stage span set in the tracing ring.  The
        stamps are contiguous monotonic instants, so the stages tile the
        root `serving/request` span exactly: a p99 outlier decomposes
        into WHICH stage ate the time (OBSERVABILITY.md).  Wall-clock
        `ts` per span is reconstructed from one time.time() anchor."""
        wall_now = time.time()
        model = self._model_name
        tid = r.trace_id
        t_taken = r.t_taken if r.t_taken is not None else t_start
        t_grouped = r.t_grouped if r.t_grouped is not None else t_start

        def _mk(name, t0, t1, **attrs):
            if t1 < t0:
                t1 = t0
            a = {"model": model} if model else {}
            a.update(attrs)
            obs_tracing.add_span(obs_tracing.Span(
                name, kind="serving", trace_id=tid,
                ts=wall_now - (now - t0), dur_ms=(t1 - t0) * 1e3,
                attrs=a))

        _mk("serving/queue_wait", r.enqueued, t_taken)
        _mk("serving/coalesce", t_taken, t_grouped)
        _mk("serving/lane_wait", t_grouped, t_start, replica=lane.index)
        _mk("serving/dispatch", t_start, t_run, replica=lane.index)
        _mk("serving/compute", t_run, t_run_end, replica=lane.index,
            rows=total, batch_fill=n_live)
        _mk("serving/scatter", t_run_end, now)
        _mk("serving/request", r.enqueued, now, replica=lane.index,
            batch=r.batch or 0, batch_fill=n_live, priority=r.priority)

    def _scatter(self, group, fetches, total, lane, t_start, t_run,
                 t_run_end):
        flags = self._fetch_flags
        offset = 0
        now = time.monotonic()
        traced = obs_tracing.enabled()
        try:
            slow_ms = float(FLAGS.trace_slow_ms)
        except Exception:
            slow_ms = 0.0
        for r in group:
            outs = []
            for i, a in enumerate(fetches):
                if flags is not None:
                    batched = i < len(flags) and flags[i]
                else:  # pre-marker AOT artifact: shape heuristic
                    batched = a.ndim >= 1 and a.shape[0] == total
                if batched and r.batch is not None:
                    outs.append(a[offset:offset + r.batch])
                else:
                    outs.append(a)
            offset += r.batch or 0
            total_ms = (now - r.enqueued) * 1000.0
            queue_wait_ms = ((r.t_taken if r.t_taken is not None else now)
                             - r.enqueued) * 1000.0
            if traced:
                self._emit_request_spans(r, lane, t_start, t_run,
                                         t_run_end, now, len(group),
                                         total)
            if slow_ms and total_ms >= slow_ms:
                # the slow-request log: findable after the ring
                # wrapped, attributed to the lane that served it so
                # per-replica triage works from the event log alone
                obs_events.emit("slow", model=self._model_name,
                                trace_id=r.trace_id,
                                replica=lane.index, device=lane.device,
                                total_ms=round(total_ms, 3),
                                queue_wait_ms=round(queue_wait_ms, 3),
                                compute_ms=round(
                                    (t_run_end - t_run) * 1e3, 3))
            if not r.future.set_running_or_notify_cancel():
                continue  # caller cancelled while queued
            # server-measured latency attribution, readable by the
            # caller (ServingClient debug replies) without server access
            r.future.obs_info = {
                "trace_id": r.trace_id,
                "queue_wait_ms": round(queue_wait_ms, 3),
                "coalesce_ms": round(
                    ((r.t_grouped or now) -
                     (r.t_taken if r.t_taken is not None else now))
                    * 1e3, 3),
                "lane_wait_ms": round(
                    (t_start - (r.t_grouped or t_start)) * 1e3, 3),
                "compute_ms": round((t_run_end - t_run) * 1e3, 3),
                "server_ms": round(total_ms, 3),
                "batch_fill": len(group),
                "batch_rows": total,
                "replica": lane.index,
            }
            r.future.set_result(outs)
            if self.metrics is not None:
                self.metrics.note_completion(
                    latency_ms=total_ms, queue_wait_ms=queue_wait_ms)

    def _dispatch(self, group, lane):
        t_start = time.monotonic()
        delay = _chaos_delay()
        if delay:
            time.sleep(delay)
        now = time.monotonic()
        live = []
        for r in group:
            if r.deadline is not None and now > r.deadline:
                if self.metrics is not None:
                    self.metrics.deadline_expired.add()
                    self.metrics.errors.add()
                obs_events.emit("deadline_expired",
                                model=self._model_name,
                                trace_id=r.trace_id,
                                replica=lane.index, device=lane.device,
                                waited_ms=round(
                                    (now - r.enqueued) * 1000.0, 3))
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(DeadlineExceeded(
                        "deadline passed after %.1f ms in queue"
                        % ((now - r.enqueued) * 1000.0)))
            else:
                live.append(r)
        if not live:
            return
        feeds = self._merge_feeds(live)
        total = sum(r.batch or 0 for r in live)
        t_run = time.monotonic()
        fetches = lane.predictor.run(feeds)
        t_run_end = time.monotonic()
        with self._cv:
            lane.batches += 1
            lane.rows += total
            lane.last_t = t_run_end
            dt = t_run_end - t_run
            lane.disp_ewma = dt if lane.disp_ewma is None \
                else 0.8 * lane.disp_ewma + 0.2 * dt
        if self.metrics is not None:
            cap = self._bucket_cap(total) if total else 0
            self.metrics.note_dispatch(
                n_requests=len(live), real_rows=total,
                padded_rows=max(cap - total, 0))
        self._scatter(live, fetches, total, lane, t_start, t_run,
                      t_run_end)

    def _lane_dead(self, lane, exc):
        """Mesh-member loss (SERVING.md "Mesh replicas"): the group is
        ONE replica, so the lane dies whole — marked dead (the router
        skips it from here on), its queued groups fail typed, sibling
        lanes keep serving.  Never wedges: a dead lane's workers exit
        cleanly instead of raising through _guarded."""
        with self._cv:
            if lane.dead is not None:
                return
            lane.dead = "%s: %s" % (type(exc).__name__, exc)
            leftovers = []
            while lane.ready:
                leftovers.extend(lane.ready.popleft())
            self._cv.notify_all()
        obs_events.emit("mesh_lane_dead", model=self._model_name,
                        replica=lane.index, device=lane.device,
                        error=str(exc))
        for r in leftovers:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(exc)
            if self.metrics is not None:
                self.metrics.errors.add()

    def _worker(self, lane):
        while True:
            with self._cv:
                while not lane.ready:
                    if self._stopped or lane.dead is not None:
                        return
                    self._cv.wait(0.1)
                group = lane.ready.popleft()
                lane.inflight += 1
                self._cv.notify_all()
            try:
                self._dispatch(group, lane)
            except BaseException as e:
                for r in group:
                    if not r.future.done() and \
                            r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
                if self.metrics is not None:
                    self.metrics.errors.add(len(group))
                if isinstance(e, MeshMemberLost):
                    self._lane_dead(lane, e)
            finally:
                with self._cv:
                    lane.inflight -= 1
                    self._cv.notify_all()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _busy(self):
        return (self._pending or self._carrying
                or any(l.ready or l.inflight for l in self._lanes))

    def drain(self, timeout=None):
        """Block until every queued, routed, and in-flight request has
        resolved — across all replica lanes."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._cv.notify_all()
            while self._busy():
                rem = None if deadline is None else \
                    max(deadline - time.monotonic(), 0.0)
                if rem == 0.0:
                    raise TimeoutError(
                        "batcher still has %d queued + %d lane-queued + "
                        "%d in-flight requests after %.1fs"
                        % (len(self._pending),
                           sum(len(l.ready) for l in self._lanes),
                           sum(l.inflight for l in self._lanes),
                           timeout))
                self._cv.wait(0.05 if rem is None else min(rem, 0.05))

    def close(self, drain=True, timeout=30.0):
        """Stop accepting; optionally finish everything queued first
        (the graceful-drain half of a hot swap or shutdown), then stop
        the router and lane workers.  With drain=False, queued requests
        fail with BatcherClosed."""
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        if drain:
            self.drain(timeout)
        with self._cv:
            self._stopped = True
            leftovers = list(self._pending)
            self._pending.clear()
            for lane in self._lanes:
                while lane.ready:
                    leftovers.extend(lane.ready.popleft())
            self._cv.notify_all()
        for r in leftovers:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(
                    BatcherClosed("server shut down before dispatch"))
            if self.metrics is not None:
                self.metrics.errors.add()
        self._router.join(timeout=5.0)
        for t in self._threads:
            t.join(timeout=5.0)


# ---------------------------------------------------------------------------
# continuous batching for autoregressive decode (SERVING.md "Continuous
# batching & streaming").  The DynamicBatcher above coalesces ONE-SHOT
# requests into one dispatch; generation inverts the shape — each
# request is MANY tiny steps over growing state, so the utilization
# lever is slot occupancy over time, not batch fill per dispatch.  The
# DecodeBatcher keeps one DecodeSession (slot-indexed KV cache,
# inference/decode.py) per replica lane and runs a continuous loop: a
# waiting request joins the RUNNING decode batch the step after any
# slot frees (EOS / max-new-tokens / deadline / client disconnect) —
# never a coalesce window, never waiting for the batch to drain.  The
# decode step is one fixed-shape executable over the whole slot table,
# so XLA compiles it once and every mix of requests reuses it.
# ---------------------------------------------------------------------------


class DecodeStream:
    """The caller's handle on one streaming generation: an event queue
    the owning lane feeds (token chunks, then exactly one terminal
    event), iterable as token-chunk lists.  ``result()`` collects the
    whole stream — the Future-shaped surface the server's one-shot
    `infer` path uses unchanged on decode models."""

    def __init__(self, trace_id, prompt_len, max_new_tokens):
        self.trace_id = trace_id
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.obs_info = None     # stage timing attribution, at finish
        self.finish_reason = None
        self._q = queue_mod.Queue()
        self._tokens = []
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._error = None

    # -- lane side ------------------------------------------------------

    def _put_tokens(self, toks):
        self._tokens.extend(int(t) for t in toks)
        self._q.put(("tokens", [int(t) for t in toks]))

    def _finish(self, reason, obs_info=None):
        self.finish_reason = reason
        self.obs_info = obs_info
        self._done.set()
        self._q.put(("done", reason))

    def _fail(self, exc):
        self._error = exc
        self.finish_reason = "error"
        self._done.set()
        self._q.put(("error", exc))

    # -- caller side ----------------------------------------------------

    def cancel(self):
        """Ask the owning lane to evict this request; the slot is freed
        (and zeroed) within one decode step.  The server's stream
        handler calls this when the client connection dies mid-reply."""
        self._cancel.set()

    def cancelled(self):
        return self._cancel.is_set()

    def done(self):
        return self._done.is_set()

    @property
    def tokens(self):
        """Tokens generated so far (grows while streaming)."""
        return list(self._tokens)

    def events(self, timeout=None):
        """Yield ("tokens", [ints]) chunks then one terminal ("done",
        reason) / ("error", exc) event.  `timeout` bounds the wait for
        EACH event."""
        while True:
            ev = self._q.get(timeout=timeout)
            yield ev
            if ev[0] != "tokens":
                return

    def __iter__(self):
        """Token-chunk iterator; raises the stream's typed error at the
        point of failure."""
        for kind, payload in self.events():
            if kind == "tokens":
                yield payload
            elif kind == "error":
                raise payload

    def result(self, timeout=None):
        """Block to completion; returns the fetch-shaped reply (one
        int32 array of every generated token) or raises the stream's
        typed error — duck-typed as the batcher Future so the registry
        and the one-shot `infer` verb serve decode models unchanged."""
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(
                "decode stream still running after %.1fs (%d tokens)"
                % (timeout or 0.0, len(self._tokens)))
        # drain keeps events() consumers and result() callers equivalent
        if self._error is not None:
            raise self._error
        return [np.asarray(self._tokens, np.int32)]


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "chunk", "deadline", "priority",
                 "trace_id", "stream", "enqueued", "t_admitted",
                 "t_first", "buf", "gen")

    def __init__(self, prompt, max_new, chunk, deadline, priority,
                 trace_id):
        self.prompt = prompt
        self.max_new = int(max_new)
        self.chunk = max(int(chunk), 1)
        self.deadline = deadline
        self.priority = int(priority)
        self.trace_id = trace_id or obs_tracing.new_trace_id()
        self.stream = DecodeStream(self.trace_id, len(prompt), max_new)
        self.enqueued = time.monotonic()
        self.t_admitted = None
        self.t_first = None
        self.buf = []
        self.gen = []


class _DecodeLane:
    """One replica's decode lane: its slot-table session plus the
    slot -> request assignment the continuous loop walks.  With a
    draft replica and spec_k >= 1 the session is a
    SpeculativeDecodeSession — the lane advances slots 1..k+1 tokens
    per round instead of exactly one."""

    __slots__ = ("index", "predictor", "session", "assigned", "steps",
                 "tokens", "spec", "degraded_noted", "last_step_t",
                 "step_ewma", "dead", "tp")

    def __init__(self, index, predictor, n_slots, draft=None, spec_k=0):
        # error string once a mesh member died under this lane
        # (SERVING.md "Mesh replicas"): loop exited, streams failed
        # typed, sibling lanes unaffected
        self.dead = None
        self.last_step_t = None  # monotonic end of the last decode step
        # EWMA seconds per decode STEP (per trip under fusion) — the
        # deadline governor's estimate for clamping fused trip counts
        self.step_ewma = None
        self.index = index
        self.predictor = predictor
        # tensor-parallel lane: decode runs the partitioned program
        # (FLAGS.mesh_tp + a TP-splittable model on a mesh replica)
        self.tp = bool(getattr(predictor, "tp_active", False))
        if draft is not None and int(spec_k) >= 1:
            from ..inference.decode import SpeculativeDecodeSession
            self.session = SpeculativeDecodeSession(
                predictor, draft, n_slots, spec_k)
            self.spec = True
        else:
            self.session = predictor.new_session(n_slots)
            self.spec = False
        self.assigned = {}   # slot -> _DecodeRequest
        self.steps = 0
        self.tokens = 0
        self.degraded_noted = False


class DecodeBatcher:
    """Slot-based continuous batching over one or more replica
    GenerativePredictors.  Admission control matches the DynamicBatcher
    contract (bounded queue, lowest-priority-first shed, shed-not-hang);
    past admission the lifecycle is streaming: prefill into a free slot,
    then ride the lane's running decode loop until EOS / max-new-tokens
    / deadline / cancel frees the slot for the next waiting request.

    ``continuous=False`` is the STATIC-batching baseline the bench
    lanes compare against: a lane only admits when it is idle, takes a
    full batch, and decodes until the LAST member finishes — the
    pre-continuous-batching serving shape (bench_zoo
    serving_decode_static).

    With ``draft_replicas``/``spec_k`` (SERVING.md "Speculative
    decoding") each lane runs a SpeculativeDecodeSession: per round the
    draft proposes k tokens, one batched target verify step scores all
    k+1 positions, and slots advance 1..k+1 committed tokens — the
    per-slot variable-accept bookkeeping below consumes each commit
    list in stream order with per-token EOS/max-new cuts, so the wire
    stream is bit-identical to the one-token-per-step path.  Draft
    failure degrades the lane to target-only decode within one round
    (`spec_degraded` event + counter), never wedging a stream.

    ``fuse_steps`` > 1 (SERVING.md "Fused multi-step decode",
    FLAGS.serving_decode_fuse_steps) runs each lane iteration as ONE
    fused dispatch of up to N decode steps (`DecodeSession.
    decode_fused`): slot joins/leaves/deadline evictions move to the
    N-step window boundary, per-token EOS/max-new cuts still land in
    stream order from the returned token block, and spec lanes fuse
    the whole draft+verify round into one dispatch
    (`SpeculativeDecodeSession.step(fused=True)`).  Streams stay
    bit-identical to N=1 whatever joins or leaves; a per-lane EWMA of
    step time clamps the trip count so no deadline overshoots by more
    than one dispatch (the overshoot lands on the `deadline_expired`
    event)."""

    def __init__(self, predictor, replicas=None, n_slots=None,
                 max_queue=None, metrics=None, max_new_tokens=None,
                 continuous=True, draft=None, draft_replicas=None,
                 spec_k=None, fuse_steps=None):
        preds = list(replicas) if replicas else [predictor]
        self.predictor = predictor if predictor is not None else preds[0]
        self.n_slots = max(int(FLAGS.serving_decode_slots
                               if n_slots is None else n_slots), 1)
        self.max_queue = int(FLAGS.serving_max_queue
                             if max_queue is None else max_queue)
        self.max_new_cap = max(int(FLAGS.serving_max_new_tokens
                                   if max_new_tokens is None
                                   else max_new_tokens), 1)
        self.continuous = bool(continuous)
        self.metrics = metrics
        # fused multi-step decode window (1 = the classic one-dispatch-
        # per-token loop; the default rides the flag so existing
        # servers keep N=1 behavior bit-for-bit)
        self.fuse_steps = max(int(FLAGS.serving_decode_fuse_steps
                                  if fuse_steps is None
                                  else fuse_steps), 1)
        # speculative decoding (SERVING.md): one draft predictor per
        # replica lane (`draft_replicas`, or one shared `draft` for the
        # single-lane shape); spec_k is the draft depth per round
        self.spec_k = int(FLAGS.serving_spec_k if spec_k is None
                          else spec_k)
        drafts = list(draft_replicas) if draft_replicas else (
            [draft] * len(preds) if draft is not None else None)
        if drafts is not None and len(drafts) != len(preds):
            raise ValueError(
                "%d draft replicas for %d target replicas — the spec "
                "lanes pair one draft per target"
                % (len(drafts), len(preds)))
        if not drafts or self.spec_k < 1:
            drafts, self.spec_k = None, 0
        self.draft_replicas = drafts
        self._cv = threading.Condition()
        self._pending = collections.deque()
        self._lanes = [_DecodeLane(i, p, self.n_slots,
                                   draft=(drafts[i] if drafts else None),
                                   spec_k=self.spec_k)
                       for i, p in enumerate(preds)]
        self._closing = False
        self._stopped = False
        if metrics is not None:
            metrics.queue_depth_fn = lambda: len(self._pending)
            metrics.replica_stats_fn = self.replica_stats
            metrics.slot_occupancy_fn = self.slot_occupancy
            metrics.kv_cache_fn = self.kv_cache_info
        self._threads = [
            threading.Thread(
                target=_guarded(self._lane_loop,
                                lambda: self._model_name, "decode-lane"),
                args=(lane,), daemon=True,
                name="paddle-tpu-decode-lane%d" % lane.index)
            for lane in self._lanes]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------

    @property
    def num_replicas(self):
        return len(self._lanes)

    @property
    def _model_name(self):
        return self.metrics.name if self.metrics is not None else None

    def batch_buckets(self):
        return self.predictor.prefill_buckets()

    def queue_depth(self):
        return len(self._pending)

    def slot_occupancy(self):
        """(occupied, total) across every LIVE lane — the occupancy
        gauge (a lane killed by mesh-member loss contributes no
        capacity)."""
        occupied = sum(len(l.assigned) for l in self._lanes)
        live = sum(1 for l in self._lanes if l.dead is None)
        return occupied, self.n_slots * live

    def lane_liveness(self):
        """Thread-level health (the `health` RPC verb): per decode
        lane, is its loop thread alive, how many slots are busy, and
        the age of its last completed decode step — a wedged lane
        reads as a growing last_step_age_s with busy slots."""
        now = time.monotonic()
        with self._cv:
            lanes = []
            for i, l in enumerate(self._lanes):
                t = self._threads[i] if i < len(self._threads) else None
                lanes.append({
                    "replica": l.index,
                    "alive": int(bool(t is not None and t.is_alive())),
                    "workers": 1,
                    "dead": l.dead,
                    "slots_busy": len(l.assigned),
                    "slots": self.n_slots,
                    "steps": l.steps,
                    "last_step_age_s":
                        round(now - l.last_step_t, 3)
                        if l.last_step_t is not None else None})
            return {"kind": "decode", "router_alive": True,
                    "queue_depth": len(self._pending),
                    "closing": self._closing, "lanes": lanes}

    def kv_cache_info(self):
        """(kv_cache_dtype, MEASURED slot-table bytes summed across
        this batcher's lanes) — the stats surface of the quantized-KV
        axis (QUANTIZE.md "Quantized KV cache"); bench_serving's
        --kv_dtype A/B reads the measured number against the static
        closed form."""
        dtype = str(getattr(self.predictor, "kv_cache_dtype",
                            "float32"))
        total = 0
        for lane in self._lanes:
            # a speculative lane wraps the target session; its cache
            # is the one the committed stream lives in
            sess = getattr(lane.session, "session", lane.session)
            cb = getattr(sess, "cache_bytes", None)
            if cb is not None:
                total += int(cb())
        return dtype, total

    def _slots_busy_total(self):
        return sum(len(l.assigned) for l in self._lanes)

    def replica_stats(self):
        with self._cv:
            out = []
            for l in self._lanes:
                from ..inference.predictor import _device_label
                dev = _device_label(getattr(l.predictor, "device",
                                            None))
                out.append({"replica": l.index,
                            "device": dev,
                            "mesh": dev.count("+") + 1 if dev else 1,
                            "dead": l.dead,
                            "tp": l.tp,
                            "dispatch_ms":
                                round(l.step_ewma * 1000.0, 3)
                                if l.step_ewma is not None else None,
                            "inflight": len(l.assigned),
                            "queue": 0,
                            "batches": l.steps,
                            "rows": l.tokens})
            return out

    # ------------------------------------------------------------------
    # submit side: the same admission-control contract as DynamicBatcher
    # ------------------------------------------------------------------

    def submit(self, tokens, max_new_tokens=None, deadline=None,
               priority=0, trace_id=None, chunk_tokens=None):
        """Enqueue one generation request.  Returns a DecodeStream.
        `max_new_tokens` is clamped to the server-side cap; `deadline`
        is an absolute time.monotonic() instant covering queue wait,
        prefill AND in-decode time — a streaming request past it is
        evicted from its slot mid-generation (the PR 8 deadline fix)."""
        prompt = np.asarray(tokens, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        # reject unservable prompts synchronously (admission decisions
        # are immediate); also guarantees >= 1 generated token fits
        self.predictor.prompt_bucket(int(prompt.size))
        if prompt.size >= self.predictor.max_seq_len:
            raise ValueError(
                "prompt of %d tokens leaves no cache room to generate "
                "(max_seq_len %d)" % (prompt.size,
                                      self.predictor.max_seq_len))
        max_new = self.max_new_cap if max_new_tokens is None else \
            max(min(int(max_new_tokens), self.max_new_cap), 1)
        chunk = int(FLAGS.serving_stream_chunk_tokens
                    if chunk_tokens is None else chunk_tokens)
        req = _DecodeRequest(list(int(t) for t in prompt), max_new,
                             chunk, deadline, priority, trace_id)
        evicted = None
        with self._cv:
            if self._closing:
                raise BatcherClosed("model batcher is draining/retired")
            dead = [l.dead for l in self._lanes if l.dead is not None]
            if len(dead) == len(self._lanes):
                # every lane lost a mesh member: fail typed at
                # admission — nothing is left to ever serve this queue
                raise MeshMemberLost(
                    "every replica lane is dead (%s)" % dead[0])
            if len(self._pending) >= self.max_queue:
                victim = None
                for r in self._pending:
                    if r.priority < req.priority and \
                            (victim is None
                             or r.priority < victim.priority):
                        victim = r
                if victim is None:
                    if self.metrics is not None:
                        self.metrics.note_shed(priority=req.priority)
                    obs_events.emit("shed", model=self._model_name,
                                    priority=req.priority,
                                    trace_id=req.trace_id,
                                    queue=len(self._pending),
                                    slots_busy=self._slots_busy_total())
                    raise ServerOverloaded(
                        "decode queue full (%d waiting, max_queue=%d) — "
                        "priority-%d request shed; back off and retry"
                        % (len(self._pending), self.max_queue,
                           req.priority),
                        priority=req.priority)
                self._pending.remove(victim)
                evicted = victim
            self._pending.append(req)
            if self.metrics is not None:
                self.metrics.requests.add()
                self.metrics.streams.add()
            self._cv.notify_all()
        if evicted is not None:
            if self.metrics is not None:
                self.metrics.note_shed(priority=evicted.priority)
            obs_events.emit("shed", model=self._model_name,
                            priority=evicted.priority,
                            trace_id=evicted.trace_id, evicted=True,
                            by_priority=req.priority,
                            slots_busy=self._slots_busy_total())
            evicted.stream._fail(ServerOverloaded(
                "priority-%d request shed from a full decode queue by "
                "a priority-%d arrival (lowest-priority-first overload "
                "policy)" % (evicted.priority, req.priority),
                priority=evicted.priority))
        return req.stream

    # ------------------------------------------------------------------
    # the continuous loop (one thread per replica lane)
    # ------------------------------------------------------------------

    def _admissible(self, lane):
        if not self._pending:
            return False
        if self.continuous:
            return len(lane.assigned) < self.n_slots
        # static baseline: only an IDLE lane admits (then decodes the
        # whole batch to completion before admitting again)
        return not lane.assigned

    def _take_admits_locked(self, lane):
        """Pop the requests this lane admits right now (caller holds
        _cv — the `_locked` suffix is the lint-checked convention)."""
        room = self.n_slots - len(lane.assigned)
        out = []
        while self._pending and room > 0:
            out.append(self._pending.popleft())
            room -= 1
        return out

    def _emit_request_spans(self, req, lane, now):
        """Stage spans cut from contiguous monotonic stamps so
        queue_wait + prefill + decode tile serving/request exactly —
        the same tiling contract as the one-shot stage spans
        (OBSERVABILITY.md)."""
        wall_now = time.time()
        model = self._model_name
        t_adm = req.t_admitted if req.t_admitted is not None \
            else req.enqueued
        t_first = req.t_first if req.t_first is not None else t_adm

        def _mk(name, t0, t1, **attrs):
            if t1 < t0:
                t1 = t0
            a = {"model": model} if model else {}
            a.update(attrs)
            obs_tracing.add_span(obs_tracing.Span(
                name, kind="serving", trace_id=req.trace_id,
                ts=wall_now - (now - t0), dur_ms=(t1 - t0) * 1e3,
                attrs=a))

        _mk("serving/queue_wait", req.enqueued, t_adm)
        _mk("serving/prefill", t_adm, t_first, replica=lane.index,
            prompt=len(req.prompt))
        _mk("serving/decode", t_first, now, replica=lane.index,
            tokens=len(req.gen))
        _mk("serving/request", req.enqueued, now, replica=lane.index,
            prompt=len(req.prompt), tokens=len(req.gen),
            priority=req.priority)

    def _obs_info(self, req, lane, now):
        t_adm = req.t_admitted or now
        t_first = req.t_first or t_adm
        return {
            "trace_id": req.trace_id,
            "queue_wait_ms": round((t_adm - req.enqueued) * 1e3, 3),
            "prefill_ms": round((t_first - t_adm) * 1e3, 3),
            "decode_ms": round((now - t_first) * 1e3, 3),
            "server_ms": round((now - req.enqueued) * 1e3, 3),
            "ttft_ms": round((t_first - req.enqueued) * 1e3, 3),
            "tokens": len(req.gen),
            "replica": lane.index,
        }

    def _finish(self, lane, slot, req, reason, exc=None):
        """Terminal transition: flush, emit spans/metrics, free (and
        therefore ZERO) the slot so the next admit starts clean."""
        now = time.monotonic()
        if req.buf:
            req.stream._put_tokens(req.buf)
            req.buf = []
        if slot is not None:
            lane.session.free(slot)
            lane.assigned.pop(slot, None)
        if obs_tracing.enabled():
            self._emit_request_spans(req, lane, now)
        info = self._obs_info(req, lane, now)
        info["finish_reason"] = reason
        if exc is not None:
            if self.metrics is not None:
                self.metrics.errors.add()
                if isinstance(exc, DeadlineExceeded):
                    self.metrics.deadline_expired.add()
            req.stream.obs_info = info
            req.stream._fail(exc)
        else:
            if self.metrics is not None and reason != "cancelled":
                self.metrics.note_completion(
                    latency_ms=info["server_ms"],
                    queue_wait_ms=info["queue_wait_ms"])
            req.stream._finish(reason, obs_info=info)

    def _expire(self, lane, slot, req, now):
        """Deadline eviction — in queue, at prefill, or MID-DECODE: the
        deadline covers in-decode time (the PR 8 admission-control
        fix), so a streaming request past it frees its slot within one
        step instead of pinning it to max_new_tokens.  `overshoot_ms`
        stamps how far past the deadline the eviction landed — under
        fused decode the check fires at window boundaries, and the
        trip-count clamp bounds this to about one dispatch."""
        obs_events.emit("deadline_expired", model=self._model_name,
                        trace_id=req.trace_id,
                        replica=lane.index,
                        tokens=len(req.gen),
                        waited_ms=round((now - req.enqueued) * 1e3, 3),
                        overshoot_ms=round((now - req.deadline) * 1e3, 3)
                        if req.deadline is not None else None)
        self._finish(lane, slot, req, "deadline", exc=DeadlineExceeded(
            "deadline passed after %.1f ms (%d tokens generated)"
            % ((now - req.enqueued) * 1e3, len(req.gen))))

    def _prefill(self, lane, req):
        """Admit one request into a free slot: prefill the prompt,
        stream the first token (the TTFT instant)."""
        now = time.monotonic()
        req.t_admitted = now
        if req.stream.cancelled():
            self._finish(lane, None, req, "cancelled")
            return
        if req.deadline is not None and now > req.deadline:
            self._expire(lane, None, req, now)
            return
        sess = lane.session
        slot = sess.free_slots()[0]
        try:
            with obs_tracing.trace("serving/prefill_compute",
                                   kind="serving", trace_id=req.trace_id,
                                   model=self._model_name,
                                   replica=lane.index,
                                   prompt=len(req.prompt)):
                first = sess.prefill(slot, req.prompt)
        except BaseException as e:
            self._finish(lane, None, req, "error", exc=e)
            if isinstance(e, MeshMemberLost):
                # the request failed typed above; the LANE is dead too —
                # let the loop's member-loss handler retire it whole
                raise
            return
        req.t_first = time.monotonic()
        if self.metrics is not None:
            self.metrics.note_prefill(
                ttft_ms=(req.t_first - req.enqueued) * 1e3)
            self.metrics.note_tokens(1)
        lane.tokens += 1
        req.gen.append(first)
        req.buf.append(first)
        lane.assigned[slot] = req
        if first == self.predictor.eos_id:
            self._finish(lane, slot, req, "eos")
        elif req.max_new <= 1 or sess.room(slot) <= 0:
            self._finish(lane, slot, req, "length")
        elif len(req.buf) >= req.chunk:
            req.stream._put_tokens(req.buf)
            req.buf = []

    def _emit_step_spans(self, lane, t0, t_draft_end, now, n_slots,
                         accepted=None, tokens=None, trips=None):
        """Per-round step spans: `serving/decode_step` always (now a
        per-DISPATCH span: `tokens` emitted and `trips` loop
        iterations ride as attrs, the tokens-per-dispatch axis of the
        fused-decode win); on a speculative round its `serving/draft`
        + `serving/verify` children are cut from the same contiguous
        monotonic stamps so they TILE the round exactly (draft end ==
        verify start).  One time.time() anchor places them on the
        wall-clock axis; every duration rides the monotonic stamps."""
        wall_now = time.time()
        attrs = {"model": self._model_name or "", "replica": lane.index,
                 "slots": n_slots}

        def _mk(name, a, b, **extra):
            at = dict(attrs)
            at.update(extra)
            obs_tracing.add_span(obs_tracing.Span(
                name, kind="serving", ts=wall_now - (now - a),
                dur_ms=(max(b, a) - a) * 1e3, attrs=at))

        if t_draft_end is not None:
            _mk("serving/draft", t0, t_draft_end,
                spec_k=lane.session.spec_k)
            _mk("serving/verify", t_draft_end, now, accepted=accepted)
        _mk("serving/decode_step", t0, now, tokens=tokens, trips=trips)

    def _note_degraded(self, lane):
        """First observation of a degraded spec session: latch the obs
        event + counter exactly once per lane (the chaos spec-fallback
        scenario pins both)."""
        if lane.degraded_noted or not lane.spec \
                or not lane.session.degraded:
            return
        lane.degraded_noted = True
        if self.metrics is not None:
            self.metrics.spec_degraded.add()
        obs_events.emit("spec_degraded", model=self._model_name,
                        replica=lane.index,
                        error=str(lane.session.degrade_error or ""))

    def _lane_dead(self, lane, exc):
        """Retire a lane whose mesh group lost a member (SERVING.md
        "Mesh replicas"): mark it dead, fail its in-flight streams
        typed — WITHOUT freeing slots, a free dispatches on the dead
        mesh — and fail everything queued once NO live lane remains to
        ever admit it.  Sibling lanes keep serving; the fleet
        controller rebuilds the lane from the model's persisted load
        spec."""
        with self._cv:
            if lane.dead is not None:
                return
            lane.dead = "%s: %s" % (type(exc).__name__, exc)
            victims = list(lane.assigned.values())
            lane.assigned.clear()
            pend = []
            if all(l.dead is not None for l in self._lanes):
                pend = list(self._pending)
                self._pending.clear()
            self._cv.notify_all()
        obs_events.emit(
            "mesh_lane_dead", model=self._model_name,
            replica=lane.index,
            device=_predictor_device_label(lane.predictor),
            error=str(exc))
        if self.metrics is not None and (victims or pend):
            self.metrics.errors.add(len(victims) + len(pend))
        for req in victims + pend:
            req.buf = []
            req.stream._fail(exc)

    def _lane_loop(self, lane):
        while True:
            try:
                if not self._lane_iter(lane):
                    return
            except MeshMemberLost as e:
                # one member of this lane's mesh is gone: the lane
                # dies WHOLE — typed failures, never a wedge — and
                # exits cleanly (no server_thread_death); the chaos
                # mesh-member-loss scenario pins this contract
                self._lane_dead(lane, e)
                return

    def _lane_iter(self, lane):
        """One iteration of the continuous loop: admit + prefill, one
        decode dispatch, stream bookkeeping.  Returns False to stop."""
        sess = lane.session
        eos = self.predictor.eos_id
        with self._cv:
            while not lane.assigned and not self._admissible(lane):
                if self._stopped:
                    return False
                self._cv.wait(0.1)
            if self._stopped and not lane.assigned:
                return False
            admits = self._take_admits_locked(lane) \
                if self._admissible(lane) else []
        # prefill OUTSIDE the lock: other lanes keep decoding
        for i, req in enumerate(admits):
            try:
                self._prefill(lane, req)
            except MeshMemberLost:
                # this lane is dying whole; admits not yet prefilled
                # never touched its mesh — push them back for a
                # surviving lane (if none survives, _lane_dead fails
                # the whole queue typed)
                with self._cv:
                    for rem in reversed(admits[i + 1:]):
                        self._pending.appendleft(rem)
                    self._cv.notify_all()
                raise
        if not lane.assigned:
            self._note_degraded(lane)
            return True
        fuse = self.fuse_steps
        if fuse > 1:
            # window-boundary housekeeping (SERVING.md "Fused
            # multi-step decode"): drop cancelled/expired streams
            # BEFORE burning an N-step window on them — joins and
            # leaves happen only at dispatch boundaries
            nowb = time.monotonic()
            for slot, req in list(lane.assigned.items()):
                if req.stream.cancelled():
                    req.buf = []
                    self._finish(lane, slot, req, "cancelled")
                elif req.deadline is not None \
                        and nowb > req.deadline:
                    self._expire(lane, slot, req, nowb)
            if not lane.assigned:
                return True
        n_act = len(lane.assigned)
        t0 = time.monotonic()
        # the same slow-worker chaos hook / deterministic per-step
        # device-cost stand-in as the one-shot lanes
        # (set_dispatch_delay — bench_serving --step_cost_ms; the
        # draft steps of a spec round price separately via
        # set_draft_delay — bench_serving --draft_cost_ms), plus
        # the per-DISPATCH host-cost stand-in (set_host_delay —
        # bench_serving --host_cost_ms) that fusion amortizes 1/N
        delay = _chaos_delay()
        host_delay = _host_chaos_delay()
        if host_delay:
            time.sleep(host_delay)
        trips = 1
        if lane.spec:
            toks2d, counts = sess.step(
                step_delay=delay,
                draft_delay=_draft_chaos_delay(),
                fused=fuse > 1)
            spec_round = sess.last_spec
        elif fuse > 1:
            # per-slot token budgets (max_new / cache-room
            # headroom) + the deadline governor: the lane's EWMA
            # step time clamps the trip count so a deadlined
            # stream never overshoots by more than ~one dispatch
            budget = np.zeros(self.n_slots, np.int32)
            max_trips = fuse
            for slot, req in lane.assigned.items():
                budget[slot] = min(req.max_new - len(req.gen),
                                   sess.room(slot), fuse)
                if req.deadline is not None and lane.step_ewma:
                    allow = int((req.deadline - t0)
                                / lane.step_ewma)
                    max_trips = min(max_trips, max(allow, 1))
            toks2d, counts, trips = sess.decode_fused(
                fuse, budget=budget, max_trips=max_trips)
            spec_round = False
            if delay:
                # the device-cost stand-in scales with the trips
                # that actually ran (in-graph early exit included)
                time.sleep(delay * trips)
        else:
            if delay:
                time.sleep(delay)
            toks = sess.decode()
            spec_round = False
        now = time.monotonic()
        lane.steps += 1
        lane.last_step_t = now
        # EWMA seconds per logical step (per trip): the fused
        # deadline governor's clamp input
        per_step = (now - t0) / max(trips, 1)
        lane.step_ewma = per_step if lane.step_ewma is None \
            else 0.5 * lane.step_ewma + 0.5 * per_step
        if self.metrics is not None:
            self.metrics.decode_steps.add(trips)
            if spec_round:
                # per-round accept telemetry: k proposals per
                # occupied slot, counts[s]-1 of them accepted
                proposed = sess.spec_k * n_act
                accepted = int(counts.sum()) - n_act
                self.metrics.note_spec(proposed, accepted)
        self._note_degraded(lane)
        fused_plain = not lane.spec and fuse > 1
        emitted = 0
        for slot, req in list(lane.assigned.items()):
            # a spec round commits 1..k+1 tokens per slot (a fused
            # window up to fuse_steps); consume them in stream
            # order with per-token EOS/max-new cuts so the emitted
            # stream is bit-identical to the plain
            # one-token-per-step path
            slot_toks = [int(toks2d[slot, j])
                         for j in range(int(counts[slot]))] \
                if (lane.spec or fused_plain) else [int(toks[slot])]
            finished = None
            for tok in slot_toks:
                req.gen.append(tok)
                req.buf.append(tok)
                emitted += 1
                if tok == eos:
                    finished = "eos"
                    break
                if len(req.gen) >= req.max_new:
                    finished = "length"
                    break
            if req.stream.cancelled():
                # client gone: nobody reads the flush — just free
                req.buf = []
                self._finish(lane, slot, req, "cancelled")
                continue
            if req.deadline is not None and now > req.deadline:
                self._expire(lane, slot, req, now)
                continue
            if finished is None and sess.room(slot) <= 0:
                finished = "length"
            if finished is not None:
                self._finish(lane, slot, req, finished)
            elif len(req.buf) >= req.chunk:
                req.stream._put_tokens(req.buf)
                req.buf = []
        if obs_tracing.enabled():
            self._emit_step_spans(
                lane, t0,
                sess.last_draft_end if spec_round else None, now,
                n_act,
                accepted=(int(counts.sum()) - n_act)
                if spec_round else None,
                tokens=emitted, trips=trips)
        lane.tokens += emitted
        if self.metrics is not None:
            # per-dispatch accounting: the tokens-per-dispatch
            # histogram is the direct readout of the fused-decode
            # amortization (TPD ~1 at N=1, ~N when fused)
            self.metrics.note_decode_dispatch(emitted)
            if emitted:
                self.metrics.note_tokens(emitted)
        with self._cv:
            self._cv.notify_all()
        return True

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _busy(self):
        return bool(self._pending
                    or any(l.assigned for l in self._lanes))

    def drain(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            self._cv.notify_all()
            while self._busy():
                rem = None if deadline is None else \
                    max(deadline - time.monotonic(), 0.0)
                if rem == 0.0:
                    raise TimeoutError(
                        "decode batcher still has %d queued + %d "
                        "in-slot requests after %.1fs"
                        % (len(self._pending),
                           sum(len(l.assigned) for l in self._lanes),
                           timeout))
                self._cv.wait(0.05 if rem is None else min(rem, 0.05))

    def close(self, drain=True, timeout=30.0):
        with self._cv:
            self._closing = True
            self._cv.notify_all()
        if drain:
            self.drain(timeout)
        with self._cv:
            self._stopped = True
            leftovers = list(self._pending)
            self._pending.clear()
            for lane in self._lanes:
                for req in lane.assigned.values():
                    req.stream.cancel()
            self._cv.notify_all()
        for req in leftovers:
            req.stream._fail(
                BatcherClosed("server shut down before dispatch"))
            if self.metrics is not None:
                self.metrics.errors.add()
        for t in self._threads:
            t.join(timeout=10.0)
