"""paddle_tpu.serving — the multi-tenant inference serving runtime.

Turns a directory of `save_inference_model` / `save_aot` artifacts into
a trafficable service (SERVING.md): cross-request dynamic micro-batching
onto the compiled batch buckets with N device-placed replicas per model
fronted by per-replica execution lanes and a least-loaded router
(batcher.py) — a replica may be a multi-chip device MESH sharding the
params and KV slot table across its members (parallel/mesh.py,
SERVING.md "Mesh replicas") while serving as ONE lane —, named/versioned models with placement specs and warm
atomic hot swap of whole replica sets (model_registry.py), a threaded
wire-protocol front with priority-class admission control and graceful
drain (server.py), per-model + per-replica serving metrics
(metrics.py), and the fleet controller closing the loop from the
SLO/queue/occupancy sensors to replica-set scaling, cold-model paging
and pressure degradation (fleet.py — SERVING.md "Fleet controller").

Reference analogue: paddle/fluid/inference/api/ stops at a synchronous
per-caller predictor; the serving layer the TensorFlow system paper
treats as a distinct subsystem (arXiv:1605.08695 §4.3, TF Serving) is
this module's territory — distinct scheduling needs (latency SLOs,
coalescing, load shedding) from the training runtime's.
"""

from .batcher import (BatcherClosed, DeadlineExceeded, DecodeBatcher,
                      DecodeStream, DynamicBatcher, ServerOverloaded,
                      set_dispatch_delay, set_draft_delay,
                      set_host_delay)
from .fleet import (FleetAction, FleetController, FleetPolicy,
                    ModelSensors, parse_fleet_spec)
from .metrics import (Counter, ModelMetrics, ReservoirHistogram,
                      ServingMetrics)
from .model_registry import (ModelEntry, ModelRegistry, open_predictor,
                             resolve_placement)
from ..parallel.mesh import MeshGroup, MeshMemberLost
from .server import (InferenceServer, ServingClient, ServingError,
                     StreamBroken)

__all__ = [
    "DynamicBatcher", "DecodeBatcher", "DecodeStream",
    "ServerOverloaded", "DeadlineExceeded",
    "BatcherClosed", "set_dispatch_delay", "set_draft_delay",
    "set_host_delay",
    "Counter", "ReservoirHistogram", "ModelMetrics", "ServingMetrics",
    "ModelRegistry", "ModelEntry", "open_predictor",
    "resolve_placement", "MeshGroup", "MeshMemberLost",
    "FleetController", "FleetPolicy", "FleetAction", "ModelSensors",
    "parse_fleet_spec",
    "InferenceServer", "ServingClient", "ServingError",
    "StreamBroken",
]
