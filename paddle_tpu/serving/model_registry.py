"""Named, versioned model registry with device-placed replicas and
atomic hot swap.

The multi-tenant half of the serving runtime: each model name maps to
versioned entries — now each entry holding N device-resident replica
predictors fronted by one DynamicBatcher whose router fans coalesced
micro-batch groups to the least-loaded replica lane; requests route
through a `latest` pointer.

Placement spec (`resolve_placement`): `FLAGS.serving_replicas` or a
per-load override — an int N (round-robin over local devices; 1 keeps
the single default-device replica), 'auto' (one replica per local
device — the whole-host serving shape), or an explicit device list
('0,2' local indices / 'cpu:0,tpu:3' platform:index / jax.Device
objects).  Each replica's params are `jax.device_put` on its assigned
device and its batch buckets compile and WARM there, so the first real
request on any replica runs at steady-state latency.

A hot swap follows the same commit discipline as the checkpoint vault
(fluid/checkpoint.py), extended per replica set: build ALL new replicas
completely — load artifact, clone+place per device, construct batcher,
warm every bucket on every replica — then flip `latest` under the
routing lock, and only afterwards drain and retire the displaced
replica set.  A request that resolved the old version before the flip
completes on whichever old replica its group was routed to (the drain
waits); a request after the flip runs the new set; no request is
dropped or answered twice.

Artifact detection: a directory containing `aot_meta.bin` is a
`save_aot` artifact (AotPredictor — no Program rebuild, no trace); any
other directory is treated as a `save_inference_model` dir served by a
live `Predictor` under `AnalysisConfig` (IR rewrites + AOT jit compile,
bucketed).
"""

import os
import threading
import time

import numpy as np

from ..flags import FLAGS
from ..obs import events as obs_events
from .batcher import DecodeBatcher, DynamicBatcher
from .metrics import ServingMetrics

__all__ = ["ModelRegistry", "ModelEntry", "open_predictor",
           "resolve_placement"]


def _pack_mesh_spec(s):
    """'mesh:N' / 'mesh:RxC' as the WHOLE placement spec: pack as many
    disjoint consecutive N-device (R*C-device) groups as the host's
    local devices allow — each group one logical mesh replica.  A
    1-device mesh is just the legacy one-replica-per-device shape."""
    import jax
    from ..parallel.mesh import MeshGroup
    body = s.split(":", 1)[1].strip()
    try:
        dims = tuple(int(p) for p in body.split("x")) if "x" in body \
            else (int(body),)
    except ValueError:
        raise ValueError(
            "bad mesh placement %r — expected 'mesh:N' or 'mesh:RxC'"
            % s)
    g = 1
    for d in dims:
        if d < 1:
            raise ValueError(
                "bad mesh placement %r — dimensions must be >= 1" % s)
        g *= d
    local = list(jax.local_devices())
    if g == 1:
        return list(local)
    n_groups = len(local) // g
    if n_groups < 1:
        raise ValueError(
            "mesh placement %r needs %d devices per replica, host has "
            "%d local device(s)" % (s, g, len(local)))
    return [MeshGroup(local[i * g:(i + 1) * g], dims)
            for i in range(n_groups)]


def resolve_placement(spec=None):
    """Turn a replica placement spec into a list of jax.Device /
    MeshGroup (or [None] for the single default-device replica).

    spec: None -> FLAGS.serving_replicas; int or digit-string N -> N
    replicas round-robin over jax.local_devices() (N == 1 -> [None],
    the pre-multichip single-replica behavior on the default device);
    'auto' -> one replica per local device; a comma list / sequence of
    local indices ('0,2'), 'platform:index' names ('cpu:0', 'tpu:3'),
    or jax.Device objects -> exactly those devices.

    Mesh replicas (SERVING.md "Mesh replicas"): 'mesh:N' / 'mesh:RxC'
    as the WHOLE spec packs the host into as many disjoint consecutive
    N-device groups as fit, each group ONE logical replica sharding
    the model across its members; '+'-joined members inside a list
    element ('tpu:0+tpu:1' or '0+1') place one explicit mesh replica
    and compose freely with plain elements.  A 1-member group
    collapses to the plain device.  A device may belong to at most one
    mesh group and never doubles as a plain replica — overlap is a
    placement error (plain single-device duplicates stay allowed: they
    multiply the fit estimate, not the sharding)."""
    import jax
    from ..parallel.mesh import MeshGroup
    if spec is None:
        spec = FLAGS.serving_replicas
    if isinstance(spec, (list, tuple)):
        local = list(jax.local_devices())
        by_key = {(d.platform, d.id): d for d in local}

        def one(tok):
            if hasattr(tok, "platform") and hasattr(tok, "id") \
                    and not isinstance(tok, str):
                return tok  # already a jax.Device
            t = str(tok).strip()
            if ":" in t:
                plat, _, idx = t.partition(":")
                dev = by_key.get((plat.strip(), int(idx)))
                if dev is None:
                    raise ValueError(
                        "no local device %r (have %s)" % (
                            t, sorted("%s:%d" % k for k in by_key)))
                return dev
            i = int(t)
            if i >= len(local):
                raise ValueError(
                    "device index %d out of range: %d local "
                    "device(s)" % (i, len(local)))
            return local[i]

        def key_of(d):
            return (getattr(d, "platform", None), getattr(d, "id", None))

        devs = []
        mesh_keys = set()   # devices claimed by a mesh group
        plain_keys = set()  # devices used as plain replicas
        for item in spec:
            if isinstance(item, MeshGroup):
                members = list(item.devices)
            elif not isinstance(item, str) and \
                    hasattr(item, "platform") and hasattr(item, "id"):
                members = [item]
            else:
                s = str(item).strip()
                if not s:
                    continue
                if s.startswith("mesh:"):
                    raise ValueError(
                        "'mesh:N' packs the WHOLE host and cannot be "
                        "combined with other placement elements — use "
                        "explicit '+'-joined groups (e.g. 'tpu:0+"
                        "tpu:1,tpu:2+tpu:3') to mix")
                members = [one(t) for t in s.split("+") if t.strip()]
            if not members:
                continue
            if len(members) == 1:
                dev = members[0]
                k = key_of(dev)
                if k in mesh_keys:
                    raise ValueError(
                        "device %s:%s is a mesh-group member and "
                        "cannot double as a plain replica" % k)
                plain_keys.add(k)
                devs.append(dev)
                continue
            keys = [key_of(d) for d in members]
            for k in keys:
                if k in mesh_keys or k in plain_keys:
                    raise ValueError(
                        "device %s:%s already placed — mesh-group "
                        "members must be exclusive" % k)
            mesh_keys.update(keys)
            devs.append(item if isinstance(item, MeshGroup)
                        else MeshGroup(members))
        if not devs:
            raise ValueError("empty replica device list")
        return devs
    if isinstance(spec, str):
        s = spec.strip()
        if s == "auto":
            return list(jax.local_devices())
        if s.startswith("mesh:") and "," not in s:
            return _pack_mesh_spec(s)
        if "," in s or ":" in s or "+" in s:
            return resolve_placement(
                [p for p in s.split(",") if p.strip()])
        spec = int(s)
    n = int(spec)
    if n < 1:
        raise ValueError("replica count must be >= 1, got %d" % n)
    if n == 1:
        # the pre-multichip contract: one replica floating on jax's
        # default device (uncommitted state, no forced transfers)
        return [None]
    local = list(jax.local_devices())
    return [local[i % len(local)] for i in range(n)]


def open_predictor(path, buckets=None, device=None,
                   kv_cache_dtype=None):
    """Open a serving artifact directory as the right predictor type,
    optionally pinned to `device` (a jax.Device).  Detection: a
    `decode_meta.bin` dir is an autoregressive decode artifact
    (GenerativePredictor — continuous-batching generation); an
    `aot_meta.bin` dir a save_aot artifact; anything else a
    save_inference_model dir.  `kv_cache_dtype` (decode artifacts
    only) overrides the artifact's KV-cache numerics pin
    (QUANTIZE.md "Quantized KV cache")."""
    from ..inference import AnalysisConfig, Predictor, AotPredictor
    from ..inference.decode import DECODE_META, GenerativePredictor
    if os.path.exists(os.path.join(path, DECODE_META)):
        return GenerativePredictor(path, device=device,
                                   kv_cache_dtype=kv_cache_dtype)
    if os.path.exists(os.path.join(path, "aot_meta.bin")):
        return AotPredictor(path, device=device)
    if not os.path.isdir(path):
        raise FileNotFoundError("no model artifact directory at %r" % path)
    config = AnalysisConfig(model_dir=path)
    if buckets:
        config.batch_size_buckets = tuple(sorted(int(b) for b in buckets))
    return Predictor(config, device=device)


def _build_replicas(path, buckets, devices, kv_cache_dtype=None):
    """One artifact load + (N-1) clone_to placements: the Program parse
    / StableHLO deserialize happens once, each replica gets its own
    device-committed param copy and compile cache."""
    first = open_predictor(path, buckets=buckets, device=devices[0],
                           kv_cache_dtype=kv_cache_dtype)
    preds = [first]
    for dev in devices[1:]:
        preds.append(first.clone_to(dev))
    return preds


class ModelEntry:
    """One (name, version): its replica predictors (device-placed), the
    batcher fronting them, and its path.  `predictor` stays the first
    replica — the introspection surface (buckets, feed specs) is
    identical across replicas by construction."""

    def __init__(self, name, version, path, predictor, batcher,
                 replicas=None, devices=None, precision="fp32",
                 resource=None, draft_path=None):
        self.name = name
        self.version = version
        self.path = path
        self.predictor = predictor
        self.batcher = batcher
        self.replicas = list(replicas) if replicas else [predictor]
        self.devices = list(devices) if devices else [None]
        # the numerics lane this version serves (QUANTIZE.md): 'int8'
        # for a PTQ artifact, 'fp32' otherwise — the axis the router
        # splits on and the metrics lane files under
        self.precision = str(precision or "fp32")
        # what THIS build+warm cost against the persistent compile
        # cache (compile_cache.stats_delta, set by load_model): a warm
        # flip shows misses == 0 — zero fresh compilations
        self.compile_cache = {}
        # the static ResourceReport the admission fit check ran on
        # (ANALYSIS.md) — what describe()/stats/Prometheus expose so a
        # fleet controller can place by cost; None when the artifact
        # could not be analyzed
        self.resource = resource
        # speculative decoding (SERVING.md): the draft artifact this
        # entry's lanes draft with, or None for target-only decode
        self.draft_path = draft_path

    def device_labels(self):
        from ..inference.predictor import _device_label
        return [_device_label(d) for d in self.devices]

    def mesh_sizes(self):
        """Members per replica, in route order: 1 for a plain device,
        N for a MeshGroup (SERVING.md "Mesh replicas")."""
        from ..parallel.mesh import as_mesh_group
        return [g.mesh_size if (g := as_mesh_group(d)) is not None
                else 1 for d in self.devices]

    @property
    def is_decode(self):
        return bool(getattr(self.predictor, "is_decode", False))

    def warm(self):
        """Run one zero dummy batch per bucket DIRECTLY on EVERY
        replica predictor (not through the batcher — warming must not
        mix with traffic).  After this, every bucket's executable is
        compiled/loaded on every replica's device and the first real
        request at any size on any lane runs at steady-state latency.
        The hot-swap commit discipline hinges on this covering the
        whole replica set BEFORE the `latest` flip.

        Decode models warm BOTH phases: every prompt-bucket prefill
        plus the fixed-shape slot-table decode step, on a scratch
        session per replica (the lane sessions share the resolved
        executables, so the first real stream pays no compile)."""
        if self.is_decode:
            n_slots = self.batcher.n_slots
            spec_k = getattr(self.batcher, "spec_k", 0)
            drafts = getattr(self.batcher, "draft_replicas", None)
            fuse = int(getattr(self.batcher, "fuse_steps", 1))
            for i, pred in enumerate(self.replicas):
                sess = pred.new_session(n_slots)
                for bucket in pred.prefill_buckets():
                    # a prompt filling the whole cache is unservable
                    # (no room to generate), so the largest bucket is
                    # warmed with the longest SERVABLE prompt length
                    n = min(bucket, pred.max_seq_len - 1)
                    sess.prefill(0, [0] * n)
                    sess.decode()
                    sess.free(0)
                if fuse > 1 and not (drafts and spec_k):
                    # fused lanes: force-resolve the (n_slots, N)
                    # window executable so the first real dispatch
                    # pays no compile (COMPILE_CACHE.md — the fused
                    # fingerprint rides the warm-reload hits:N pin)
                    pred.fused_step_fn(n_slots, fuse)
                if drafts and spec_k:
                    # spec lanes: force-resolve the verify executable
                    # plus the draft's phases so the first real stream
                    # pays no compile on EITHER side of the flip
                    pred.verify_fn(n_slots, spec_k)
                    if fuse > 1:
                        pred.fused_spec_fn(drafts[i], n_slots, spec_k)
                    dsess = drafts[i].new_session(n_slots)
                    for bucket in drafts[i].prefill_buckets():
                        n = min(bucket, drafts[i].max_seq_len - 1)
                        dsess.prefill(0, [0] * n)
                        dsess.decode()
                        dsess.free(0)
            return self
        specs = self.predictor.feed_specs()
        buckets = self.predictor.batch_buckets() or (1,)
        batched = self.predictor.batched_feed_names()
        for pred in self.replicas:
            for cap in buckets:
                feeds = {}
                for fname, (shape, dtype) in specs.items():
                    if fname in batched:
                        s = [cap if d == -1 else d for d in shape]
                    else:
                        s = [1 if d == -1 else d for d in shape]
                    feeds[fname] = np.zeros(tuple(s),
                                            dtype=np.dtype(dtype))
                pred.run(feeds)
        return self


class ModelRegistry:
    """name -> {versions, latest} with hot swap and drain-on-retire."""

    def __init__(self, metrics=None, max_queue=None, deadline_ms=None,
                 workers=None, replicas=None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._max_queue = max_queue
        self._deadline_ms = deadline_ms
        self._workers = workers
        self._replicas = replicas  # default placement spec for loads
        self._lock = threading.Lock()
        self._models = {}  # name -> {"versions": {v: entry}, "latest": v}
        # unload-to-spec (SERVING.md "Fleet controller"): every unload
        # persists how to REBUILD the exact lane set (per-lane load
        # specs + A/B weights); paged models additionally fault back in
        # on the next request.  One per-name lock serializes fault-ins
        # so a request burst rebuilds the model once.
        self._unload_specs = {}   # name -> {"lanes": [...], "ab": {...}}
        self._paged = {}          # name -> same record + "paged_at"
        self._fault_locks = {}    # name -> threading.Lock
        # last measured fault-in per model: {"ms", "trigger", "t_mono"}
        # — the fleet controller's fault_in_ms gauge reads this
        self.last_fault_in = {}

    # ------------------------------------------------------------------

    @staticmethod
    def _fit_check(name, path, placement, decode_slots=None,
                   draft_path=None, kv_cache_dtype=None):
        """Static admission gate (ANALYSIS.md): analyze the artifact,
        then check the per-replica peak estimate against every
        placement device's memory budget.  Returns the ResourceReport
        (None when the artifact defies analysis — advisory only);
        raises ResourceFitError on a placement that cannot fit.

        Replicas sharing one device (the [None] default-device spec
        with N > 1 never happens; explicit duplicate devices can)
        multiply the estimate on that device.

        `draft_path` (speculative decoding) adds the draft artifact's
        estimate — its weights AND its own KV slot table — to every
        replica's footprint: the draft lives on the same device as its
        target, so both must fit TOGETHER or the load is rejected
        before any build/warm work.

        A MeshGroup replica (SERVING.md "Mesh replicas") prices PER
        MEMBER device: params + KV shard at rest (~1/mesh_size each),
        the replicated-compute activation peak does not — so a model
        whose whole-footprint estimate exceeds any one chip's budget
        still ADMITS on a mesh whose members each fit their share.
        The draft rides the same group, priced the same way."""
        from ..analysis import ResourceFitError, check_fit, resources
        from ..parallel.mesh import as_mesh_group
        try:
            report = resources.analyze_artifact(
                path, decode_slots=decode_slots,
                kv_cache_dtype=kv_cache_dtype)
        except Exception:
            return None
        draft_report = None
        if draft_path:
            try:
                draft_report = resources.analyze_artifact(
                    draft_path, decode_slots=decode_slots)
            except Exception:
                draft_report = None
        by_dev = {}
        for dev in placement:
            key = id(dev) if dev is not None else None
            by_dev[key] = (dev, by_dev.get(key, (dev, 0))[1] + 1)
        what = "model %r (%s)" % (name, path)
        if draft_report is not None:
            what += " + draft (%s)" % (draft_path,)
        mesh_max = 1
        for dev, n in by_dev.values():
            group = as_mesh_group(dev)
            m = group.mesh_size if group is not None else 1
            mesh_max = max(mesh_max, m)
            members = group.devices if group is not None else (dev,)
            w = what if group is None else \
                "%s on mesh replica %s" % (what, group.label())
            est = avail = None
            for member in members:
                try:
                    est, avail = check_fit(
                        report, device=member, what=w, replicas=n,
                        mesh_size=m)
                    if draft_report is not None and avail is not None:
                        est += draft_report.per_device_bytes(m) * int(n)
                        if est > avail:
                            raise ResourceFitError(w, est, avail,
                                                   device=member)
                except ResourceFitError as e:
                    obs_events.emit(
                        "model_fit_rejected", model=name, path=path,
                        draft=draft_path or None,
                        est_bytes=e.estimated_bytes,
                        available_bytes=e.available_bytes,
                        mesh_size=int(m))
                    raise
            if avail is not None:
                obs_events.emit(
                    "model_fit_check", model=name, path=path,
                    draft=draft_path or None,
                    est_bytes=int(est), available_bytes=int(avail),
                    replicas=int(n), mesh_size=int(m),
                    step_bytes=int(report.per_device_step_bytes(
                        m, tp=bool(FLAGS.mesh_tp))))
        # stamp the placement's mesh shape (and the tensor-parallel
        # compute mode) on the stored report so describe()/stats (and
        # the fleet's placement-by-capacity math) read the per-device
        # resident estimate + per-member step traffic, not the
        # whole-model sums
        report.mesh_size = int(mesh_max)
        report.tp = bool(FLAGS.mesh_tp and mesh_max > 1)
        return report

    def load_model(self, name, path, version=None, warm=True,
                   buckets=None, drain_timeout=30.0, replicas=None,
                   devices=None, decode_slots=None, decode_mode=None,
                   precision=None, ab_weight=None, draft=None,
                   spec_k=None, kv_cache_dtype=None, fuse_steps=None):
        """Load (or hot-swap in) `path` as `name`.  Returns the entry.
        `replicas`/`devices` override the registry's default placement
        spec (see resolve_placement).  ALL replicas are built and
        warmed before the flip; the displaced latest version OF THE
        SAME PRECISION LANE, if any, is drained and retired AFTER the
        flip — in-flight requests on it complete.  Loading an int8
        sibling never touches the live fp32 lane (and vice versa):
        that's the A/B axis, not a hot swap.

        `precision` overrides the artifact's own lane (auto-detected
        from quant_meta.bin / the rewritten program — 'int8' vs
        'fp32'); `ab_weight` sets this lane's share of DEFAULT-routed
        traffic (requests carrying no explicit precision), e.g. 0.1
        canaries the quantized lane at 10%.  Without weights, default
        traffic stays on the fp32 lane — loading a quantized sibling
        must not silently move traffic.

        A decode artifact (decode_meta.bin) is fronted by a
        DecodeBatcher instead: per-replica slot tables of
        `decode_slots` (default FLAGS.serving_decode_slots) with
        continuous batching; `decode_mode="static"` keeps the
        static-batch baseline (bench comparison only).

        `draft`/`spec_k` (SERVING.md "Speculative decoding", decode
        artifacts only): `draft` names a vocab-compatible decode
        artifact (default FLAGS.serving_spec_draft — canonically the
        int8 twin) built on the SAME placement, one draft replica per
        target replica; each lane then drafts `spec_k` (default
        FLAGS.serving_spec_k) tokens per round and the target verifies
        them in one batched step, streams staying bit-identical to
        target-only decode.  The draft is fit-checked alongside the
        target before any build work.

        `kv_cache_dtype` (decode artifacts only, QUANTIZE.md
        "Quantized KV cache"): 'int8' stores this load's KV slot
        tables quantized (~0.25x cache bytes, in-graph quantized
        writes, in-register dequant reads); default resolves from the
        artifact's decode_meta pin then FLAGS.serving_kv_cache_dtype.
        The admission fit check prices the requested cache dtype, and
        the compile cache fingerprints it, so fp32 and int8 loads
        never share an executable.

        `fuse_steps` (decode artifacts only, SERVING.md "Fused
        multi-step decode"): each lane dispatch fuses up to this many
        decode steps into ONE device executable (default
        FLAGS.serving_decode_fuse_steps; 1 keeps the classic loop).
        Streams stay bit-identical to N=1; warm() force-resolves the
        fused-window executables so the flip pays no first-dispatch
        compile."""
        from .. import compile_cache
        spec = devices if devices is not None else (
            replicas if replicas is not None else self._replicas)
        placement = resolve_placement(spec)
        is_decode_path = os.path.exists(
            os.path.join(path, "decode_meta.bin"))
        draft_path, spec_depth = None, 0
        if is_decode_path:
            # normalize/validate at admission so a bad wire value is a
            # typed error before any analysis or build work
            from ..inference.decode import normalize_kv_dtype
            if kv_cache_dtype is not None:
                kv_cache_dtype = normalize_kv_dtype(kv_cache_dtype)
            spec_depth = int(FLAGS.serving_spec_k if spec_k is None
                             else spec_k)
            draft_path = draft if draft is not None \
                else (FLAGS.serving_spec_draft or None)
            if not draft_path or spec_depth < 1:
                draft_path, spec_depth = None, 0
            fuse_steps = max(int(FLAGS.serving_decode_fuse_steps
                                 if fuse_steps is None
                                 else fuse_steps), 1)
        else:
            kv_cache_dtype = None
            fuse_steps = None
        # admission fit check (ANALYSIS.md resource analysis): the
        # static per-replica peak estimate is checked against each
        # placement device's budget BEFORE any artifact build / clone /
        # warm work — an un-fittable placement fails fast with a
        # ResourceFitError naming the estimated and available bytes.
        # Analysis failures (not fit failures) must never block a load:
        # the estimate is advisory when it cannot be computed.
        report = self._fit_check(name, path, placement,
                                 decode_slots=decode_slots,
                                 draft_path=draft_path,
                                 kv_cache_dtype=kv_cache_dtype)
        cc_before = compile_cache.stats()
        preds = _build_replicas(path, buckets, placement,
                                kv_cache_dtype=kv_cache_dtype)
        precision = str(precision or getattr(preds[0], "precision",
                                             "fp32"))
        lane_metrics = self.metrics.model(name, precision)
        if getattr(preds[0], "is_decode", False):
            draft_preds = _build_replicas(draft_path, None, placement) \
                if draft_path else None
            batcher = DecodeBatcher(
                preds[0], replicas=preds, n_slots=decode_slots,
                max_queue=self._max_queue,
                metrics=lane_metrics,
                continuous=(decode_mode != "static"),
                draft_replicas=draft_preds, spec_k=spec_depth,
                fuse_steps=fuse_steps)
        else:
            batcher = DynamicBatcher(
                preds[0], max_queue=self._max_queue,
                deadline_ms=self._deadline_ms, workers=self._workers,
                metrics=lane_metrics, replicas=preds)
        entry = ModelEntry(name, version, path, preds[0], batcher,
                           replicas=preds, devices=placement,
                           precision=precision, resource=report,
                           draft_path=draft_path)
        # unload-to-spec record (SERVING.md "Fleet controller"): the
        # RESOLVED kwargs that rebuild exactly this lane — what
        # unload_model persists, fault_in replays, and resize_model
        # replays at a new placement.  Values are resolved (not the
        # FLAGS-dependent None defaults) so a later flag change cannot
        # silently rebuild a different lane.
        entry.load_spec = {
            "path": path,
            "buckets": list(buckets) if buckets else None,
            "precision": precision,
            "draft": draft_path,
            "spec_k": spec_depth,
            "decode_slots": (batcher.n_slots
                             if entry.is_decode else None),
            "decode_mode": decode_mode,
            "kv_cache_dtype": (str(getattr(preds[0], "kv_cache_dtype",
                                           "float32"))
                               if entry.is_decode else None),
            "fuse_steps": (batcher.fuse_steps
                           if entry.is_decode else None),
        }
        if placement == [None]:
            entry.load_spec["replicas"] = 1
        else:
            entry.load_spec["devices"] = entry.device_labels()
        if report is not None:
            lane_metrics.note_resource(report.peak_mb,
                                       report.total_flops)
        if warm:
            try:
                entry.warm()
            except BaseException:
                batcher.close(drain=False, timeout=1.0)
                raise
        # build+warm covered every (bucket, replica) executable — the
        # counter delta is exactly what this load/flip cost against the
        # persistent compile cache (load_model reply + metrics)
        entry.compile_cache = compile_cache.stats_delta(cc_before)
        lane_metrics.note_compile(entry.compile_cache)
        # the compile-cache delta is a lifecycle fact worth keeping: a
        # warm flip reads hits=N misses=0 in the event log forever,
        # even after the stats counters blur across later loads
        obs_events.emit("compile_cache_delta", model=name,
                        precision=precision,
                        hits=int(entry.compile_cache.get("hits", 0)),
                        misses=int(entry.compile_cache.get("misses", 0)))
        displaced = None
        with self._lock:
            slot = self._models.setdefault(
                name, {"versions": {}, "latest": None,
                       "latest_prec": {}, "ab": {}, "ab_credit": {}})
            if version is None:
                prev = [v for v in slot["versions"] if isinstance(v, int)]
                version = entry.version = (max(prev) + 1) if prev else 1
            # hot swap is per precision LANE: the displaced set is the
            # old latest of THIS lane, never the A/B sibling
            old_lane = slot.setdefault("latest_prec", {}).get(precision)
            if old_lane is not None and old_lane != version:
                displaced = slot["versions"].get(old_lane)
            replaced_same = slot["versions"].get(version)
            slot["versions"][version] = entry
            slot["latest"] = version  # the atomic flip
            slot["latest_prec"][precision] = version
            if ab_weight is not None:
                slot.setdefault("ab", {})[precision] = float(ab_weight)
            flipped_from = old_lane
            # the model is resident again: a load supersedes any
            # paged/unloaded spec record
            self._paged.pop(name, None)
            self._unload_specs.pop(name, None)
        # the new batcher owns the live replica/queue-depth hooks from
        # here on; the displaced set still drains below
        obs_events.emit("hot_swap", model=name, version=version,
                        from_version=flipped_from, precision=precision,
                        replicas=len(entry.replicas))
        for old in (displaced, replaced_same):
            if old is not None and old is not entry:
                old.batcher.close(drain=True, timeout=drain_timeout)
                with self._lock:
                    slot = self._models.get(name)
                    if slot and slot["versions"].get(old.version) is old:
                        del slot["versions"][old.version]
        return entry

    def set_ab_weights(self, name, weights):
        """Set the default-traffic split across precision lanes, e.g.
        ``{"fp32": 0.5, "int8": 0.5}``.  Requests carrying an explicit
        `precision` (or `version`) bypass the split.  Weights are
        absolute traffic fractions: a lane absent from the dict shares
        whatever fraction the named lanes leave unassigned (so one
        ``{"int8": 0.1}`` entry canaries int8 at 10% with fp32 keeping
        90%); weights summing >= 1 leave absent lanes nothing."""
        clean = {str(k): float(v) for k, v in dict(weights).items()
                 if float(v) > 0.0}
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise KeyError("no model %r" % name)
            slot["ab"] = clean
            slot["ab_credit"] = {}

    def _retire(self, name, drain_timeout, page):
        """Drop `name` from the routing table, persist its REBUILD
        record {"lanes": [per-lane load specs in route order], "ab":
        weights}, then drain the batchers.  The pop and the record
        insert happen under ONE lock acquisition, so a request racing
        a page-out always sees either the live entry or the paged
        record — never a no_model gap.  The load-spec persistence is
        the unload contract (SERVING.md "Fleet controller"): before
        it, an unloaded model kept no record of how to rebuild its
        lane set."""
        with self._lock:
            slot = self._models.pop(name, None)
            if slot is None:
                raise KeyError("no model %r" % name)
            record = {"lanes": [], "ab": dict(slot.get("ab") or {})}
            lanes = slot.get("latest_prec") or {}
            if not lanes and slot["latest"] is not None:
                lanes = {"fp32": slot["latest"]}
            # fp32 first (sorted), so the replay's default-routing
            # shape matches the original load order
            for prec, v in sorted(lanes.items()):
                entry = slot["versions"].get(v)
                spec = getattr(entry, "load_spec", None)
                if spec:
                    record["lanes"].append(dict(spec))
            if page:
                record["paged_at"] = time.monotonic()
                self._paged[name] = record
                self._unload_specs.pop(name, None)
            else:
                self._unload_specs[name] = record
                self._paged.pop(name, None)
        for entry in slot["versions"].values():
            entry.batcher.close(drain=True, timeout=drain_timeout)
        return record

    def unload_model(self, name, drain_timeout=30.0):
        """Remove `name`: new requests fail immediately, in-flight/
        queued ones drain first.  The load spec of every precision
        lane (artifact path, placement, precision, kv_cache_dtype,
        draft/spec_k) plus the A/B weights are persisted, so
        `fault_in` can reconstruct the exact lane set later — but an
        unloaded model does NOT fault in on traffic (that is
        `page_out`'s contract)."""
        record = self._retire(name, drain_timeout, page=False)
        self.metrics.drop(name)
        obs_events.emit("model_unloaded", model=name,
                        lanes=len(record["lanes"]))

    def page_out(self, name, drain_timeout=30.0, signal=None):
        """Page `name` out to its artifact path(s): the replica sets
        drain and free their device memory, the rebuild record is kept
        PAGED, and the next request (or the fleet controller, on
        rising burn) faults the exact lane set back in.  Metrics lanes
        survive paging — counters must not reset across a page/fault
        cycle."""
        record = self._retire(name, drain_timeout, page=True)
        # the triggering signal rides the event; the emitter's own
        # fields win on key collisions (e.g. the signal's 'model')
        fields = dict(signal or {})
        fields.update(model=name, lanes=len(record["lanes"]))
        obs_events.emit("fleet_paged_out", **fields)

    def paged_models(self):
        """{name: {"age_s", "lanes"}} for every currently-paged
        model."""
        now = time.monotonic()
        with self._lock:
            return {n: {"age_s": round(now - r.get("paged_at", now), 3),
                        "lanes": len(r["lanes"])}
                    for n, r in self._paged.items()}

    def fault_in(self, name, trigger="request", signal=None):
        """Rebuild a paged/unloaded model from its persisted load
        specs: every precision lane replays through load_model (fit
        check, build, warm, flip — the COMPILE_CACHE.md store makes
        this a reload, not a recompile) and the A/B weights are
        restored, so the reconstructed lane set answers bit-exactly
        like the original.  Idempotent and burst-safe: one per-name
        lock serializes concurrent fault-ins, later arrivals find the
        model live and return immediately.  The measured wall time
        lands in `last_fault_in` (the fleet fault_in_ms gauge) and on
        the model's metrics lane."""
        with self._lock:
            if name in self._models:
                return self._entry_locked(name, None)
            lock = self._fault_locks.setdefault(name, threading.Lock())
        with lock:
            with self._lock:
                if name in self._models:  # a concurrent fault-in won
                    return self._entry_locked(name, None)
                rec = self._paged.get(name)
                if rec is None and str(trigger) != "request":
                    # traffic only resurrects PAGED models; an
                    # operator unload stays unloaded until an explicit
                    # fault_in/load — but its spec is still here
                    rec = self._unload_specs.get(name)
            if rec is None or not rec["lanes"]:
                raise KeyError(
                    "no model %r (and no persisted load spec to fault "
                    "in)" % name)
            t0 = time.monotonic()
            entry = None
            for lane_spec in rec["lanes"]:
                kw = dict(lane_spec)
                entry = self.load_model(name, kw.pop("path"), **kw)
            if rec.get("ab"):
                self.set_ab_weights(name, rec["ab"])
            ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self._paged.pop(name, None)
                self._unload_specs.pop(name, None)
            self.last_fault_in[name] = {"ms": round(ms, 3),
                                        "trigger": str(trigger),
                                        "t_mono": time.monotonic()}
            first_prec = rec["lanes"][0].get("precision") or "fp32"
            self.metrics.model(name, first_prec).note_fault_in(ms)
            fields = dict(signal or {})
            fields.update(model=name, trigger=str(trigger),
                          fault_in_ms=round(ms, 3),
                          lanes=len(rec["lanes"]))
            obs_events.emit("fleet_fault_in", **fields)
            return entry

    def resize_model(self, name, replicas, precision=None, signal=None):
        """Scale one model's replica set to `replicas` by replaying
        its persisted load spec at the new placement through
        load_model — so every resize rides the build-warm-flip
        hot-swap discipline (zero-drop by construction) and the
        ANALYSIS.md fit check gates every grow BEFORE any build work.
        Returns the new entry (the current one when already at size)."""
        n = int(replicas)
        if n < 1:
            raise ValueError("replica count must be >= 1, got %d" % n)
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise KeyError("no model %r" % name)
            lanes = slot.get("latest_prec") or {}
            prec = str(precision) if precision is not None else (
                "fp32" if "fp32" in lanes
                else (sorted(lanes)[0] if lanes else None))
            v = lanes.get(prec, slot["latest"])
            entry = slot["versions"].get(v)
        spec = getattr(entry, "load_spec", None) if entry is not None \
            else None
        if not spec:
            raise KeyError("model %r has no rebuildable load spec"
                           % name)
        old_n = len(entry.replicas)
        if n == old_n:
            return entry
        kw = dict(spec)
        path = kw.pop("path")
        kw.pop("devices", None)
        m = max(entry.mesh_sizes() or [1])
        if m > 1:
            # a mesh entry resizes in whole GROUPS: n replicas of the
            # entry's mesh size, packed over disjoint consecutive local
            # devices — the same shard-at-rest shape the original fit
            # check admitted
            import jax
            local = list(jax.local_devices())
            if n * m > len(local):
                raise ValueError(
                    "resize of mesh model %r to %d replicas needs "
                    "%d x %d = %d devices, host has %d"
                    % (name, n, n, m, n * m, len(local)))
            kw["devices"] = [
                "+".join("%s:%d" % (d.platform, d.id)
                         for d in local[i * m:(i + 1) * m])
                for i in range(n)]
        else:
            kw["replicas"] = n
        new_entry = self.load_model(name, path, **kw)
        fields = dict(signal or {})
        fields.update(model=name, precision=new_entry.precision,
                      from_replicas=old_n, to_replicas=n)
        obs_events.emit(
            "fleet_scale_up" if n > old_n else "fleet_scale_down",
            **fields)
        return new_entry

    def model_names(self):
        with self._lock:
            return sorted(self._models)

    def describe(self):
        with self._lock:
            out = {}
            for name, slot in self._models.items():
                info = {"latest": slot["latest"],
                        "versions": sorted(slot["versions"])}
                lanes = slot.get("latest_prec") or {}
                if lanes:
                    # the precision axis: which version each numerics
                    # lane routes to, plus the default-traffic split
                    info["precisions"] = dict(sorted(lanes.items()))
                    if slot.get("ab"):
                        info["ab_weights"] = dict(
                            sorted(slot["ab"].items()))
                latest = slot["versions"].get(slot["latest"])
                if latest is not None:
                    info["buckets"] = list(
                        latest.predictor.batch_buckets())
                    info["replicas"] = len(latest.replicas)
                    info["devices"] = latest.device_labels()
                    info["precision"] = latest.precision
                    sizes = latest.mesh_sizes()
                    if any(s > 1 for s in sizes):
                        # mesh replicas (SERVING.md): members per
                        # replica, in route order — serving_top's MESH
                        # column and the load reply's resolved shape
                        info["mesh"] = sizes
                        info["mesh_size"] = max(sizes)
                        # tensor-parallel compute (FLAGS.mesh_tp +
                        # a TP-splittable model): the partitioned
                        # program instead of gather-and-replicate
                        info["mesh_tp"] = any(
                            getattr(p, "tp_active", False)
                            for p in latest.replicas)
                    if latest.resource is not None:
                        # the static cost the fleet controller places
                        # by (ANALYSIS.md): per-replica peak estimate
                        # + one-step FLOPs
                        info["est_peak_mb"] = round(
                            latest.resource.peak_mb, 3)
                        info["est_flops"] = int(
                            latest.resource.total_flops)
                        if int(getattr(latest.resource, "mesh_size",
                                       1)) > 1:
                            # what each mesh MEMBER holds resident —
                            # the number the per-device fit admitted on
                            info["est_per_device_mb"] = round(
                                latest.resource.per_device_mb, 3)
                    if latest.is_decode:
                        # decode entry: buckets above are the PROMPT
                        # prefill buckets; surface the generation shape
                        info["decode"] = True
                        info["decode_slots"] = latest.batcher.n_slots
                        info["max_seq_len"] = \
                            latest.predictor.max_seq_len
                        info["eos_id"] = latest.predictor.eos_id
                        info["kv_cache_dtype"] = str(getattr(
                            latest.predictor, "kv_cache_dtype",
                            "float32"))
                        info["fuse_steps"] = int(getattr(
                            latest.batcher, "fuse_steps", 1))
                        if getattr(latest.batcher, "spec_k", 0):
                            # speculative lanes: the draft + depth the
                            # operator tuned (SERVING.md)
                            info["spec_k"] = latest.batcher.spec_k
                            info["draft"] = latest.draft_path
                else:
                    info["buckets"] = []
                out[name] = info
            now = time.monotonic()
            for name, rec in self._paged.items():
                if name in out:
                    continue
                # paged models stay visible (SERVING.md "Fleet
                # controller"): resident nowhere, but one request away
                out[name] = {
                    "paged": True,
                    "paged_age_s": round(
                        now - rec.get("paged_at", now), 3),
                    "lanes": [s.get("precision", "fp32")
                              for s in rec["lanes"]]}
            return out

    def health(self):
        """Per-model liveness readout (the `health` RPC verb's
        ``models`` section): for each precision lane's routed version,
        the batcher's thread/lane liveness (router alive, workers
        alive, last-dispatch / last-decode-step age) plus queue depth.
        Snapshot the slots under the lock, read the batchers outside it
        — liveness reads must not serialize against a hot swap."""
        with self._lock:
            snap = []
            for name, slot in self._models.items():
                lanes = dict(slot.get("latest_prec") or {})
                if not lanes and slot["latest"] is not None:
                    lanes = {"fp32": slot["latest"]}
                snap.append((name, slot["latest"],
                             sorted(slot["versions"]),
                             [(prec, v, slot["versions"].get(v))
                              for prec, v in sorted(lanes.items())]))
        out = {}
        for name, latest, versions, lanes in snap:
            minfo = {"latest": latest, "versions": versions,
                     "lanes": {}}
            for prec, v, entry in lanes:
                if entry is None:
                    continue
                li = {"version": v,
                      "queue_depth": entry.batcher.queue_depth(),
                      "decode": entry.is_decode}
                try:
                    li["liveness"] = entry.batcher.lane_liveness()
                except Exception as e:
                    li["liveness"] = {"error": "%s: %s"
                                      % (type(e).__name__, e)}
                if entry.is_decode:
                    # the freshest decode-step age across this lane set
                    # — the "is anything still making progress" number
                    ages = [l.get("last_step_age_s")
                            for l in li["liveness"].get("lanes", [])
                            if l.get("last_step_age_s") is not None]
                    li["last_decode_step_age_s"] = min(ages) \
                        if ages else None
                minfo["lanes"][prec] = li
            out[name] = minfo
        return out

    # ------------------------------------------------------------------

    def _entry_locked(self, name, version, precision=None):
        slot = self._models.get(name)
        if slot is None:
            raise KeyError("no model %r" % name)
        if version is None:
            v = self._route_version_locked(slot, name, precision)
        else:
            v = version
        entry = slot["versions"].get(v)
        if entry is None:
            raise KeyError("model %r has no version %r" % (name, v))
        return entry

    def _route_version_locked(self, slot, name, precision):
        """The precision router (QUANTIZE.md A/B axis).  An explicit
        `precision` resolves to that lane's latest (KeyError when the
        lane was never loaded).  Default traffic: with A/B weights set
        (set_ab_weights / load_model ab_weight) the pick is a smooth
        weighted round-robin over the live lanes — deterministic, no
        RNG, exact shares over any window; without weights it stays on
        the fp32 lane when one exists (loading a quantized sibling
        must not move traffic by itself), else the overall latest."""
        lanes = slot.get("latest_prec") or {}
        if precision is not None:
            v = lanes.get(str(precision))
            if v is None:
                raise KeyError(
                    "model %r has no %r precision lane (have %s)"
                    % (name, precision, sorted(lanes) or ["fp32"]))
            return v
        ab = {p: w for p, w in (slot.get("ab") or {}).items()
              if p in lanes and w > 0.0}
        if len(lanes) > 1 and ab:
            # weights are absolute traffic fractions: lanes left out of
            # the dict share the UNASSIGNED remainder, so
            # load_model(ab_weight=0.1) canaries the new lane at 10%
            # with the fp32 lane keeping the other 90% (weights summing
            # >= 1 leave nothing for unweighted lanes)
            others = [p for p in lanes if p not in ab]
            rem = max(0.0, 1.0 - sum(ab.values()))
            if others and rem > 0.0:
                for p in others:
                    ab[p] = rem / len(others)
            credit = slot.setdefault("ab_credit", {})
            total = sum(ab.values())
            for p, w in ab.items():
                credit[p] = credit.get(p, 0.0) + w
            pick = max(sorted(ab), key=lambda p: credit.get(p, 0.0))
            credit[pick] -= total
            return lanes[pick]
        if len(lanes) > 1 and "fp32" in lanes:
            return lanes["fp32"]
        return slot["latest"]

    def _fault_pending(self, name):
        """True when `name` can be (or is being) faulted in by
        traffic: it is paged, or another thread's fault-in of it is in
        flight right now (the submit that lost the race must WAIT on
        the fault lock, not bounce with no_model)."""
        with self._lock:
            if name in self._paged:
                return True
            lock = self._fault_locks.get(name)
        return lock is not None and lock.locked()

    def _submit_entry(self, entry, name, feeds, deadline, priority,
                      trace_id, max_new_tokens, chunk_tokens):
        if entry.is_decode:
            if not isinstance(feeds, dict) or "tokens" not in feeds:
                raise ValueError(
                    "decode model %r takes feeds {'tokens': "
                    "int array}, got %s"
                    % (name, sorted(feeds) if isinstance(feeds, dict)
                       else type(feeds).__name__))
            return entry.batcher.submit(
                feeds["tokens"], max_new_tokens=max_new_tokens,
                deadline=deadline, priority=priority,
                trace_id=trace_id, chunk_tokens=chunk_tokens)
        return entry.batcher.submit(feeds, deadline=deadline,
                                    priority=priority,
                                    trace_id=trace_id)

    def submit(self, name, feeds, version=None, deadline=None,
               priority=0, trace_id=None, max_new_tokens=None,
               chunk_tokens=None, precision=None):
        """Route one request; returns the batcher Future.  Resolution
        and submit happen under ONE lock acquisition so a concurrent hot
        swap can never retire a version between the two (the no-dropped-
        request guarantee: the swap's drain only starts after the flip,
        and every pre-flip submit is already queued).  `trace_id` rides
        through to the batcher's stage spans (OBSERVABILITY.md).
        `precision` pins the request to one numerics lane ('fp32' /
        'int8'); None routes by the A/B weights (see load_model).

        A PAGED model (SERVING.md "Fleet controller") faults back in
        here: the first request pays the reload (warm compile cache —
        a deserialize, not a recompile), concurrent arrivals wait on
        the same per-name fault lock, and the rebuilt lane set answers
        every one of them.

        On a DECODE entry, `feeds` must carry the prompt as "tokens";
        the returned DecodeStream duck-types the batcher Future
        (`result()` -> [generated int32 tokens]), so one-shot `infer`
        callers work unchanged — streaming callers use submit_stream."""
        try:
            with self._lock:
                entry = self._entry_locked(name, version,
                                           precision=precision)
                return self._submit_entry(entry, name, feeds, deadline,
                                          priority, trace_id,
                                          max_new_tokens, chunk_tokens)
        except KeyError:
            if not self._fault_pending(name):
                raise
        self.fault_in(name, trigger="request")
        with self._lock:
            entry = self._entry_locked(name, version,
                                       precision=precision)
            return self._submit_entry(entry, name, feeds, deadline,
                                      priority, trace_id,
                                      max_new_tokens, chunk_tokens)

    def submit_stream(self, name, tokens, version=None,
                      max_new_tokens=None, deadline=None, priority=0,
                      trace_id=None, chunk_tokens=None):
        """Streaming generation entry point: returns the DecodeStream
        whose token chunks the server's `infer_stream` verb flushes to
        the wire as they decode.  Same single-lock resolution contract
        (and paged-model fault-in) as submit()."""
        try:
            with self._lock:
                entry = self._entry_locked(name, version)
                return self._stream_entry(entry, name, tokens,
                                          max_new_tokens, deadline,
                                          priority, trace_id,
                                          chunk_tokens)
        except KeyError:
            if not self._fault_pending(name):
                raise
        self.fault_in(name, trigger="request")
        with self._lock:
            entry = self._entry_locked(name, version)
            return self._stream_entry(entry, name, tokens,
                                      max_new_tokens, deadline,
                                      priority, trace_id, chunk_tokens)

    @staticmethod
    def _stream_entry(entry, name, tokens, max_new_tokens, deadline,
                      priority, trace_id, chunk_tokens):
        if not entry.is_decode:
            raise ValueError(
                "model %r is not a decode model — infer_stream "
                "serves autoregressive artifacts only" % name)
        return entry.batcher.submit(
            tokens, max_new_tokens=max_new_tokens,
            deadline=deadline, priority=priority,
            trace_id=trace_id, chunk_tokens=chunk_tokens)

    def infer(self, name, feeds, version=None, deadline=None,
              timeout=None, priority=0, precision=None):
        """Blocking submit+wait convenience for in-process callers."""
        return self.submit(name, feeds, version=version,
                           deadline=deadline, priority=priority,
                           precision=precision).result(timeout=timeout)

    def close_all(self, drain=True, timeout=30.0):
        with self._lock:
            slots = list(self._models.values())
            self._models.clear()
        for slot in slots:
            for entry in slot["versions"].values():
                entry.batcher.close(drain=drain, timeout=timeout)
