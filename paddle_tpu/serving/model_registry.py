"""Named, versioned model registry with atomic hot swap.

The multi-tenant half of the serving runtime: each model name maps to
versioned entries (predictor + its DynamicBatcher); requests route
through a `latest` pointer.  A hot swap follows the same commit
discipline as the checkpoint vault (fluid/checkpoint.py): build the new
version completely — load artifact, construct batcher, WARM it with a
dummy batch per bucket so the first real request never eats a compile
stall — then flip `latest` under the routing lock, and only afterwards
drain and retire the displaced version.  A request that resolved the old
version before the flip completes on it (the drain waits); a request
after the flip runs the new one; no request is dropped or answered
twice.

Artifact detection: a directory containing `aot_meta.bin` is a
`save_aot` artifact (AotPredictor — no Program rebuild, no trace); any
other directory is treated as a `save_inference_model` dir served by a
live `Predictor` under `AnalysisConfig` (IR rewrites + AOT jit compile,
bucketed).
"""

import os
import threading

import numpy as np

from .batcher import DynamicBatcher
from .metrics import ServingMetrics

__all__ = ["ModelRegistry", "ModelEntry", "open_predictor"]


def open_predictor(path, buckets=None):
    """Open a serving artifact directory as the right predictor type."""
    from ..inference import AnalysisConfig, Predictor, load_aot_predictor
    if os.path.exists(os.path.join(path, "aot_meta.bin")):
        return load_aot_predictor(path)
    if not os.path.isdir(path):
        raise FileNotFoundError("no model artifact directory at %r" % path)
    config = AnalysisConfig(model_dir=path)
    if buckets:
        config.batch_size_buckets = tuple(sorted(int(b) for b in buckets))
    return Predictor(config)


class ModelEntry:
    """One (name, version): the predictor, its batcher, and its path."""

    def __init__(self, name, version, path, predictor, batcher):
        self.name = name
        self.version = version
        self.path = path
        self.predictor = predictor
        self.batcher = batcher

    def warm(self):
        """Run one zero dummy batch per bucket DIRECTLY on the predictor
        (not through the batcher — warming must not mix with traffic).
        After this, every bucket's executable is compiled/loaded and the
        first real request at any size runs at steady-state latency."""
        specs = self.predictor.feed_specs()
        buckets = self.predictor.batch_buckets() or (1,)
        batched = self.predictor.batched_feed_names()
        for cap in buckets:
            feeds = {}
            for fname, (shape, dtype) in specs.items():
                if fname in batched:
                    s = [cap if d == -1 else d for d in shape]
                else:
                    s = [1 if d == -1 else d for d in shape]
                feeds[fname] = np.zeros(tuple(s), dtype=np.dtype(dtype))
            self.predictor.run(feeds)
        return self


class ModelRegistry:
    """name -> {versions, latest} with hot swap and drain-on-retire."""

    def __init__(self, metrics=None, max_queue=None, deadline_ms=None,
                 workers=None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._max_queue = max_queue
        self._deadline_ms = deadline_ms
        self._workers = workers
        self._lock = threading.Lock()
        self._models = {}  # name -> {"versions": {v: entry}, "latest": v}

    # ------------------------------------------------------------------

    def load_model(self, name, path, version=None, warm=True,
                   buckets=None, drain_timeout=30.0):
        """Load (or hot-swap in) `path` as `name`.  Returns the entry.
        The displaced latest version, if any, is drained and retired
        AFTER the flip — in-flight requests on it complete."""
        predictor = open_predictor(path, buckets=buckets)
        batcher = DynamicBatcher(
            predictor, max_queue=self._max_queue,
            deadline_ms=self._deadline_ms, workers=self._workers,
            metrics=self.metrics.model(name))
        entry = ModelEntry(name, version, path, predictor, batcher)
        if warm:
            try:
                entry.warm()
            except BaseException:
                batcher.close(drain=False, timeout=1.0)
                raise
        displaced = None
        with self._lock:
            slot = self._models.setdefault(
                name, {"versions": {}, "latest": None})
            if version is None:
                prev = [v for v in slot["versions"] if isinstance(v, int)]
                version = entry.version = (max(prev) + 1) if prev else 1
            old_latest = slot["latest"]
            if old_latest is not None and old_latest != version:
                displaced = slot["versions"].get(old_latest)
            replaced_same = slot["versions"].get(version)
            slot["versions"][version] = entry
            slot["latest"] = version  # the atomic flip
        for old in (displaced, replaced_same):
            if old is not None and old is not entry:
                old.batcher.close(drain=True, timeout=drain_timeout)
                with self._lock:
                    slot = self._models.get(name)
                    if slot and slot["versions"].get(old.version) is old:
                        del slot["versions"][old.version]
        return entry

    def unload_model(self, name, drain_timeout=30.0):
        """Remove `name` entirely: new requests fail immediately,
        in-flight/queued ones drain first."""
        with self._lock:
            slot = self._models.pop(name, None)
        if slot is None:
            raise KeyError("no model %r" % name)
        for entry in slot["versions"].values():
            entry.batcher.close(drain=True, timeout=drain_timeout)
        self.metrics.drop(name)

    def model_names(self):
        with self._lock:
            return sorted(self._models)

    def describe(self):
        with self._lock:
            return {
                name: {"latest": slot["latest"],
                       "versions": sorted(slot["versions"]),
                       "buckets": list(
                           slot["versions"][slot["latest"]]
                           .predictor.batch_buckets())
                       if slot["latest"] in slot["versions"] else []}
                for name, slot in self._models.items()}

    # ------------------------------------------------------------------

    def submit(self, name, feeds, version=None, deadline=None):
        """Route one request; returns the batcher Future.  Resolution
        and submit happen under ONE lock acquisition so a concurrent hot
        swap can never retire a version between the two (the no-dropped-
        request guarantee: the swap's drain only starts after the flip,
        and every pre-flip submit is already queued)."""
        with self._lock:
            slot = self._models.get(name)
            if slot is None:
                raise KeyError("no model %r" % name)
            v = slot["latest"] if version is None else version
            entry = slot["versions"].get(v)
            if entry is None:
                raise KeyError("model %r has no version %r" % (name, v))
            return entry.batcher.submit(feeds, deadline=deadline)

    def infer(self, name, feeds, version=None, deadline=None,
              timeout=None):
        """Blocking submit+wait convenience for in-process callers."""
        return self.submit(name, feeds, version=version,
                           deadline=deadline).result(timeout=timeout)

    def close_all(self, drain=True, timeout=30.0):
        with self._lock:
            slots = list(self._models.values())
            self._models.clear()
        for slot in slots:
            for entry in slot["versions"].values():
                entry.batcher.close(drain=drain, timeout=timeout)
