"""Program verifier passes.

Five read-only analysis passes over the Program IR, registered on the
fluid/ir_passes.py Pass substrate (so ``get_pass("verify_shapes_pass")``
works like any rewrite pass) but subclassing :class:`AnalysisPass`,
which collects :class:`Diagnostic` records instead of mutating the graph
— and deliberately does NOT bump the program version, so verifying a
program never invalidates an executor's compiled-step cache.

Checks and their diagnostic ids:

  verify_use_before_def_pass   use-before-def [error]    a var read by an
      op before any op defined it (and it is not a feed / data var /
      persistable); undefined-var [error] when the name resolves nowhere
      in the block hierarchy.  Cross-block: sub-blocks see what their
      parent defined *before* the owning op; writes a sub-block makes to
      parent vars count as definitions after the owning op.  Loop bodies
      (while / recurrent) are seeded with every name the body writes —
      iteration N legitimately reads what iteration N-1 wrote, so only
      reads no iteration could satisfy are flagged.

  verify_shapes_pass   shape-mismatch [error], dtype-mismatch [error],
      unregistered-op [error].  Static shape/dtype propagation: each op
      whose input shapes are fully recorded is abstractly evaluated via
      its registered lowering under jax.eval_shape (the registry's
      infer_shape machinery, run in *checking* mode: a lowering that
      raises, or disagrees with the recorded output var, is a diagnostic
      instead of a silent skip).

  verify_dead_code_pass   dead-op [warning], unused-var [warning].
      With fetches known, backward reachability from fetches +
      side-effecting ops (host / stateful / persistable-writing /
      control-flow); without fetches, only vars that no op touches are
      reported (any terminal op could be somebody's fetch target).

  verify_fetch_reachability_pass   unknown-fetch [error],
      unreachable-fetch [error], unused-feed [warning].  Forward
      dataflow from feeds + persistables + data vars.

  verify_aot_export_pass   aot-unexportable [warning], aot-ineligible
      [warning].  Predicts — before any tracing — the compile cache's
      ``_UNEXPORTABLE`` fallback (host ops cannot ride jax.export, see
      inference/predictor.py) and the executor's ``_aot_cache_eligible``
      gate (multi-block / *_grad / optimizer ops, executor.py), so a
      serving artifact that will silently recompile every boot is
      flagged at build time (COMPILE_CACHE.md).
"""

import collections

from ..fluid.ir_passes import Pass, register_pass

__all__ = ["Diagnostic", "ProgramVerificationError", "AnalysisPass",
           "verify_program", "verify_program_cached", "check_program",
           "ANALYSIS_PASSES"]


class Diagnostic:
    """One finding, locatable: block idx / op index / op type / var."""

    __slots__ = ("check", "severity", "block", "op_index", "op_type",
                 "var", "message")

    def __init__(self, check, severity, message, block=None, op_index=None,
                 op_type=None, var=None):
        self.check = check
        self.severity = severity          # "error" | "warning"
        self.message = message
        self.block = block
        self.op_index = op_index
        self.op_type = op_type
        self.var = var

    @property
    def is_error(self):
        return self.severity == "error"

    def where(self):
        parts = []
        if self.block is not None:
            parts.append("block %d" % self.block)
        if self.op_index is not None:
            parts.append("op %d" % self.op_index)
        if self.op_type:
            parts.append("(%s)" % self.op_type)
        if self.var:
            parts.append("var '%s'" % self.var)
        return " ".join(parts)

    def __repr__(self):
        w = self.where()
        return "%s[%s] %s%s" % (self.severity, self.check,
                                w + ": " if w else "", self.message)

    __str__ = __repr__


class ProgramVerificationError(RuntimeError):
    """The program verifier found error-severity findings.  Carries the
    full diagnostic list (``.diagnostics``)."""

    def __init__(self, diagnostics, what="program"):
        self.diagnostics = list(diagnostics)
        errs = [d for d in self.diagnostics if d.is_error]
        lines = ["%s failed verification: %d error(s), %d warning(s)"
                 % (what, len(errs), len(self.diagnostics) - len(errs))]
        lines += ["  " + str(d) for d in self.diagnostics]
        super().__init__("\n".join(lines))


class AnalysisPass(Pass):
    """Read-only pass: collects diagnostics, never mutates the program —
    and never bumps the program version (a verify must not invalidate
    the executor's (id, version)-keyed compiled-step cache)."""

    def apply(self, program):
        diags = self.attrs.setdefault("diagnostics", [])
        self.analyze(program, diags)
        return program

    def analyze(self, program, diagnostics):
        raise NotImplementedError

    def diagnostics(self):
        return list(self.attrs.get("diagnostics", ()))

    # -- shared graph helpers ------------------------------------------

    @staticmethod
    def _known_defined(block, name, feeds):
        """Defined without any op running: a feed, a data var, or a
        persistable (params/buffers the scope carries across steps)."""
        if feeds and name in feeds:
            return True
        v = block._find_var_recursive(name)
        if v is None:
            return None                       # resolves nowhere
        return bool(v.persistable or v.is_data)

    @staticmethod
    def _subtree_writes(block, acc=None):
        """Every name written by any op in `block` or its sub-blocks."""
        acc = acc if acc is not None else set()
        for op in block.ops:
            acc.update(n for n in op.output_arg_names if n)
            sub = op.attrs.get("sub_block")
            if sub is not None:
                AnalysisPass._subtree_writes(sub, acc)
        return acc

    @staticmethod
    def _external_reads(block):
        """Names `block`'s subtree reads that no earlier op in the same
        subtree wrote — i.e. reads satisfied by the parent scope."""
        local = set()
        reads = []
        for op in block.ops:
            for n in op.input_arg_names:
                if n and n not in local:
                    reads.append(n)
            sub = op.attrs.get("sub_block")
            if sub is not None:
                reads.extend(n for n in AnalysisPass._external_reads(sub)
                             if n not in local)
            local.update(n for n in op.output_arg_names if n)
        return reads


# loop-shaped sub-block owners: iteration N reads what iteration N-1
# wrote, so ordered-walk use-before-def does not apply inside the body
_LOOP_OPS = frozenset(["while", "recurrent"])

# sub-block vars the owning op's execution harness injects into the step
# environment (they are defined by the lowering, not by any op): the
# recurrent op's per-step sequence slices, previous-state memories, and
# pass-through external params (ops/control_flow_ops.py _recurrent)
_SUB_BLOCK_INJECTED_ATTRS = {
    "recurrent": ("seq_input_names", "state_prev_names", "param_names"),
}


def _is_side_effecting(op):
    """Ops that must stay live regardless of dataflow: host side effects
    (RPC/IO/py_func), stateful lowerings, control flow (its sub-block
    may write parent vars the op does not declare), optimizer updates."""
    from ..fluid import functionalizer
    from ..ops import registry as op_registry
    if op.attrs.get("sub_block") is not None:
        return True
    if functionalizer.is_host_op(op):
        return True
    od = op_registry._REGISTRY.get(op.type)
    if od is not None and od.stateful:
        return True
    return False


@register_pass
class VerifyUseBeforeDefPass(AnalysisPass):
    name = "verify_use_before_def_pass"

    def analyze(self, program, diagnostics):
        feeds = frozenset(self.get("feeds") or ())
        self._walk(program.global_block(), set(), feeds, diagnostics)

    def _walk(self, block, defined, feeds, out):
        defined = set(defined)
        for idx, op in enumerate(block.ops):
            for slot, names in op.inputs.items():
                for name in names:
                    if not name or name in defined:
                        continue
                    known = self._known_defined(block, name, feeds)
                    if known:
                        defined.add(name)
                        continue
                    if known is None:
                        out.append(Diagnostic(
                            "undefined-var", "error",
                            "input %s reads '%s', which exists nowhere "
                            "in the block hierarchy" % (slot, name),
                            block=block.idx, op_index=idx,
                            op_type=op.type, var=name))
                    else:
                        out.append(Diagnostic(
                            "use-before-def", "error",
                            "input %s read before any op defines it "
                            "(not a feed/data var, not persistable)"
                            % slot,
                            block=block.idx, op_index=idx,
                            op_type=op.type, var=name))
                    defined.add(name)     # report each name once
            sub = op.attrs.get("sub_block")
            if sub is not None:
                inner = defined | {n for n in op.input_arg_names if n}
                for attr in _SUB_BLOCK_INJECTED_ATTRS.get(op.type, ()):
                    inner.update(n for n in (op.attrs.get(attr) or ())
                                 if n)
                if op.type in _LOOP_OPS:
                    inner |= self._subtree_writes(sub)
                self._walk(sub, inner, feeds, out)
                # writes the sub-block makes to parent-scope vars are
                # visible after the owning op (conditional_block outputs
                # are undeclared on the op itself)
                defined |= self._subtree_writes(sub)
            defined.update(n for n in op.output_arg_names if n)


# op types verify_shapes skips: their lowerings need the interpreter
# environment (arrays / control flow write results into env), concrete
# index values, or host execution — the registry's infer_shape skips
# them for the same reason (each entry names why)
#
# NOT here by design: the quantized-inference ops (dequant_mul,
# dequant_conv2d, dequant_lookup_table — ops/quant_ops.py).  They are
# ordinary registry lowerings that evaluate abstractly (the int8 weight
# and fp32 scale are plain ShapeDtypeStructs; the Pallas dequant-matmul
# traces in interpret mode off-TPU), so quantized artifacts go through
# verify_shapes_pass like any other program — no `unregistered-op`
# findings and full shape/dtype checking of the PTQ rewrite
# (QUANTIZE.md; tools/lint_program.py additionally CRCs the payloads).
_EVAL_SKIP_TYPES = frozenset([
    "while", "conditional_block", "recurrent",   # env-mutating control flow
    "while_grad_dynamic",                        # host replay
    "write_to_array", "read_from_array",         # env arrays + concrete I
    "array_length", "array_to_lod_tensor",       # env arrays
    "lod_tensor_to_array", "max_sequence_len",   # env arrays / lod companion
    "go", "channel_create", "channel_send",      # CSP: real channels/threads
    "channel_recv", "channel_close",
])


def _dtype_family(np_dtype):
    import numpy as np
    k = np.dtype(np_dtype).kind
    if k == "f":
        return "float"
    if k in "iub":
        return "int"           # int/uint/bool interchange is tolerated
    return k


@register_pass
class VerifyShapesPass(AnalysisPass):
    name = "verify_shapes_pass"

    def analyze(self, program, diagnostics):
        # vars with multiple writers (assign-style re-binding) carry the
        # LAST writer's recorded shape — comparing an earlier writer's
        # inferred output against it would be a false conflict
        writers = collections.Counter()
        for block in program.blocks:
            for op in block.ops:
                writers.update(n for n in op.output_arg_names if n)
        for block in program.blocks:
            for idx, op in enumerate(block.ops):
                self._check_op(block, idx, op, writers, diagnostics)

    @staticmethod
    def _dims_conflict(rec, inf):
        if rec is None or inf is None:
            return False
        # squeeze unit dims before comparing: the IR tolerates rank-0 vs
        # rank-1 scalars (mean's () loss vs fill_constant's (1,) seed)
        # and keepdim variations — those execute fine under broadcasting
        rec = [d for d in rec if d is None or int(d) != 1]
        inf = [d for d in inf if d is None or int(d) != 1]
        if len(rec) != len(inf):
            return True
        for a, b in zip(rec, inf):
            if a is None or b is None or int(a) < 0 or int(b) < 0:
                continue        # dynamic dim matches anything
            if int(a) != int(b):
                return True
        return False

    def _check_op(self, block, idx, op, writers, out):
        from ..fluid import core as fcore
        from ..fluid import functionalizer
        from ..ops import registry as op_registry
        from ..ops.optimizer_ops import MERGEABLE_OPT_OPS

        if functionalizer.is_host_op(op) or \
                op.attrs.get("sub_block") is not None:
            return      # interpreted by the host/segmented path
        od = op_registry._REGISTRY.get(op.type)
        if od is None:
            if op.type.endswith("_grad") and (
                    "fwd_uid" in op.attrs
                    or op_registry.has_op(op.type[:-len("_grad")])):
                # generic vjp-based grad op: executed from the forward
                # op's stashed closure, no standalone lowering to check
                return
            out.append(Diagnostic(
                "unregistered-op", "error",
                "op type has no registered lowering — the executor "
                "will refuse this program", block=block.idx,
                op_index=idx, op_type=op.type))
            return
        if (op.type in _EVAL_SKIP_TYPES or op.type in MERGEABLE_OPT_OPS
                or od.custom_infer_shape is not None):
            return
        import jax
        dummy = op_registry._pick_dummy(op, block)
        in_structs = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                v = block._find_var_recursive(n)
                if v is None or v.shape is None:
                    return          # inputs not fully recorded: no claim
                vals.append(jax.ShapeDtypeStruct(
                    op_registry._subst_dummy(v.shape, dummy),
                    fcore.convert_dtype_to_np(v.dtype)))
            in_structs[slot] = vals
        try:
            inferred = jax.eval_shape(
                lambda ins: od.lower(op_registry.ExecContext(
                    op, ins, step=0, seed=0)), in_structs)
        except Exception as e:
            msg = str(e).strip().splitlines()
            out.append(Diagnostic(
                "shape-mismatch", "error",
                "lowering rejects the recorded input shapes/dtypes: "
                "%s: %s" % (type(e).__name__,
                            msg[0] if msg else "<no message>"),
                block=block.idx, op_index=idx, op_type=op.type,
                var=(op.input_arg_names or [None])[0]))
            return
        if inferred is None:
            return
        for slot, vals in inferred.items():
            names = op.outputs.get(slot, [])
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, s in zip(names, vals):
                v = block._find_var_recursive(n)
                if v is None or s is None or v.shape is None or \
                        writers[n] > 1:
                    continue
                inf_shape = op_registry._restore_dummy(
                    s.shape, True, dummy)
                if self._dims_conflict(v.shape, inf_shape):
                    out.append(Diagnostic(
                        "shape-mismatch", "error",
                        "output %s: recorded shape %s but the lowering "
                        "produces %s" % (slot, tuple(v.shape),
                                         tuple(inf_shape)),
                        block=block.idx, op_index=idx, op_type=op.type,
                        var=n))
                    continue
                rec_np = fcore.convert_dtype_to_np(v.dtype)
                if _dtype_family(rec_np) != _dtype_family(s.dtype):
                    out.append(Diagnostic(
                        "dtype-mismatch", "error",
                        "output %s: recorded dtype %s but the lowering "
                        "produces %s" % (slot, rec_np.__name__
                                         if hasattr(rec_np, "__name__")
                                         else rec_np, s.dtype),
                        block=block.idx, op_index=idx, op_type=op.type,
                        var=n))


@register_pass
class VerifyDeadCodePass(AnalysisPass):
    name = "verify_dead_code_pass"

    def analyze(self, program, diagnostics):
        fetches = tuple(self.get("fetches") or ())
        feeds = frozenset(self.get("feeds") or ())
        blk = program.global_block()
        if fetches:
            self._dead_ops(blk, fetches, diagnostics)
        self._unused_vars(program, feeds, fetches, diagnostics)

    def _dead_ops(self, blk, fetches, out):
        needed = set(fetches)
        live = [False] * len(blk.ops)
        for i in range(len(blk.ops) - 1, -1, -1):
            op = blk.ops[i]
            outputs = set(n for n in op.output_arg_names if n)
            writes_persistable = any(
                getattr(blk._find_var_recursive(n), "persistable", False)
                for n in outputs)
            if (outputs & needed) or writes_persistable or \
                    _is_side_effecting(op):
                live[i] = True
                needed.update(n for n in op.input_arg_names if n)
                sub = op.attrs.get("sub_block")
                if sub is not None:
                    needed.update(self._external_reads(sub))
        for i, op in enumerate(blk.ops):
            if not live[i]:
                out.append(Diagnostic(
                    "dead-op", "warning",
                    "no fetch is reachable from its outputs %s — the "
                    "op costs compile time and (if not DCE'd by XLA) "
                    "step time for nothing"
                    % sorted(n for n in op.output_arg_names if n),
                    block=blk.idx, op_index=i, op_type=op.type,
                    var=(op.output_arg_names or [None])[0]))

    def _unused_vars(self, program, feeds, fetches, out):
        fetch_set = set(fetches)
        for block in program.blocks:
            touched = set()
            for op in block.ops:
                touched.update(n for n in op.input_arg_names if n)
                touched.update(n for n in op.output_arg_names if n)
                sub = op.attrs.get("sub_block")
                if sub is not None:
                    touched.update(self._external_reads(sub))
                    touched.update(self._subtree_writes(sub))
            for name, v in block.vars.items():
                if name in touched or name in feeds or \
                        name in fetch_set or v.persistable or v.is_data:
                    continue
                out.append(Diagnostic(
                    "unused-var", "warning",
                    "declared but no op reads or writes it (stale var "
                    "table entry)", block=block.idx, var=name))


@register_pass
class VerifyFetchReachabilityPass(AnalysisPass):
    name = "verify_fetch_reachability_pass"

    def analyze(self, program, diagnostics):
        feeds = tuple(self.get("feeds") or ())
        fetches = tuple(self.get("fetches") or ())
        if not fetches:
            return
        blk = program.global_block()
        defined = set(feeds)
        consumed = set()
        for v in program.list_vars():
            if v.persistable or v.is_data:
                defined.add(v.name)
        for op in blk.ops:
            ins = [n for n in op.input_arg_names if n]
            consumed.update(ins)
            sub = op.attrs.get("sub_block")
            if sub is not None:
                consumed.update(self._external_reads(sub))
            if all(n in defined for n in ins):
                defined.update(n for n in op.output_arg_names if n)
                if sub is not None:
                    defined |= self._subtree_writes(sub)
        for f in fetches:
            if blk._find_var_recursive(f) is None:
                diagnostics.append(Diagnostic(
                    "unknown-fetch", "error",
                    "fetch target exists nowhere in the program",
                    block=blk.idx, var=f))
            elif f not in defined:
                diagnostics.append(Diagnostic(
                    "unreachable-fetch", "error",
                    "no dataflow path from the feeds/persistables "
                    "produces this fetch", block=blk.idx, var=f))
        for f in feeds:
            if f not in consumed and f not in fetches:
                diagnostics.append(Diagnostic(
                    "unused-feed", "warning",
                    "declared as a feed but no op consumes it",
                    block=blk.idx, var=f))


@register_pass
class VerifyAotExportPass(AnalysisPass):
    name = "verify_aot_export_pass"

    def analyze(self, program, diagnostics):
        from ..fluid import functionalizer
        from ..ops.optimizer_ops import MERGEABLE_OPT_OPS
        opt = frozenset(MERGEABLE_OPT_OPS)
        training = []            # (block, idx, type) — summarized as ONE
        for block in program.blocks:
            for idx, op in enumerate(block.ops):
                if functionalizer.is_host_op(op):
                    diagnostics.append(Diagnostic(
                        "aot-unexportable", "warning",
                        "host op: jax.export cannot serialize it, so "
                        "the persistent compile cache will fall back "
                        "to direct compilation (_UNEXPORTABLE) and the "
                        "executor takes the segmented eager path",
                        block=block.idx, op_index=idx, op_type=op.type))
                elif op.type.endswith("_grad") or op.type in opt:
                    training.append((block.idx, idx, op.type))
        if training:
            b, i, t = training[0]
            diagnostics.append(Diagnostic(
                "aot-ineligible", "warning",
                "%d training op(s): the executor's persistent compile "
                "cache only serves inference-shaped programs "
                "(_aot_cache_eligible gate)" % len(training),
                block=b, op_index=i, op_type=t))
        if program.num_blocks > 1:
            diagnostics.append(Diagnostic(
                "aot-ineligible", "warning",
                "%d blocks: the executor's persistent compile cache "
                "requires a single-block program (_aot_cache_eligible "
                "gate)" % program.num_blocks))


ANALYSIS_PASSES = (
    "verify_use_before_def_pass",
    "verify_shapes_pass",
    "verify_dead_code_pass",
    "verify_fetch_reachability_pass",
    "verify_aot_export_pass",
)


def verify_program(program, feeds=None, fetches=None, passes=None,
                   emit_events=True, what=None):
    """Run the analysis passes over `program`; returns [Diagnostic].

    `feeds`/`fetches` sharpen the analysis (dead-op and reachability
    need fetch roots; use-before-def treats feeds as defined).  Each
    finding is also emitted as a ``verify_finding`` obs event so the
    structured log records what the verifier said about an artifact at
    its build/load boundary (OBSERVABILITY.md)."""
    from ..fluid.ir_passes import get_pass
    feeds = tuple(feeds or ())
    fetches = tuple(fetches or ())
    diags = []
    for name in (passes or ANALYSIS_PASSES):
        p = get_pass(name, feeds=feeds, fetches=fetches)
        p.apply(program)
        diags.extend(p.diagnostics())
    if emit_events and diags:
        from ..obs import events as obs_events
        for d in diags:
            obs_events.emit("verify_finding", check=d.check,
                            severity=d.severity, what=what,
                            block=d.block, op_index=d.op_index,
                            op_type=d.op_type, var=d.var,
                            message=d.message)
    return diags


def check_program(program, feeds=None, fetches=None, passes=None,
                  what="program", warn=True):
    """verify_program + policy: error findings raise
    ProgramVerificationError; warnings go to warnings.warn (once per
    call).  Returns the diagnostics on success."""
    import warnings as _warnings
    diags = verify_program(program, feeds=feeds, fetches=fetches,
                           passes=passes, what=what)
    if any(d.is_error for d in diags):
        raise ProgramVerificationError(diags, what=what)
    if warn and diags:
        _warnings.warn(
            "program verifier: %d warning(s) for %s:\n%s"
            % (len(diags), what,
               "\n".join("  " + str(d) for d in diags)),
            RuntimeWarning, stacklevel=2)
    return diags


# bounded memo for the FLAGS.verify_program pre-run check: verification
# happens at build/load, never per step — keyed by program identity +
# version + the feed/fetch signature of the run
_VERIFY_MEMO = collections.OrderedDict()
_VERIFY_MEMO_CAP = 128


def verify_program_cached(program, feeds=None, fetches=None,
                          what="program"):
    """Memoized check_program for executor hot paths: the first run of a
    (program version, feeds, fetches) signature pays the analysis; every
    later step is one dict hit.  Raises ProgramVerificationError on
    error findings (and re-raises the cached error on repeat runs —
    a failing program stays failing until it changes)."""
    key = (id(program), program._version, tuple(feeds or ()),
           tuple(fetches or ()))
    hit = _VERIFY_MEMO.get(key)
    if hit is not None:
        _VERIFY_MEMO.move_to_end(key)
        if isinstance(hit, ProgramVerificationError):
            raise hit
        return hit
    try:
        diags = check_program(program, feeds=feeds, fetches=fetches,
                              what=what)
    except ProgramVerificationError as e:
        _VERIFY_MEMO[key] = e
        raise
    finally:
        while len(_VERIFY_MEMO) > _VERIFY_MEMO_CAP:
            _VERIFY_MEMO.popitem(last=False)
    _VERIFY_MEMO[key] = diags
    return diags


def check_serialized_cached(program, content, feeds=None, fetches=None,
                            what="program"):
    """Artifact-boundary memo keyed by the program's serialized CONTENT
    (sha256) — save/load_inference_model verify unconditionally, but a
    serving registry warm, hot-swap flip, or replica build loads the
    same artifact many times: one analysis per distinct
    (artifact bytes, feeds, fetches), every repeat a dict hit.  Raises
    the memoized ProgramVerificationError on repeat failures."""
    import hashlib
    key = ("sha", hashlib.sha256(content.encode()).hexdigest(),
           tuple(feeds or ()), tuple(fetches or ()))
    hit = _VERIFY_MEMO.get(key)
    if hit is not None:
        _VERIFY_MEMO.move_to_end(key)
        if isinstance(hit, ProgramVerificationError):
            raise hit
        return hit
    try:
        diags = check_program(program, feeds=feeds, fetches=fetches,
                              what=what)
    except ProgramVerificationError as e:
        _VERIFY_MEMO[key] = e
        raise
    finally:
        while len(_VERIFY_MEMO) > _VERIFY_MEMO_CAP:
            _VERIFY_MEMO.popitem(last=False)
    _VERIFY_MEMO[key] = diags
    return diags
