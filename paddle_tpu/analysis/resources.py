"""Static resource & cost analysis — liveness-based memory planning and
a per-op FLOP/byte roofline model over the Program IR.

The PR 9 verifier proves a Program is *correct* before it runs; this
module answers the two questions every placement decision starts with —
does it FIT, and how fast can it possibly GO — without running it.  The
Julia-to-TPU compiler paper treats whole-program shape inference as a
compilability precondition; here the same static shapes are folded into
byte and FLOP counts, so ROOFLINE.md's *measured* ceilings get a
*predicted* twin per program (ANALYSIS.md "Resource analysis").

Three read-only passes on the fluid/ir_passes.py Pass substrate (same
AnalysisPass discipline as the verifier — never mutates, never bumps
the program version):

  analyze_liveness_pass     per-var lifetime intervals over the
      linearized global-block op order.  Persistables are pinned for
      the whole program (params/buffers the scope carries); feeds and
      data vars are live from op 0; everything else lives
      [first write, last read] (fetches extend to the end).  A
      sub-block's locals are LOOP-RESIDENT: a while/recurrent body's
      working set exists for the whole owning op, so the entire
      subtree's vars count at that op's point in the timeline.

  analyze_memory_plan_pass  folds the intervals into a per-op live-byte
      timeline and its peak: ``peak_bytes = param_bytes + max over ops
      of (live activations + loop-resident state)``.  Var bytes come
      from ``Variable.nbytes_hint`` — dtype-accurate, so an int8
      quantized program statically shows its ~0.3x weight footprint
      with zero special cases.

  analyze_cost_pass         per-op FLOP and HBM-byte estimates over the
      registered lowerings (a formula table for the matmul/conv-class
      ops; element-count defaults elsewhere), rolled up into a static
      roofline: arithmetic intensity, and a time lower bound
      ``max(flops/peak_flops, bytes/peak_bw)`` against the device peaks
      table below.

``analyze_program`` runs all three and returns a typed
:class:`ResourceReport`; ``analyze_artifact`` does the same for a saved
artifact dir — save_inference_model (fp32 or quantized) via its
Program, decode artifacts (decode_meta.bin) via their meta record plus
the slot-table KV-cache bytes, save_aot dirs via their state payload.
``check_fit`` is the serving admission gate model_registry.load_model
runs per replica BEFORE any build/warm work (SERVING.md).
"""

import json
import os

from ..fluid.ir_passes import register_pass
from .verifier import AnalysisPass

__all__ = [
    "ResourceReport", "ResourceFitError", "analyze_program",
    "analyze_artifact", "check_fit", "device_memory_bytes",
    "device_peaks", "RESOURCE_PASSES",
]


# ---------------------------------------------------------------------------
# device peaks — the denominator of the static roofline
# ---------------------------------------------------------------------------

# (device_kind substring, peak FLOP/s dense bf16, HBM bytes/s
# practically attainable, HBM capacity bytes).  The v5e row matches
# ROOFLINE.md's measured basis (197 TFLOP/s peak, ~819 GB/s attainable,
# 16 GiB); other TPU rows are public datasheet numbers.  The cpu row is
# a deliberately round smoke-lane placeholder — predictions on CPU are
# for exercising the machinery, not for believing.
_DEVICE_PEAKS = (
    ("v5 lite", 197e12, 819e9, 16 << 30),
    ("v5e", 197e12, 819e9, 16 << 30),
    ("v5p", 459e12, 2765e9, 95 << 30),
    ("v4", 275e12, 1228e9, 32 << 30),
    ("v3", 123e12, 900e9, 32 << 30),
    ("v2", 45e12, 700e9, 8 << 30),
    ("cpu", 1e11, 20e9, 0),
)


def device_peaks(device=None):
    """{kind, peak_flops, hbm_bytes_per_s, hbm_bytes} for `device` (a
    jax.Device or None for the default device).  Unknown kinds get the
    cpu placeholder row."""
    kind = ""
    if device is not None:
        kind = "%s %s" % (getattr(device, "platform", ""),
                          getattr(device, "device_kind", ""))
    else:
        try:
            import jax
            devs = jax.devices()
            if devs:
                kind = "%s %s" % (devs[0].platform, devs[0].device_kind)
        except Exception:
            kind = "cpu"
    low = kind.lower()
    for sub, flops, bw, mem in _DEVICE_PEAKS:
        if sub in low:
            return {"kind": kind, "peak_flops": flops,
                    "hbm_bytes_per_s": bw, "hbm_bytes": mem}
    return {"kind": kind or "cpu", "peak_flops": _DEVICE_PEAKS[-1][1],
            "hbm_bytes_per_s": _DEVICE_PEAKS[-1][2], "hbm_bytes": 0}


def device_memory_bytes(device=None):
    """Per-replica memory budget for the admission fit check, or None
    when no budget is known (the check then passes trivially).

    Resolution order: ``FLAGS.serving_device_mem_mb`` (> 0: the
    operator's configured budget — the deterministic/testable path);
    the device's own ``memory_stats()['bytes_limit']`` when the backend
    exposes one; the peaks table's HBM capacity for recognized TPU
    kinds.  CPU with no configured flag returns None — host RAM is not
    a serving budget."""
    from ..flags import FLAGS
    mb = int(FLAGS.serving_device_mem_mb)
    if mb > 0:
        return mb << 20
    try:
        if device is not None and hasattr(device, "memory_stats"):
            stats = device.memory_stats() or {}
            limit = stats.get("bytes_limit")
            if limit:
                return int(limit)
    except Exception:
        pass
    peaks = device_peaks(device)
    return int(peaks["hbm_bytes"]) or None


class ResourceFitError(RuntimeError):
    """A model's static per-replica peak-memory estimate exceeds the
    device budget — raised by the serving admission gate BEFORE any
    build/warm work.  Carries ``estimated_bytes`` / ``available_bytes``
    and names both in the message."""

    def __init__(self, what, estimated_bytes, available_bytes,
                 device=None):
        self.what = what
        self.estimated_bytes = int(estimated_bytes)
        self.available_bytes = int(available_bytes)
        self.device = device
        super().__init__(
            "%s does not fit: estimated peak %.1f MiB exceeds the "
            "%.1f MiB device budget%s (estimate %d bytes vs %d "
            "available; raise FLAGS.serving_device_mem_mb or shrink "
            "the placement)"
            % (what, estimated_bytes / (1 << 20),
               available_bytes / (1 << 20),
               " on %s" % device if device is not None else "",
               self.estimated_bytes, self.available_bytes))


# ---------------------------------------------------------------------------
# the typed report
# ---------------------------------------------------------------------------

class ResourceReport:
    """What the static analyzer says about one program/artifact.

    Bytes:  ``param_bytes`` (persistables, dtype-accurate),
    ``activation_peak_bytes`` (max live non-persistable bytes over the
    timeline), ``kv_cache_bytes`` (decode slot table; 0 elsewhere),
    ``peak_bytes`` = params + activation peak + kv cache.
    ``actual_param_bytes`` is filled by ``analyze_artifact`` from the
    on-disk payloads so est-vs-actual is one subtraction.

    Cost:  ``total_flops``, ``total_bytes`` (estimated HBM traffic of
    one step), ``arithmetic_intensity``, ``est_step_ms`` — the roofline
    time lower bound against ``device`` (peaks table row).

    Tables:  ``ops`` (one row per op: block, index, type, est_flops,
    est_bytes, live_bytes), ``per_block`` roll-ups, and
    ``top_contributors`` — the vars holding the most bytes at the peak
    op.  Everything is plain data; ``to_dict()`` is wire-encodable.
    """

    __slots__ = ("what", "batch", "param_bytes", "activation_peak_bytes",
                 "kv_cache_bytes", "actual_param_bytes", "total_flops",
                 "total_bytes", "device", "ops", "per_block",
                 "top_contributors", "peak_op", "n_ops", "precision",
                 "mesh_size", "tp")

    def __init__(self, what="program", batch=1):
        self.what = what
        self.batch = int(batch)
        self.param_bytes = 0
        self.activation_peak_bytes = 0
        self.kv_cache_bytes = 0
        self.actual_param_bytes = None
        self.total_flops = 0
        self.total_bytes = 0
        self.device = device_peaks(None)
        self.ops = []
        self.per_block = []
        self.top_contributors = []
        self.peak_op = None
        self.n_ops = 0
        self.precision = "fp32"
        # devices per replica (SERVING.md "Mesh replicas"): params + KV
        # shard at rest over the mesh, so the PER-DEVICE resident
        # estimate divides by this while activations (replicated
        # compute) do not
        self.mesh_size = 1
        # tensor-parallel compute (SERVING.md "Tensor-parallel
        # compute"): when True, per-STEP traffic also divides by the
        # mesh — each member streams only its resident shard per token,
        # instead of gathering and re-reading the whole model
        self.tp = False

    @property
    def peak_bytes(self):
        return (self.param_bytes + self.activation_peak_bytes
                + self.kv_cache_bytes)

    @property
    def peak_mb(self):
        return self.peak_bytes / float(1 << 20)

    def per_device_bytes(self, mesh_size=None):
        """Estimated resident bytes on EACH member device of a
        `mesh_size`-device replica (default: the report's own
        ``mesh_size``): params + KV cache shard ~1/mesh (ceil), the
        replicated-compute activation peak does not.  mesh_size 1 is
        exactly ``peak_bytes`` — the single-device admission number."""
        m = max(int(self.mesh_size if mesh_size is None else mesh_size),
                1)
        if m == 1:
            return int(self.peak_bytes)
        sharded = int(self.param_bytes) + int(self.kv_cache_bytes)
        return -(-sharded // m) + int(self.activation_peak_bytes)

    @property
    def per_device_mb(self):
        return self.per_device_bytes() / float(1 << 20)

    def per_device_step_bytes(self, mesh_size=None, tp=None):
        """Estimated per-STEP HBM traffic on EACH member device of a
        `mesh_size`-device replica (defaults: the report's own stamped
        ``mesh_size`` / ``tp``).

        Gather mode (tp False — PR 18's replicate-compute contract):
        every member materializes and streams the WHOLE model per step,
        so the per-member traffic is ``total_bytes`` regardless of
        mesh size — sharding at rest buys capacity, not bandwidth.
        Tensor-parallel (tp True): the partitioned program touches only
        the member's resident shard — ceil(total_bytes / m).  This is
        the decode-bandwidth roofline column (ROOFLINE.md) and the
        modeled-bytes basis of bench_serving's --mesh_tp A/B."""
        m = max(int(self.mesh_size if mesh_size is None else mesh_size),
                1)
        t = self.tp if tp is None else bool(tp)
        total = int(self.total_bytes)
        if m == 1 or not t:
            return total
        return -(-total // m)

    def per_device_step_ms(self, mesh_size=None, tp=None):
        """Per-member roofline time lower bound for one step.  Under
        tensor parallelism both the FLOPs and the streamed bytes divide
        by the mesh (each member computes its head/column slice on its
        resident shard); gather mode keeps the single-device number —
        every member does the full step."""
        m = max(int(self.mesh_size if mesh_size is None else mesh_size),
                1)
        t = self.tp if tp is None else bool(tp)
        flops = self.total_flops / float(m if (t and m > 1) else 1)
        t_flop = flops / max(self.device["peak_flops"], 1.0)
        t_mem = (self.per_device_step_bytes(m, t)
                 / max(self.device["hbm_bytes_per_s"], 1.0))
        return max(t_flop, t_mem) * 1000.0

    @property
    def arithmetic_intensity(self):
        if not self.total_bytes:
            return 0.0
        return self.total_flops / float(self.total_bytes)

    @property
    def est_step_ms(self):
        """Roofline time lower bound for one step: whichever of the
        compute and memory ceilings binds."""
        t_flop = self.total_flops / max(self.device["peak_flops"], 1.0)
        t_mem = self.total_bytes / max(self.device["hbm_bytes_per_s"],
                                       1.0)
        return max(t_flop, t_mem) * 1000.0

    def mfu_cap(self):
        """The MFU ceiling this traffic level allows (ROOFLINE.md's
        intensity / machine-balance ratio), in [0, 1]."""
        balance = (self.device["peak_flops"]
                   / max(self.device["hbm_bytes_per_s"], 1.0))
        if not balance:
            return 0.0
        return min(1.0, self.arithmetic_intensity / balance)

    def op_cost(self, block_idx, op_index):
        """(est_flops, est_bytes) for one op, or None — the debugger's
        per-op column hook (fluid/debugger.py costs=)."""
        for row in self.ops:
            if row["block"] == block_idx and row["index"] == op_index:
                return row["est_flops"], row["est_bytes"]
        return None

    def to_dict(self):
        return {
            "what": self.what,
            "batch": self.batch,
            "precision": self.precision,
            "n_ops": self.n_ops,
            "param_bytes": int(self.param_bytes),
            "activation_peak_bytes": int(self.activation_peak_bytes),
            "kv_cache_bytes": int(self.kv_cache_bytes),
            "peak_bytes": int(self.peak_bytes),
            "peak_mb": round(self.peak_mb, 3),
            "mesh_size": int(self.mesh_size),
            "tp": bool(self.tp),
            "per_device_bytes": int(self.per_device_bytes()),
            "per_device_mb": round(self.per_device_mb, 3),
            "per_device_step_bytes": int(self.per_device_step_bytes()),
            "per_device_step_ms": round(self.per_device_step_ms(), 6),
            "actual_param_bytes": self.actual_param_bytes,
            "total_flops": int(self.total_flops),
            "total_bytes": int(self.total_bytes),
            "arithmetic_intensity": round(self.arithmetic_intensity, 3),
            "est_step_ms": round(self.est_step_ms, 6),
            "mfu_cap": round(self.mfu_cap(), 4),
            "device": dict(self.device),
            "peak_op": self.peak_op,
            "per_block": list(self.per_block),
            "top_contributors": list(self.top_contributors),
        }

    def render(self, top_n=5):
        """Human table for lint_program --report."""
        d = self.to_dict()
        lines = [
            "%s  (batch=%d, %s, %d ops, device %s)"
            % (self.what, self.batch, self.precision, self.n_ops,
               self.device["kind"] or "?"),
            "  params      %10.2f MiB%s"
            % (self.param_bytes / (1 << 20),
               "" if self.actual_param_bytes is None else
               "   (actual %.2f MiB, delta %+.1f%%)"
               % (self.actual_param_bytes / (1 << 20),
                  100.0 * (self.param_bytes - self.actual_param_bytes)
                  / max(self.actual_param_bytes, 1))),
            "  activations %10.2f MiB peak"
            % (self.activation_peak_bytes / (1 << 20)),
        ]
        if self.kv_cache_bytes:
            lines.append("  kv cache    %10.2f MiB"
                         % (self.kv_cache_bytes / (1 << 20)))
        lines += [
            "  peak HBM    %10.2f MiB" % self.peak_mb,
            "  cost        %.3f GFLOP, %.2f MiB moved, intensity "
            "%.1f FLOP/B" % (self.total_flops / 1e9,
                             self.total_bytes / (1 << 20),
                             self.arithmetic_intensity),
            "  roofline    >= %.3f ms/step, MFU cap %.1f%%"
            % (self.est_step_ms, 100.0 * self.mfu_cap()),
        ]
        if self.mesh_size > 1:
            lines.append(
                "  per member  %10.2f MiB resident, %.2f MiB moved"
                "/step, >= %.3f ms/step  (mesh=%d, %s)"
                % (self.per_device_mb,
                   self.per_device_step_bytes() / (1 << 20),
                   self.per_device_step_ms(), self.mesh_size,
                   "tensor-parallel" if self.tp else "gather"))
        if len(self.per_block) > 1:
            lines.append("  per block:")
            for row in self.per_block:
                lines.append(
                    "    block %-3d %5d ops  %10.3f GFLOP  %10.2f MiB"
                    % (row["block"], row["ops"],
                       row["est_flops"] / 1e9,
                       row["est_bytes"] / (1 << 20)))
        if self.top_contributors:
            lines.append("  top peak contributors:")
            for row in self.top_contributors[:top_n]:
                lines.append("    %-32s %10.2f MiB  [%s]"
                             % (row["var"], row["bytes"] / (1 << 20),
                                row["kind"]))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# liveness
# ---------------------------------------------------------------------------

def _subtree_var_bytes(block, batch, acc):
    """Sum of nbytes of every non-persistable var DECLARED in `block`'s
    subtree (loop-resident working set of a sub-block op), recording
    each into `acc` for the contributor table."""
    total = 0
    for name, v in block.vars.items():
        if v.persistable:
            continue
        nb = v.nbytes_hint(batch=batch)
        if nb:
            total += nb
            acc[name] = max(acc.get(name, 0), nb)
    for op in block.ops:
        sub = op.attrs.get("sub_block")
        if sub is not None:
            total += _subtree_var_bytes(sub, batch, acc)
    return total


@register_pass
class AnalyzeLivenessPass(AnalysisPass):
    """Computes ``intervals``: {var_name: (start, end, bytes, kind)}
    over the linearized global-block op order, plus ``resident``:
    {op_index: loop-resident sub-block bytes} and ``resident_vars``
    per-op contributor maps.  Results land in the pass attrs (read by
    analyze_program / the memory-plan pass); the diagnostics list stays
    empty — resource analysis reports numbers, not findings."""

    name = "analyze_liveness_pass"

    def analyze(self, program, diagnostics):
        batch = int(self.get("batch") or 1)
        feeds = frozenset(self.get("feeds") or ())
        fetches = frozenset(self.get("fetches") or ())
        blk = program.global_block()
        n = len(blk.ops)
        first_write, last_touch = {}, {}
        resident, resident_vars = {}, {}
        for i, op in enumerate(blk.ops):
            reads = [x for x in op.input_arg_names if x]
            writes = [x for x in op.output_arg_names if x]
            sub = op.attrs.get("sub_block")
            if sub is not None:
                reads.extend(x for x in self._external_reads(sub) if x)
                writes.extend(x for x in self._subtree_writes(sub) if x)
                acc = {}
                resident[i] = _subtree_var_bytes(sub, batch, acc)
                resident_vars[i] = acc
            for x in reads:
                last_touch[x] = i
            for x in writes:
                first_write.setdefault(x, i)
                last_touch[x] = i
        params, intervals = {}, {}
        for v in program.list_vars():
            if v.persistable:
                nb = v.nbytes_hint(batch=batch) or 0
                # shared global-block Parameters appear once per name
                params[v.name] = max(params.get(v.name, 0), nb)
        for name, v in blk.vars.items():
            if v.persistable or name not in last_touch:
                continue
            nb = v.nbytes_hint(batch=batch)
            if not nb:
                continue
            if v.is_data or name in feeds:
                start, kind = 0, "feed"
            else:
                start, kind = first_write.get(name, 0), "activation"
            end = last_touch[name]
            if name in fetches:
                end = max(end, n - 1 if n else 0)
            intervals[name] = (start, end, nb, kind)
        self.attrs["intervals"] = intervals
        self.attrs["param_bytes_by_var"] = params
        self.attrs["resident"] = resident
        self.attrs["resident_vars"] = resident_vars
        self.attrs["n_ops"] = n


# ---------------------------------------------------------------------------
# memory plan
# ---------------------------------------------------------------------------

@register_pass
class AnalyzeMemoryPlanPass(AnalysisPass):
    """Folds the liveness intervals into the per-op live-byte timeline:
    ``timeline`` [live activation+resident bytes per global op],
    ``param_bytes``, ``activation_peak_bytes``, ``peak_op`` and the
    ``top_contributors`` at the peak.  Expects the liveness pass attrs
    under ``liveness`` (analyze_program wires them through)."""

    name = "analyze_memory_plan_pass"

    def analyze(self, program, diagnostics):
        live = self.get("liveness") or {}
        intervals = live.get("intervals") or {}
        params = live.get("param_bytes_by_var") or {}
        resident = live.get("resident") or {}
        resident_vars = live.get("resident_vars") or {}
        n = live.get("n_ops") or 0
        # sweep-line: +bytes at start, -bytes after end
        delta = [0] * (n + 1)
        for (start, end, nb, _kind) in intervals.values():
            delta[start] += nb
            if end + 1 <= n:
                delta[end + 1] -= nb
        timeline, cur, peak, peak_op = [], 0, 0, None
        for i in range(n):
            cur += delta[i]
            total = cur + resident.get(i, 0)
            timeline.append(total)
            if total > peak:
                peak, peak_op = total, i
        top = []
        if peak_op is not None:
            for name, (start, end, nb, kind) in intervals.items():
                if start <= peak_op <= end:
                    top.append({"var": name, "bytes": nb, "kind": kind})
            for name, nb in (resident_vars.get(peak_op) or {}).items():
                top.append({"var": name, "bytes": nb, "kind": "loop"})
        for name, nb in params.items():
            top.append({"var": name, "bytes": nb, "kind": "param"})
        top.sort(key=lambda r: (-r["bytes"], r["var"]))
        self.attrs["param_bytes"] = sum(params.values())
        self.attrs["activation_peak_bytes"] = peak
        self.attrs["timeline"] = timeline
        self.attrs["peak_op"] = peak_op
        self.attrs["top_contributors"] = top


# ---------------------------------------------------------------------------
# per-op FLOP / byte cost model
# ---------------------------------------------------------------------------

def _numel(shape, batch):
    n = 1
    for d in shape or ():
        n *= int(batch) if (d is None or int(d) < 0) else int(d)
    return int(n)


def _shape_of(block, name, batch):
    v = block._find_var_recursive(name)
    if v is None or v.shape is None:
        return None
    return tuple(int(batch) if (d is None or int(d) < 0) else int(d)
                 for d in v.shape)


def _first_in(op, slot):
    names = op.inputs.get(slot) or []
    return names[0] if names else None


def _out_numel(op, block, batch):
    total = 0
    for names in op.outputs.values():
        for x in names:
            s = _shape_of(block, x, batch)
            if s is not None:
                total += _numel(s, batch)
    return total


def _flops_mul(op, block, batch):
    # X [.., K] x Y [K, N]: 2*M*K*N = 2 * out_elems * K
    y = _shape_of(block, _first_in(op, "Y"), batch)
    k = y[0] if y else 1
    return 2 * _out_numel(op, block, batch) * k


def _flops_matmul(op, block, batch):
    x = _shape_of(block, _first_in(op, "X"), batch)
    if not x or len(x) < 2:
        return _out_numel(op, block, batch)
    k = x[-2] if op.attrs.get("transpose_X") else x[-1]
    return 2 * _out_numel(op, block, batch) * k


def _flops_conv(op, block, batch):
    # Filter [O, I/g, kh, kw]: 2 * out_elems * (I/g * kh * kw) — exact
    # for grouped and depthwise convs alike
    f = _shape_of(block, _first_in(op, "Filter"), batch)
    if not f or len(f) < 4:
        return _out_numel(op, block, batch)
    return 2 * _out_numel(op, block, batch) * f[1] * f[2] * f[3]


def _flops_conv_transpose(op, block, batch):
    # Filter [I, O/g, kh, kw]: every input element scatters into
    # O/g * kh * kw outputs
    f = _shape_of(block, _first_in(op, "Filter"), batch)
    x = _shape_of(block, _first_in(op, "Input") or _first_in(op, "X"),
                  batch)
    if not f or len(f) < 4 or not x:
        return _out_numel(op, block, batch)
    return 2 * _numel(x, batch) * f[1] * f[2] * f[3]


def _flops_flash_attention(op, block, batch):
    q = _shape_of(block, _first_in(op, "Q"), batch)
    if not q or len(q) < 4:
        return _out_numel(op, block, batch)
    b, s, h, d = q[0], q[1], q[2], q[3]
    return 4 * b * h * s * s * d          # QK^T + PV, 2 FLOP/MAC each


def _flops_pool(op, block, batch):
    k = op.attrs.get("ksize") or op.attrs.get("pool_size") or (1,)
    if isinstance(k, (int, float)):
        k = (int(k),)
    win = 1
    for d in k:
        win *= int(d)
    return _out_numel(op, block, batch) * win


def _in_numel(op, block, batch):
    total = 0
    for names in op.inputs.values():
        for x in names:
            s = _shape_of(block, x, batch)
            if s is not None:
                total += _numel(s, batch)
    return total


# op type -> flops(op, block, batch).  The contraction class gets exact
# formulas; normalization/softmax get a small per-element constant; the
# default (absent here) is one FLOP per output element — elementwise /
# activation / copy ops are all bandwidth-bound anyway, so the BYTES
# side (below) is what prices them.
_FLOP_MODELS = {
    "mul": _flops_mul,
    "dequant_mul": _flops_mul,
    "matmul": _flops_matmul,
    "conv2d": _flops_conv,
    "depthwise_conv2d": _flops_conv,
    "conv3d": _flops_conv,
    "dequant_conv2d": _flops_conv,
    "conv2d_transpose": _flops_conv_transpose,
    "conv3d_transpose": _flops_conv_transpose,
    "flash_attention": _flops_flash_attention,
    "pool2d": _flops_pool,
    "softmax": lambda op, blk, b: 5 * _out_numel(op, blk, b),
    "log_softmax": lambda op, blk, b: 5 * _out_numel(op, blk, b),
    "sequence_softmax": lambda op, blk, b: 5 * _out_numel(op, blk, b),
    "softmax_with_cross_entropy":
        lambda op, blk, b: 6 * _in_numel(op, blk, b),
    "batch_norm": lambda op, blk, b: 8 * _out_numel(op, blk, b),
    "layer_norm": lambda op, blk, b: 8 * _out_numel(op, blk, b),
    "group_norm": lambda op, blk, b: 8 * _out_numel(op, blk, b),
    "reduce_sum": lambda op, blk, b: _in_numel(op, blk, b),
    "reduce_mean": lambda op, blk, b: _in_numel(op, blk, b),
    "mean": lambda op, blk, b: _in_numel(op, blk, b),
    "sum": lambda op, blk, b: _in_numel(op, blk, b),
    # gathers move bytes, they do not multiply
    "lookup_table": lambda op, blk, b: 0,
    "dequant_lookup_table": lambda op, blk, b: 0,
}


def _op_bytes(op, block, batch):
    """Estimated HBM traffic of one op: bytes of every distinct input
    var read + every output var written.  lookup_table-class gathers
    count the GATHERED rows, not the whole table (the table itself is
    priced once in param_bytes, and a step touches only ids x D of
    it)."""
    from ..fluid import core as fcore
    seen, total = set(), 0
    gather = op.type in ("lookup_table", "dequant_lookup_table")
    for slot, names in op.inputs.items():
        for x in names:
            if not x or x in seen:
                continue
            seen.add(x)
            v = block._find_var_recursive(x)
            if v is None or v.shape is None:
                continue
            if gather and slot == "W":
                ids = _shape_of(block, _first_in(op, "Ids"), batch)
                rows = _numel(ids, batch) if ids else 1
                width = _numel(v.shape[1:], batch)
                total += rows * width * fcore.dtype_size(v.dtype)
                continue
            total += v.nbytes_hint(batch=batch) or 0
    for names in op.outputs.values():
        for x in names:
            if not x or x in seen:
                continue
            seen.add(x)
            v = block._find_var_recursive(x)
            if v is not None:
                total += v.nbytes_hint(batch=batch) or 0
    return total


class _GradShim:
    """A ``<base>_grad`` op viewed through its forward op's slot
    layout: the generated grad ops carry the forward inputs under
    their original slot names plus ``Out:<slot>`` (forward outputs)
    and ``GRAD:<slot>`` companions (fluid/backward.py), so the base
    FLOP formula evaluates directly — the backward of a contraction
    costs ~2x the forward (dgrad + wgrad)."""

    __slots__ = ("type", "inputs", "outputs", "attrs")

    def __init__(self, op):
        self.type = op.type[:-len("_grad")]
        self.inputs = {k: v for k, v in op.inputs.items()
                       if not k.startswith(("Out:", "GRAD:"))}
        self.outputs = {k[len("Out:"):]: v
                        for k, v in op.inputs.items()
                        if k.startswith("Out:")}
        self.attrs = op.attrs


def _op_flops(op, block, batch):
    model = _FLOP_MODELS.get(op.type)
    if model is not None:
        return int(model(op, block, batch))
    if op.type.endswith("_grad"):
        base = _FLOP_MODELS.get(op.type[:-len("_grad")])
        if base is not None:
            return 2 * int(base(_GradShim(op), block, batch))
    return _out_numel(op, block, batch)


@register_pass
class AnalyzeCostPass(AnalysisPass):
    """Per-op FLOP/byte estimates over EVERY block (sub-block bodies
    count once — trip counts are not static knowledge), rolled up per
    block and in total.  Results in attrs: ``op_costs`` (list of row
    dicts), ``per_block``, ``total_flops``, ``total_bytes``."""

    name = "analyze_cost_pass"

    def analyze(self, program, diagnostics):
        batch = int(self.get("batch") or 1)
        rows, per_block = [], []
        total_flops = total_bytes = 0
        for block in program.blocks:
            b_flops = b_bytes = 0
            for idx, op in enumerate(block.ops):
                try:
                    flops = _op_flops(op, block, batch)
                except Exception:
                    flops = 0
                nbytes = _op_bytes(op, block, batch)
                rows.append({"block": block.idx, "index": idx,
                             "type": op.type, "est_flops": flops,
                             "est_bytes": nbytes})
                b_flops += flops
                b_bytes += nbytes
            per_block.append({"block": block.idx, "ops": len(block.ops),
                              "est_flops": b_flops,
                              "est_bytes": b_bytes})
            total_flops += b_flops
            total_bytes += b_bytes
        self.attrs["op_costs"] = rows
        self.attrs["per_block"] = per_block
        self.attrs["total_flops"] = total_flops
        self.attrs["total_bytes"] = total_bytes


RESOURCE_PASSES = (
    "analyze_liveness_pass",
    "analyze_memory_plan_pass",
    "analyze_cost_pass",
)


# ---------------------------------------------------------------------------
# surfaces
# ---------------------------------------------------------------------------

def analyze_program(program, feeds=None, fetches=None, batch=1,
                    device=None, what="program"):
    """Run the three resource passes; returns a :class:`ResourceReport`.

    `batch` substitutes every dynamic (-1) dim — pass the serving
    bucket / training batch for honest numbers (the default 1 gives
    the per-sample floor).  `device` (jax.Device or None) selects the
    roofline denominator."""
    from ..fluid.ir_passes import get_pass
    live = get_pass("analyze_liveness_pass", batch=batch,
                    feeds=tuple(feeds or ()),
                    fetches=tuple(fetches or ()))
    live.apply(program)
    mem = get_pass("analyze_memory_plan_pass", liveness=live.attrs)
    mem.apply(program)
    cost = get_pass("analyze_cost_pass", batch=batch)
    cost.apply(program)

    rep = ResourceReport(what=what, batch=batch)
    rep.device = device_peaks(device)
    rep.param_bytes = int(mem.attrs["param_bytes"])
    rep.activation_peak_bytes = int(mem.attrs["activation_peak_bytes"])
    rep.peak_op = mem.attrs["peak_op"]
    rep.top_contributors = mem.attrs["top_contributors"][:16]
    # live_bytes column: join the timeline onto the global-block rows
    timeline = mem.attrs["timeline"]
    rep.ops = cost.attrs["op_costs"]
    for row in rep.ops:
        if row["block"] == 0 and row["index"] < len(timeline):
            row["live_bytes"] = int(timeline[row["index"]])
    rep.per_block = cost.attrs["per_block"]
    rep.total_flops = int(cost.attrs["total_flops"])
    rep.total_bytes = int(cost.attrs["total_bytes"])
    rep.n_ops = sum(len(b.ops) for b in program.blocks)
    rep.precision = "int8" if any(
        op.type.startswith("dequant_")
        for op in program.global_block().ops) else "fp32"
    return rep


def _decode_report(path, meta, decode_slots, device, what,
                   kv_cache_dtype=None, fuse_steps=None):
    """Resource report for a decode artifact (no Program IR): weights
    from the state payload, the slot-table KV cache from the meta
    geometry — the bytes that bound decode slots (SERVING.md).

    ``fuse_steps`` prices the FUSED decode dispatch (SERVING.md "Fused
    multi-step decode"): one dispatch runs up to N steps on-device, so
    ``total_flops`` / ``total_bytes`` scale by N while the PEAK is
    unchanged — the while_loop carries the same one-token working set
    and the same slot table through every trip, so fusing never moves
    the admission gate, only the per-dispatch work it amortizes.

    The cache prices at its DTYPE's width (QUANTIZE.md "Quantized KV
    cache"): `kv_cache_dtype` (a load_model override) > the artifact's
    decode_meta pin > FLAGS.serving_kv_cache_dtype > fp32 — the same
    resolution the GenerativePredictor makes, so the admission fit
    check statically reads ~0.25x KV bytes for an int8-cache load
    (int8 slots + the per-(layer,head) fp32 scale table)."""
    from ..flags import FLAGS
    from ..inference.decode import normalize_kv_dtype
    n_slots = int(decode_slots or FLAGS.serving_decode_slots)
    L = int(meta["n_layers"])
    H = int(meta["n_heads"])
    D = int(meta["d_model"])
    S = int(meta["max_seq_len"])
    dh = D // H
    kv_dtype = normalize_kv_dtype(
        kv_cache_dtype if kv_cache_dtype is not None
        else (meta.get("kv_cache_dtype")
              or FLAGS.serving_kv_cache_dtype))
    rep = ResourceReport(what=what, batch=n_slots)
    rep.device = device_peaks(device)
    state_path = os.path.join(path, "decode_state.bin")
    try:
        from ..native import wire
        with open(state_path, "rb") as f:
            state = wire.decode(f.read())
        import numpy as np
        rep.param_bytes = sum(int(np.asarray(v).nbytes)
                              for v in state.values())
        rep.actual_param_bytes = rep.param_bytes
        n_params = sum(int(np.asarray(v).size) for v in state.values())
    except Exception:
        rep.param_bytes = os.path.getsize(state_path) \
            if os.path.exists(state_path) else 0
        rep.actual_param_bytes = rep.param_bytes
        n_params = rep.param_bytes // 4
    # K and V, [L, n_slots, S, H, Dh] each at the cache dtype's width
    # (4 B fp32, 1 B int8 + the fp32 scale table) — must match
    # GenerativePredictor.kv_cache_bytes exactly (pinned by
    # tests/test_resources.py)
    kv_elem = 1 if kv_dtype == "int8" else 4
    kv_scales = 2 * L * H * 4 if kv_dtype == "int8" else 0
    rep.kv_cache_bytes = (2 * L * n_slots * S * H * dh * kv_elem
                          + kv_scales)
    # decode-step working set: one token's activations per slot
    rep.activation_peak_bytes = n_slots * D * 4 * (L + 2)
    # one decode step: every weight multiplies once per slot, and the
    # whole KV cache streams through the attention gather; a fused
    # dispatch is N such steps back-to-back at the same peak
    fuse = max(int(fuse_steps or 1), 1)
    rep.total_flops = 2 * n_params * n_slots * fuse
    rep.total_bytes = (rep.param_bytes + rep.kv_cache_bytes) * fuse
    rep.n_ops = 0
    return rep


def _with_mesh(rep, mesh_size, tp=None):
    """Stamp a replica mesh size (and tensor-parallel compute mode) on
    a report (SERVING.md "Mesh replicas" / "Tensor-parallel compute")
    — makes ``per_device_bytes`` the 1/mesh sharded-at-rest estimate
    the per-member fit check admits on, and ``per_device_step_bytes``
    the per-member traffic the bandwidth roofline prices."""
    if mesh_size:
        rep.mesh_size = max(int(mesh_size), 1)
    if tp is not None:
        rep.tp = bool(tp)
    return rep


def analyze_artifact(path, batch=1, decode_slots=None, device=None,
                     kv_cache_dtype=None, fuse_steps=None,
                     mesh_size=None, tp=None):
    """Static resource report for a saved artifact dir — the admission
    gate's input, and lint_program --report's row source.

    save_inference_model dirs (fp32 or quantized) analyze their
    serialized Program and also total the on-disk payload bytes into
    ``actual_param_bytes``; decode artifacts (decode_meta.bin) come
    from their meta geometry + KV slot table priced at the cache dtype
    (`kv_cache_dtype` overrides the artifact's pin — the load_model
    knob, and ``fuse_steps`` prices the N-step fused dispatch at N·step
    FLOPs/bytes with the peak unchanged); save_aot dirs (aot_meta.bin)
    from their state payload + feed specs.  ``mesh_size`` stamps a
    mesh-replica shape on the report: total bytes are unchanged, but
    ``per_device_bytes`` (what `check_fit` prices per mesh member)
    reads params + KV at ~1/mesh_size.  ``tp`` marks tensor-parallel
    compute (FLAGS.mesh_tp): ``per_device_step_bytes`` /
    ``per_device_step_ms`` then divide the per-step traffic roofline
    by the mesh too."""
    from ..inference.decode import DECODE_META
    dm = os.path.join(path, DECODE_META)
    if os.path.exists(dm):
        from ..native import wire
        with open(dm, "rb") as f:
            meta = wire.decode(f.read())
        return _with_mesh(
            _decode_report(path, meta, decode_slots, device, path,
                           kv_cache_dtype=kv_cache_dtype,
                           fuse_steps=fuse_steps), mesh_size, tp=tp)
    am = os.path.join(path, "aot_meta.bin")
    if os.path.exists(am):
        from ..native import wire
        with open(am, "rb") as f:
            meta = wire.decode(f.read())
        rep = ResourceReport(what=path, batch=batch)
        rep.device = device_peaks(device)
        state_path = os.path.join(path, "aot_state.bin")
        if os.path.exists(state_path):
            rep.param_bytes = os.path.getsize(state_path)
            rep.actual_param_bytes = rep.param_bytes
        import numpy as np
        act = 0
        for name, spec in (meta.get("feed_specs") or {}).items():
            shape = [int(batch) if int(d) < 0 else int(d)
                     for d in spec["shape"]]
            act += int(np.prod(shape)) * np.dtype(spec["dtype"]).itemsize
        rep.activation_peak_bytes = act
        rep.total_bytes = rep.param_bytes + act
        rep.total_flops = (rep.param_bytes // 4) * 2 * int(batch)
        return _with_mesh(rep, mesh_size, tp=tp)
    model_file = os.path.join(path, "__model__")
    if not os.path.exists(model_file):
        raise FileNotFoundError(
            "%s: no __model__ / aot_meta.bin / decode_meta.bin — not a "
            "serving artifact directory" % path)
    from ..fluid.framework import Program
    with open(model_file) as f:
        meta = json.load(f)
    program = Program.parse_from_string(meta["program"])
    rep = analyze_program(program, feeds=meta["feed_names"],
                          fetches=meta["fetch_names"], batch=batch,
                          device=device, what=path)
    actual = 0
    gb = program.global_block()
    for name, v in gb.vars.items():
        if not v.persistable:
            continue
        fpath = os.path.join(path, name.replace("/", "__") + ".npy")
        if os.path.exists(fpath):
            # .npy header is ~128 bytes of metadata, not payload
            actual += max(os.path.getsize(fpath) - 128, 0)
    if actual:
        rep.actual_param_bytes = actual
    return _with_mesh(rep, mesh_size, tp=tp)


def check_fit(report, device=None, what=None, replicas=1,
              mesh_size=None):
    """Serving admission gate: raise :class:`ResourceFitError` when the
    report's per-replica peak exceeds the device budget
    (``device_memory_bytes``).  Returns (estimated, available) — with
    available None (no known budget) the check passes trivially.

    ``replicas`` multiplies the estimate for placements putting several
    replicas on ONE device (the [None] single-default-device spec).

    ``mesh_size`` > 1 (SERVING.md "Mesh replicas") prices the
    PER-MEMBER estimate — params + KV shard ~1/mesh at rest, the
    replicated-compute activation peak does not — against ONE member
    device's budget (`device` should be that member): how a model too
    big for any single chip admits on a mesh.  Default: the report's
    own stamped ``mesh_size``."""
    avail = device_memory_bytes(device)
    est = int(report.per_device_bytes(mesh_size)) \
        * max(int(replicas), 1)
    if avail is not None and est > avail:
        raise ResourceFitError(what or report.what, est, avail,
                               device=device)
    return est, avail
