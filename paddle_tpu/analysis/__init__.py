"""Static program analysis — verifier passes over the Program IR.

The ProgramDesc is a static graph the framework can inspect *before*
execution (the paper's premise, and the pre-execution graph analysis the
TensorFlow system paper exploits; the Julia-to-TPU paper treats whole-
program shape inference as a compilability precondition).  This package
turns that property into a checked contract: a suite of read-only
analysis passes riding the fluid/ir_passes.py Pass substrate that catch
graph bugs — uninitialized reads, shape/dtype conflicts, dead ops,
unreachable fetches, programs that will silently miss the AOT compile
cache — at build/load time instead of as runtime stack traces (or
silent staleness) N steps in.

Surfaces:
  verify_program(program, feeds=, fetches=)  -> [Diagnostic]
  check_program(...)        -> raises ProgramVerificationError on errors
  FLAGS.verify_program      -> opt-in pre-run check in Executor /
                               ParallelExecutor / Predictor (memoized per
                               program version — build/load cost, never
                               per-step)
  save_inference_model / load_inference_model verify unconditionally —
  the artifact boundary is where a broken graph becomes someone else's
  3am page (ANALYSIS.md documents the policy).

Resource analysis (the predictive side — ANALYSIS.md "Resource
analysis"):
  analyze_program(program, ...)   -> ResourceReport (liveness-based
                                     peak-HBM plan + FLOP/byte roofline)
  analyze_artifact(dir, ...)      -> same for saved artifact dirs
                                     (quantized/decode/aot aware)
  check_fit(report, device=)      -> serving admission gate; raises
                                     ResourceFitError naming the
                                     estimated vs available bytes

CLI twin: tools/lint_program.py (artifact dirs + the model zoo; --report
renders the resource tables); the runtime-side concurrency lint lives
in tools/lint_runtime.py.
"""

from .verifier import (
    ANALYSIS_PASSES,
    Diagnostic,
    ProgramVerificationError,
    check_program,
    check_serialized_cached,
    verify_program,
    verify_program_cached,
)
from .resources import (
    RESOURCE_PASSES,
    ResourceFitError,
    ResourceReport,
    analyze_artifact,
    analyze_program,
    check_fit,
    device_memory_bytes,
    device_peaks,
)

__all__ = [
    "ANALYSIS_PASSES",
    "Diagnostic",
    "ProgramVerificationError",
    "RESOURCE_PASSES",
    "ResourceFitError",
    "ResourceReport",
    "analyze_artifact",
    "analyze_program",
    "check_fit",
    "check_program",
    "check_serialized_cached",
    "device_memory_bytes",
    "device_peaks",
    "verify_program",
    "verify_program_cached",
]
