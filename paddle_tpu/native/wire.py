"""Typed wire codec — the no-pickle message format for every socket and
snapshot path (native/wire.cc; reference analogue grpc_serde.cc +
send_recv.proto.in VariableMessage).

`encode(obj)` / `decode(buf)` round-trip None/bool/int/float/str/bytes/
list/tuple/dict(str keys)/np.ndarray. Decoding validates every offset,
length, count, and depth in C++ before any Python object is built, so a
malformed or hostile frame raises `WireError` — it can never execute
code, which is the whole point of replacing pickle on sockets. A pure-
Python codec implements the identical format when the native library is
unavailable (same validation, slower).
"""

import ctypes
import struct

import numpy as np

from . import lib, _as_u8p

__all__ = ["encode", "decode", "WireError"]

_MAGIC = 0x31575450  # "PTW1"
_VERSION = 1
_MAX_DEPTH = 64
_MAX_NDIM = 8

_NONE, _BOOL, _INT, _FLOAT, _STR, _BYTES, _LIST, _TUPLE, _DICT, _TENSOR = \
    range(10)

# dtype codes: ONE table with tensor_serde (native/__init__) so the wire
# format and the save/load-op format can never diverge on codes 0-7;
# wire-only extensions start at 8
from . import _DTYPE_CODES as _BASE_DTYPE_CODES

_DTYPE_CODES = dict(_BASE_DTYPE_CODES)
_DTYPE_CODES.update({
    np.dtype(np.uint32): 9, np.dtype(np.uint64): 10,
    np.dtype(np.int16): 11, np.dtype(np.uint16): 12,
    np.dtype(np.complex64): 13, np.dtype(np.complex128): 14,
})
try:
    import ml_dtypes
    _DTYPE_CODES[np.dtype(ml_dtypes.bfloat16)] = 8
except ImportError:  # pragma: no cover
    pass
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


class WireError(ValueError):
    """Malformed frame (truncated, bad magic, bad tag, lying counts...)."""


_HAS_NATIVE = lib is not None and hasattr(lib, "wirb_new")

if _HAS_NATIVE and lib.wirb_new.restype is not ctypes.c_void_p:
    lib.wirb_new.restype = ctypes.c_void_p
    lib.wirb_none.argtypes = [ctypes.c_void_p]
    lib.wirb_bool.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.wirb_int.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.wirb_float.argtypes = [ctypes.c_void_p, ctypes.c_double]
    for _fn in (lib.wirb_str, lib.wirb_bytes, lib.wirb_key):
        _fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                        ctypes.c_uint32]
    for _fn in (lib.wirb_list, lib.wirb_tuple, lib.wirb_dict):
        _fn.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.wirb_tensor.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    lib.wirb_finish.restype = ctypes.c_long
    lib.wirb_finish.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    lib.wirb_abort.argtypes = [ctypes.c_void_p]
    lib.wire_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    lib.wirp_new.restype = ctypes.c_void_p
    lib.wirp_new.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_long]
    lib.wirp_tag.restype = ctypes.c_int
    lib.wirp_tag.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.wirp_int.restype = ctypes.c_int
    lib.wirp_int.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                             ctypes.POINTER(ctypes.c_int64)]
    lib.wirp_float.restype = ctypes.c_int
    lib.wirp_float.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.POINTER(ctypes.c_double)]
    lib.wirp_payload.restype = ctypes.c_int
    lib.wirp_payload.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.POINTER(ctypes.c_uint64)]
    lib.wirp_count.restype = ctypes.c_long
    lib.wirp_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.wirp_child.restype = ctypes.c_long
    lib.wirp_child.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_uint32]
    lib.wirp_key.restype = ctypes.c_int
    lib.wirp_key.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                             ctypes.c_uint32,
                             ctypes.POINTER(ctypes.c_uint64),
                             ctypes.POINTER(ctypes.c_uint32)]
    lib.wirp_tensor.restype = ctypes.c_int
    lib.wirp_tensor.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64)]
    lib.wirp_free.argtypes = [ctypes.c_void_p]


def _tensor_parts(obj):
    # ascontiguousarray promotes 0-d to 1-d; reshape restores the rank
    arr = np.ascontiguousarray(obj).reshape(np.shape(obj))
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise WireError("unsupported tensor dtype %s" % arr.dtype)
    if arr.ndim > _MAX_NDIM:
        # the parser (both C++ and python) caps rank at _MAX_NDIM —
        # refusing HERE keeps encode/decode a round trip instead of
        # writing frames our own decoder calls malformed
        raise WireError("tensor rank %d exceeds the wire format's max "
                        "of %d" % (arr.ndim, _MAX_NDIM))
    return arr, code


def _encode_native(obj):
    h = lib.wirb_new()
    try:
        _build_native(h, obj, 0)
    except Exception:
        lib.wirb_abort(h)
        raise
    out = ctypes.POINTER(ctypes.c_uint8)()
    n = lib.wirb_finish(h, ctypes.byref(out))
    if n < 0:
        raise MemoryError("wire encode failed")
    buf = ctypes.string_at(out, n)
    lib.wire_free(out)
    return buf


def _check_i64(v):
    if not (-(1 << 63) <= v < (1 << 63)):
        raise WireError("int %d outside the wire int64 range" % v)
    return v


def _build_native(h, obj, depth):
    if depth > _MAX_DEPTH:
        raise WireError("wire value nested too deep")
    if obj is None:
        lib.wirb_none(h)
    elif isinstance(obj, (bool, np.bool_)):
        lib.wirb_bool(h, int(obj))
    elif isinstance(obj, (int, np.integer)):
        lib.wirb_int(h, _check_i64(int(obj)))
    elif isinstance(obj, (float, np.floating)):
        lib.wirb_float(h, float(obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        lib.wirb_str(h, _as_u8p(raw), len(raw))
    elif isinstance(obj, (bytes, bytearray)):
        raw = bytes(obj)
        lib.wirb_bytes(h, _as_u8p(raw), len(raw))
    elif isinstance(obj, np.ndarray):
        arr, code = _tensor_parts(obj)
        dims = (ctypes.c_uint64 * max(arr.ndim, 1))(*arr.shape)
        raw = arr.tobytes()
        lib.wirb_tensor(h, code, dims, arr.ndim, _as_u8p(raw), len(raw))
    elif isinstance(obj, (list, tuple)):
        (lib.wirb_list if isinstance(obj, list) else lib.wirb_tuple)(
            h, len(obj))
        for item in obj:
            _build_native(h, item, depth + 1)
    elif isinstance(obj, dict):
        lib.wirb_dict(h, len(obj))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise WireError("dict keys must be str, got %r" % (k,))
            raw = k.encode("utf-8")
            lib.wirb_key(h, _as_u8p(raw), len(raw))
            _build_native(h, v, depth + 1)
    else:
        raise WireError("unsupported wire type %s" % type(obj).__name__)


def _decode_native(buf):
    buf = bytes(buf)
    h = lib.wirp_new(_as_u8p(buf), len(buf))
    if not h:
        raise WireError("malformed wire frame (%d bytes)" % len(buf))
    try:
        return _read_native(h, buf, 0)
    finally:
        lib.wirp_free(h)


def _read_native(h, buf, idx):
    tag = lib.wirp_tag(h, idx)
    if tag == _NONE:
        return None
    if tag in (_BOOL, _INT):
        v = ctypes.c_int64()
        if lib.wirp_int(h, idx, ctypes.byref(v)) != 0:
            raise WireError("bad scalar node")
        return bool(v.value) if tag == _BOOL else v.value
    if tag == _FLOAT:
        v = ctypes.c_double()
        if lib.wirp_float(h, idx, ctypes.byref(v)) != 0:
            raise WireError("bad float node")
        return v.value
    if tag in (_STR, _BYTES):
        off, ln = ctypes.c_uint64(), ctypes.c_uint64()
        if lib.wirp_payload(h, idx, ctypes.byref(off),
                            ctypes.byref(ln)) != 0:
            raise WireError("bad payload node")
        raw = buf[off.value:off.value + ln.value]
        if tag == _STR:
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("non-utf8 string payload")
        return raw
    if tag in (_LIST, _TUPLE, _DICT):
        n = lib.wirp_count(h, idx)
        if n < 0:
            raise WireError("bad container node")
        if tag == _DICT:
            out = {}
            for i in range(n):
                koff, klen = ctypes.c_uint64(), ctypes.c_uint32()
                if lib.wirp_key(h, idx, i, ctypes.byref(koff),
                                ctypes.byref(klen)) != 0:
                    raise WireError("bad dict key")
                try:
                    key = buf[koff.value:koff.value + klen.value] \
                        .decode("utf-8")
                except UnicodeDecodeError:
                    raise WireError("non-utf8 dict key")
                out[key] = _read_native(h, buf, lib.wirp_child(h, idx, i))
            return out
        items = [_read_native(h, buf, lib.wirp_child(h, idx, i))
                 for i in range(n)]
        return items if tag == _LIST else tuple(items)
    if tag == _TENSOR:
        dtype, ndim = ctypes.c_uint32(), ctypes.c_uint32()
        dims = (ctypes.c_uint64 * _MAX_NDIM)()
        off, nbytes = ctypes.c_uint64(), ctypes.c_uint64()
        if lib.wirp_tensor(h, idx, ctypes.byref(dtype), ctypes.byref(ndim),
                           dims, ctypes.byref(off),
                           ctypes.byref(nbytes)) != 0:
            raise WireError("bad tensor node")
        dt = _CODE_DTYPES.get(dtype.value)
        if dt is None:
            raise WireError("unknown tensor dtype code %d" % dtype.value)
        shape = tuple(dims[i] for i in range(ndim.value))
        count = 1
        for d in shape:
            count *= d
        if count * dt.itemsize != nbytes.value:
            raise WireError("tensor shape/bytes mismatch")
        return np.frombuffer(buf, dtype=dt, count=count,
                             offset=off.value).reshape(shape).copy()
    raise WireError("bad tag %d" % tag)


# ---------------------------------------------------------------------------
# Pure-Python codec (same format, used when the .so is unavailable)
# ---------------------------------------------------------------------------

def _encode_py(obj):
    parts = [struct.pack("<II", _MAGIC, _VERSION)]
    _build_py(parts, obj, 0)
    return b"".join(parts)


def _build_py(parts, obj, depth):
    if depth > _MAX_DEPTH:
        raise WireError("wire value nested too deep")
    if obj is None:
        parts.append(bytes([_NONE]))
    elif isinstance(obj, (bool, np.bool_)):
        parts.append(struct.pack("<BB", _BOOL, int(obj)))
    elif isinstance(obj, (int, np.integer)):
        parts.append(struct.pack("<Bq", _INT, _check_i64(int(obj))))
    elif isinstance(obj, (float, np.floating)):
        parts.append(struct.pack("<Bd", _FLOAT, float(obj)))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        parts.append(struct.pack("<BI", _STR, len(raw)))
        parts.append(raw)
    elif isinstance(obj, (bytes, bytearray)):
        parts.append(struct.pack("<BI", _BYTES, len(obj)))
        parts.append(bytes(obj))
    elif isinstance(obj, np.ndarray):
        arr, code = _tensor_parts(obj)
        raw = arr.tobytes()
        parts.append(struct.pack("<BII", _TENSOR, code, arr.ndim))
        parts.append(struct.pack("<%dQ" % arr.ndim, *arr.shape)
                     if arr.ndim else b"")
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    elif isinstance(obj, (list, tuple)):
        parts.append(struct.pack(
            "<BI", _LIST if isinstance(obj, list) else _TUPLE, len(obj)))
        for item in obj:
            _build_py(parts, item, depth + 1)
    elif isinstance(obj, dict):
        parts.append(struct.pack("<BI", _DICT, len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise WireError("dict keys must be str, got %r" % (k,))
            raw = k.encode("utf-8")
            parts.append(struct.pack("<I", len(raw)))
            parts.append(raw)
            _build_py(parts, v, depth + 1)
    else:
        raise WireError("unsupported wire type %s" % type(obj).__name__)


class _PyCursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf, pos):
        self.buf = buf
        self.pos = pos

    def take(self, n):
        if n < 0 or len(self.buf) - self.pos < n:
            raise WireError("truncated wire frame")
        raw = self.buf[self.pos:self.pos + n]
        self.pos += n
        return raw

    def unpack(self, fmt):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.take(size))


def _decode_py(buf):
    buf = bytes(buf)
    if len(buf) < 9:
        raise WireError("malformed wire frame (%d bytes)" % len(buf))
    magic, version = struct.unpack_from("<II", buf, 0)
    if magic != _MAGIC or version != _VERSION:
        raise WireError("bad wire magic/version")
    c = _PyCursor(buf, 8)
    obj = _read_py(c, 0)
    if c.pos != len(buf):
        raise WireError("trailing junk after wire frame")
    return obj


def _read_py(c, depth):
    if depth > _MAX_DEPTH:
        raise WireError("wire frame nested too deep")
    (tag,) = c.unpack("<B")
    if tag == _NONE:
        return None
    if tag == _BOOL:
        (v,) = c.unpack("<B")
        if v > 1:
            raise WireError("bad bool")
        return bool(v)
    if tag == _INT:
        return c.unpack("<q")[0]
    if tag == _FLOAT:
        return c.unpack("<d")[0]
    if tag in (_STR, _BYTES):
        (n,) = c.unpack("<I")
        raw = c.take(n)
        if tag == _STR:
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("non-utf8 string payload")
        return raw
    if tag in (_LIST, _TUPLE):
        (n,) = c.unpack("<I")
        items = [_read_py(c, depth + 1) for _ in range(n)]
        return items if tag == _LIST else tuple(items)
    if tag == _DICT:
        (n,) = c.unpack("<I")
        out = {}
        for _ in range(n):
            (klen,) = c.unpack("<I")
            try:
                key = c.take(klen).decode("utf-8")
            except UnicodeDecodeError:
                raise WireError("non-utf8 dict key")
            out[key] = _read_py(c, depth + 1)
        return out
    if tag == _TENSOR:
        code, ndim = c.unpack("<II")
        if ndim > _MAX_NDIM:
            raise WireError("tensor ndim too large")
        shape = c.unpack("<%dQ" % ndim) if ndim else ()
        (nbytes,) = c.unpack("<Q")
        dt = _CODE_DTYPES.get(code)
        if dt is None:
            raise WireError("unknown tensor dtype code %d" % code)
        count = 1
        for d in shape:
            count *= d
        if count * dt.itemsize != nbytes:
            raise WireError("tensor shape/bytes mismatch")
        raw = c.take(nbytes)
        return np.frombuffer(raw, dtype=dt, count=count).reshape(shape) \
            .copy()
    raise WireError("bad tag %d" % tag)


def encode(obj):
    """Serialize a wire-encodable value to a framed bytes object."""
    if _HAS_NATIVE:
        return _encode_native(obj)
    return _encode_py(obj)


def decode(buf):
    """Parse a frame; raises WireError on anything malformed."""
    if _HAS_NATIVE:
        return _decode_native(buf)
    return _decode_py(buf)
