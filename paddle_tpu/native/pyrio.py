"""Pure-Python recordio fallback (same file format as native/recordio.cc).
Used only when the C++ library cannot be built."""

import struct
import zlib

_MAGIC = b"PTRIO001"
_HDR = struct.Struct("<IIII")


def _crc(payload):
    return zlib.crc32(payload) & 0xFFFFFFFF


class PyWriter:
    def __init__(self, path, max_chunk_records=1000,
                 max_chunk_bytes=32 << 20):
        self._f = open(path, "wb")
        self._f.write(_MAGIC)
        self._pending = []
        self._pending_bytes = 0
        self._max_records = max_chunk_records
        self._max_bytes = max_chunk_bytes

    def write(self, record):
        self._pending.append(record)
        self._pending_bytes += len(record)
        if len(self._pending) >= self._max_records or \
                self._pending_bytes >= self._max_bytes:
            self._flush()

    def _flush(self):
        if not self._pending:
            return
        payload = b"".join(self._pending)
        self._f.write(_HDR.pack(len(self._pending), len(payload),
                                _crc(payload), 0))
        self._f.write(struct.pack("<%dI" % len(self._pending),
                                  *[len(r) for r in self._pending]))
        self._f.write(payload)
        self._pending = []
        self._pending_bytes = 0

    def close(self):
        self._flush()
        self._f.close()


class PyScanner:
    def __init__(self, path):
        self._f = open(path, "rb")
        if self._f.read(8) != _MAGIC:
            raise IOError("bad recordio magic in %s" % path)
        self._chunk = []
        self._idx = 0

    def next(self):
        if self._idx >= len(self._chunk):
            hdr = self._f.read(_HDR.size)
            if len(hdr) < _HDR.size:
                raise StopIteration
            n, payload_len, crc, _ = _HDR.unpack(hdr)
            lens = struct.unpack("<%dI" % n, self._f.read(4 * n))
            payload = self._f.read(payload_len)
            if _crc(payload) != crc:
                raise IOError("recordio crc mismatch")
            self._chunk = []
            off = 0
            for ln in lens:
                self._chunk.append(payload[off:off + ln])
                off += ln
            self._idx = 0
        rec = self._chunk[self._idx]
        self._idx += 1
        return rec

    def close(self):
        self._f.close()
