"""ctypes bindings for the C++ runtime pieces (native/*.cc).

The reference's native layer (recordio C++, LoDTensorBlockingQueue, tensor
serde in save_op.cc) maps here: we dlopen libpaddle_tpu_native.so (built
from native/ via make; pybind11 is not available in this image, so the ABI
is a plain C API). If the library is missing we build it on first import;
if no compiler is available, pure-Python fallbacks keep everything
functional (slower).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["lib", "available", "RecordIOWriter", "RecordIOScanner",
           "NativeBlockingQueue", "serialize_tensor", "deserialize_tensor"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libpaddle_tpu_native.so")

lib = None


def _try_build():
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load():
    global lib
    if not os.path.exists(_LIB_PATH):
        if not _try_build():
            return None
    try:
        l = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    # ---- signatures ----
    l.rio_writer_open.restype = ctypes.c_void_p
    l.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_long]
    l.rio_writer_write.restype = ctypes.c_int
    l.rio_writer_write.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.c_uint8),
                                   ctypes.c_long]
    l.rio_writer_close.restype = ctypes.c_int
    l.rio_writer_close.argtypes = [ctypes.c_void_p]
    l.rio_scanner_open.restype = ctypes.c_void_p
    l.rio_scanner_open.argtypes = [ctypes.c_char_p]
    l.rio_scanner_next.restype = ctypes.c_long
    l.rio_scanner_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    l.rio_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    l.rio_scanner_close.argtypes = [ctypes.c_void_p]

    l.bq_create.restype = ctypes.c_void_p
    l.bq_create.argtypes = [ctypes.c_long]
    l.bq_push.restype = ctypes.c_int
    l.bq_push.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                          ctypes.c_long, ctypes.c_long]
    l.bq_pop.restype = ctypes.c_long
    l.bq_pop.argtypes = [ctypes.c_void_p,
                         ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                         ctypes.c_long]
    l.bq_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    l.bq_size.restype = ctypes.c_long
    l.bq_size.argtypes = [ctypes.c_void_p]
    l.bq_close.argtypes = [ctypes.c_void_p]
    l.bq_destroy.argtypes = [ctypes.c_void_p]

    l.ts_serialize.restype = ctypes.c_long
    l.ts_serialize.argtypes = [
        ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
    l.ts_parse_header.restype = ctypes.c_int
    l.ts_parse_header.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_long,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64)]
    l.ts_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
    return l


lib = _load()


def available():
    return lib is not None


def _as_u8p(data):
    return ctypes.cast(ctypes.c_char_p(data),
                       ctypes.POINTER(ctypes.c_uint8))


# ---------------------------------------------------------------------------
# RecordIO
# ---------------------------------------------------------------------------

class RecordIOWriter:
    """reference recordio/writer.h; native-backed with Python fallback."""

    def __init__(self, path, max_chunk_records=1000,
                 max_chunk_bytes=32 << 20):
        self._path = path
        self._native = None
        self._py = None
        if lib is not None:
            self._native = lib.rio_writer_open(
                path.encode(), max_chunk_records, max_chunk_bytes)
        if not self._native:
            from . import pyrio
            self._py = pyrio.PyWriter(path, max_chunk_records,
                                      max_chunk_bytes)

    def write(self, record):
        record = bytes(record)
        if self._native:
            rc = lib.rio_writer_write(self._native, _as_u8p(record),
                                      len(record))
            if rc != 0:
                raise IOError("recordio write failed: %s" % self._path)
        else:
            self._py.write(record)

    def close(self):
        if self._native:
            rc = lib.rio_writer_close(self._native)
            self._native = None
            if rc != 0:
                raise IOError("recordio close failed: %s" % self._path)
        elif self._py:
            self._py.close()
            self._py = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordIOScanner:
    """reference recordio/scanner.h:26."""

    def __init__(self, path):
        self._path = path
        self._native = None
        self._py = None
        if lib is not None:
            self._native = lib.rio_scanner_open(path.encode())
        if not self._native:
            from . import pyrio
            self._py = pyrio.PyScanner(path)

    def __iter__(self):
        return self

    def __next__(self):
        if self._native:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = lib.rio_scanner_next(self._native, ctypes.byref(out))
            if n == -1:
                raise StopIteration
            if n == -2:
                raise IOError("recordio corruption in %s" % self._path)
            data = ctypes.string_at(out, n)
            lib.rio_free(out)
            return data
        return self._py.next()

    def close(self):
        if self._native:
            lib.rio_scanner_close(self._native)
            self._native = None
        elif self._py:
            self._py.close()
            self._py = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


# ---------------------------------------------------------------------------
# Blocking queue
# ---------------------------------------------------------------------------

class NativeBlockingQueue:
    """reference operators/reader/lod_tensor_blocking_queue.h:31 — bounded
    byte-buffer queue whose waits happen in C++ (GIL released during ctypes
    calls)."""

    def __init__(self, capacity):
        self._capacity = capacity
        self._native = lib.bq_create(capacity) if lib is not None else None
        if self._native is None:
            import queue
            self._py = queue.Queue(maxsize=capacity)
            self._closed = threading.Event()

    def push(self, data, timeout_ms=-1):
        data = bytes(data)
        if self._native:
            rc = lib.bq_push(self._native, _as_u8p(data), len(data),
                             timeout_ms)
            if rc == -1:
                raise EOFError("queue closed")
            if rc == -2:
                raise TimeoutError("queue push timeout")
            return
        if self._closed.is_set():
            raise EOFError("queue closed")
        self._py.put(data, timeout=None if timeout_ms < 0
                     else timeout_ms / 1000.0)

    def pop(self, timeout_ms=-1):
        if self._native:
            out = ctypes.POINTER(ctypes.c_uint8)()
            n = lib.bq_pop(self._native, ctypes.byref(out), timeout_ms)
            if n == -1:
                raise EOFError("queue closed")
            if n == -2:
                raise TimeoutError("queue pop timeout")
            data = ctypes.string_at(out, n)
            lib.bq_free(out)
            return data
        import queue as pyq
        while True:
            try:
                return self._py.get(timeout=0.1)
            except pyq.Empty:
                if self._closed.is_set():
                    raise EOFError("queue closed")
                if timeout_ms >= 0:
                    raise TimeoutError("queue pop timeout")

    def size(self):
        if self._native:
            return lib.bq_size(self._native)
        return self._py.qsize()

    def close(self):
        if self._native:
            lib.bq_close(self._native)
        else:
            self._closed.set()

    def __del__(self):
        if getattr(self, "_native", None):
            try:
                lib.bq_destroy(self._native)
            except Exception:
                pass
            self._native = None


# ---------------------------------------------------------------------------
# Tensor serde (save/load op format)
# ---------------------------------------------------------------------------

_DTYPE_CODES = {
    np.dtype(np.float32): 0, np.dtype(np.float64): 1,
    np.dtype(np.int32): 2, np.dtype(np.int64): 3,
    np.dtype(np.float16): 4, np.dtype(np.uint8): 5,
    np.dtype(np.int8): 6, np.dtype(np.bool_): 7,
}
_CODE_DTYPES = {v: k for k, v in _DTYPE_CODES.items()}


def serialize_tensor(arr, lod=None):
    """save_op.cc tensor serialization (+LoD levels)."""
    arr = np.ascontiguousarray(arr)
    code = _DTYPE_CODES[np.dtype(arr.dtype)]
    lod = lod or []
    if lib is not None:
        dims = (ctypes.c_uint64 * max(arr.ndim, 1))(*arr.shape)
        data = arr.tobytes()
        lod_lens = (ctypes.c_uint64 * max(len(lod), 1))(
            *[len(l) for l in lod])
        flat = [x for l in lod for x in l]
        lod_flat = (ctypes.c_uint64 * max(len(flat), 1))(*flat)
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = lib.ts_serialize(code, dims, arr.ndim, _as_u8p(data),
                             len(data), lod_lens, len(lod), lod_flat,
                             ctypes.byref(out))
        if n < 0:
            raise MemoryError("ts_serialize failed")
        buf = ctypes.string_at(out, n)
        lib.ts_free(out)
        return buf
    # python fallback
    import struct
    parts = [struct.pack("<III", 1, code, arr.ndim)]
    parts.append(struct.pack("<%dQ" % arr.ndim, *arr.shape))
    raw = arr.tobytes()
    parts.append(struct.pack("<Q", len(raw)))
    parts.append(raw)
    parts.append(struct.pack("<I", len(lod)))
    for l in lod:
        parts.append(struct.pack("<Q", len(l)))
        parts.append(struct.pack("<%dQ" % len(l), *l) if l else b"")
    return b"".join(parts)


def deserialize_tensor(buf):
    """Returns (ndarray, lod)."""
    import struct
    version, code, ndim = struct.unpack_from("<III", buf, 0)
    if version != 1:
        raise ValueError("bad tensor record version %d" % version)
    off = 12
    dims = struct.unpack_from("<%dQ" % ndim, buf, off)
    off += 8 * ndim
    (nbytes,) = struct.unpack_from("<Q", buf, off)
    off += 8
    dtype = _CODE_DTYPES[code]
    arr = np.frombuffer(buf, dtype=dtype, count=nbytes // dtype.itemsize,
                        offset=off).reshape(dims).copy()
    off += nbytes
    lod = []
    if off < len(buf):
        (levels,) = struct.unpack_from("<I", buf, off)
        off += 4
        for _ in range(levels):
            (n,) = struct.unpack_from("<Q", buf, off)
            off += 8
            lod.append(list(struct.unpack_from("<%dQ" % n, buf, off)))
            off += 8 * n
    return arr, lod
