"""v2 training events (reference python/paddle/v2/event.py — the trainer
fires these into the user's event_handler)."""

__all__ = ["EndIteration", "BeginIteration", "BeginPass", "EndPass",
           "TestResult", "EndForwardBackward"]


class WithMetric(object):
    """reference event.py:31 — exposes evaluator metric pairs."""

    def __init__(self, evaluator=None):
        self.__evaluator__ = evaluator or {}

    @property
    def metrics(self):
        return dict(self.__evaluator__)


class TestResult(WithMetric):
    """reference event.py:48"""

    def __init__(self, evaluator=None, cost=None):
        super(TestResult, self).__init__(evaluator)
        self.cost = cost


class BeginPass(object):
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator=None, gm=None):
        self.pass_id = pass_id
        super(EndPass, self).__init__(evaluator)


class BeginIteration(object):
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndForwardBackward(object):
    def __init__(self, pass_id, batch_id, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator=None, gm=None):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        super(EndIteration, self).__init__(evaluator)
