"""paddle.v2-compatible API (reference python/paddle/v2/__init__.py).

The legacy v2 generation (SURVEY §2.8): a declarative layer DSL +
Parameters + SGD trainer + inference, originally interpreted by the C++
gserver GradientMachine stack. Here the whole surface is a thin veneer
over the fluid/XLA substrate — one execution engine serves both API
generations, which is the TPU-native answer to the reference's 139k-LoC
second engine: topologies lower to fluid Programs, training steps jit to
single XLA computations, and Parameters are numpy pools synced with
executor scopes.

Usage mirrors the reference:

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False, trainer_count=1)
    images = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    label = paddle.layer.data("label", paddle.data_type.integer_value(10))
    out = paddle.layer.fc(images, size=10,
                          act=paddle.activation.Softmax())
    cost = paddle.layer.classification_cost(input=out, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(cost, params,
                                 paddle.optimizer.Momentum(momentum=0.9))
    trainer.train(paddle.batch(reader, 64), num_passes=2)
"""

from . import activation
from . import attr
from . import config_base
from . import data_feeder
from . import data_type
from . import evaluator
from . import event
from . import image
from . import inference
from . import layer
from . import minibatch
from . import networks
from . import op
from . import optimizer
from . import parameters
from . import plot
from . import pooling
from . import topology
from . import trainer

from .inference import infer
from .minibatch import batch
from ..dataset import *  # noqa: F401,F403 — paddle.v2.dataset surface
from .. import dataset
from .. import reader
from ..fluid.framework import (default_main_program,
                               default_startup_program)

__all__ = [
    "init", "optimizer", "layer", "activation", "parameters", "trainer",
    "event", "data_type", "attr", "pooling", "dataset", "reader",
    "topology", "networks", "infer", "batch", "inference", "image",
    "master", "default_main_program", "default_startup_program",
]

_init_kwargs = {}


def init(**kwargs):
    """reference v2/__init__.py init() — swallow the v1 runtime knobs
    (use_gpu, trainer_count, log levels); device selection is jax-native
    here. Distributed knobs map onto the collective bootstrap."""
    _init_kwargs.update(kwargs)
    if kwargs.get("trainer_count", 1) > 1:
        # multi-device: the fluid ParallelExecutor path serves this; the
        # v2 trainer itself stays single-stream like the reference's
        # local updater
        pass
    return None


class _MasterModule(object):
    """paddle.v2.master client surface — backed by the TPU build's elastic
    layer (paddle_tpu.distributed.elastic), reference go/master."""

    @property
    def client(self):
        from ..distributed.elastic import MasterClient
        return MasterClient


master = _MasterModule()
