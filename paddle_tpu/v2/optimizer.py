"""v2 optimizers (reference python/paddle/v2/optimizer.py).

There, each v2 optimizer routes kwargs through trainer_config_helpers
``settings()`` into a C++ ParameterUpdater. Here each one lowers to the
matching fluid optimizer (whose update rules are jitted XLA ops), keeping
the v2 surface: learning_rate, regularization=L2Regularization(rate),
learning_rate_schedule ('constant' | 'poly' | 'exp' | 'discexp'), and
model_average=ModelAverage(...).
"""

from ..fluid import optimizer as F_opt
from ..fluid import regularizer as F_reg
from ..fluid import layers as F

__all__ = [
    "Momentum", "Adam", "Adamax", "AdaGrad", "DecayedAdaGrad", "AdaDelta",
    "RMSProp", "ModelAverage", "L2Regularization", "Optimizer",
]


class L2Regularization(object):
    """settings(regularization=...) analogue."""

    def __init__(self, rate=0.0):
        self.rate = rate


class ModelAverage(object):
    """settings(model_average=...) analogue — carried through to the fluid
    ModelAverage wrapper when used via trainer."""

    def __init__(self, average_window=0.15, max_average_window=None,
                 min_average_window=None, do_average_in_cpu=False):
        self.average_window = average_window
        self.max_average_window = max_average_window
        self.min_average_window = min_average_window


class Optimizer(object):
    def __init__(self, learning_rate=1e-3, learning_rate_decay_a=0.0,
                 learning_rate_decay_b=0.0,
                 learning_rate_schedule="constant", regularization=None,
                 model_average=None, gradient_clipping_threshold=None,
                 batch_size=None, learning_rate_args=None, **kwargs):
        self.learning_rate = learning_rate
        self.decay_a = learning_rate_decay_a
        self.decay_b = learning_rate_decay_b
        self.schedule = learning_rate_schedule
        self.regularization = regularization
        self.model_average = model_average
        self.gradient_clipping_threshold = gradient_clipping_threshold

    def _lr(self):
        """Lower the v1 learning_rate_schedule to in-graph decay ops
        (trainer_config_helpers optimizers.py schedule semantics:
        poly: lr*(1+a*t)^-b, exp/discexp: lr*a^(t/b))."""
        lr = self.learning_rate
        if self.schedule in (None, "constant"):
            return lr
        from ..fluid.layers import learning_rate_scheduler as sched
        if self.schedule == "poly":
            counter = sched._decay_step_counter()
            return F.scale(
                F.pow(F.scale(counter, scale=self.decay_a, bias=1.0),
                      factor=-self.decay_b), scale=lr)
        if self.schedule in ("exp", "discexp"):
            return sched.exponential_decay(
                lr, decay_steps=max(int(self.decay_b), 1),
                decay_rate=self.decay_a,
                staircase=(self.schedule == "discexp"))
        raise ValueError("unknown learning_rate_schedule %r" % self.schedule)

    def _reg(self):
        if isinstance(self.regularization, L2Regularization) \
                and self.regularization.rate:
            return F_reg.L2Decay(self.regularization.rate)
        return None

    def to_fluid(self):
        raise NotImplementedError

    def _wrap(self, opt):
        if self.gradient_clipping_threshold:
            from ..fluid import clip as F_clip
            opt._v2_grad_clip = F_clip.GradientClipByGlobalNorm(
                self.gradient_clipping_threshold)
        return opt


class Momentum(Optimizer):
    def __init__(self, momentum=None, sparse=False, **kwargs):
        super(Momentum, self).__init__(**kwargs)
        self.momentum = momentum or 0.0

    def to_fluid(self):
        return self._wrap(F_opt.MomentumOptimizer(
            learning_rate=self._lr(), momentum=self.momentum,
            regularization=self._reg()))


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super(Adam, self).__init__(**kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_fluid(self):
        return self._wrap(F_opt.AdamOptimizer(
            learning_rate=self._lr(), beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, regularization=self._reg()))


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kwargs):
        super(Adamax, self).__init__(**kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def to_fluid(self):
        return self._wrap(F_opt.AdamaxOptimizer(
            learning_rate=self._lr(), beta1=self.beta1, beta2=self.beta2,
            regularization=self._reg()))


class AdaGrad(Optimizer):
    def to_fluid(self):
        return self._wrap(F_opt.AdagradOptimizer(
            learning_rate=self._lr(), regularization=self._reg()))


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-06, **kwargs):
        super(DecayedAdaGrad, self).__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return self._wrap(F_opt.DecayedAdagradOptimizer(
            learning_rate=self._lr(), decay=self.rho, epsilon=self.epsilon,
            regularization=self._reg()))


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-06, **kwargs):
        super(AdaDelta, self).__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return self._wrap(F_opt.AdadeltaOptimizer(
            learning_rate=self._lr(), rho=self.rho, epsilon=self.epsilon,
            regularization=self._reg()))


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super(RMSProp, self).__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self):
        return self._wrap(F_opt.RMSPropOptimizer(
            learning_rate=self._lr(), rho=self.rho, epsilon=self.epsilon,
            regularization=self._reg()))
