"""v2 input data types.

Reference: python/paddle/v2/data_type.py re-exports py_paddle.dataprovider
converter types (dense_vector, integer_value, sparse vectors, each with
_sequence/_sub_sequence variants). The TPU build keeps the same constructor
names; the returned ``InputType`` records dim/seq/kind and drives the v2
DataFeeder's dense encoding (ragged sequences ride the fluid LoD system's
padded-dense form, SURVEY §5 long-context note).
"""

__all__ = [
    "InputType", "DataType", "SequenceType",
    "dense_vector_sub_sequence", "integer_value_sub_sequence",
    "dense_vector", "dense_vector_sequence", "dense_array",
    "integer_value", "integer_value_sequence",
    "sparse_binary_vector", "sparse_binary_vector_sequence",
    "sparse_float_vector", "sparse_float_vector_sequence",
]


class DataType(object):
    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class SequenceType(object):
    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


class InputType(object):
    def __init__(self, dim, seq_type, tp):
        self.dim = dim
        self.seq_type = seq_type
        self.type = tp

    def __repr__(self):
        return "InputType(dim=%d, seq=%d, type=%d)" % (
            self.dim, self.seq_type, self.type)


def dense_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def dense_vector_sequence(dim):
    return dense_vector(dim, SequenceType.SEQUENCE)


def dense_vector_sub_sequence(dim):
    return dense_vector(dim, SequenceType.SUB_SEQUENCE)


def dense_array(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.Dense)


def integer_value(value_range, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(value_range, seq_type, DataType.Index)


def integer_value_sequence(value_range):
    return integer_value(value_range, SequenceType.SEQUENCE)


def integer_value_sub_sequence(value_range):
    return integer_value(value_range, SequenceType.SUB_SEQUENCE)


def sparse_binary_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseNonValue)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, SequenceType.SEQUENCE)


def sparse_float_vector(dim, seq_type=SequenceType.NO_SEQUENCE):
    return InputType(dim, seq_type, DataType.SparseValue)


def sparse_float_vector_sequence(dim):
    return sparse_float_vector(dim, SequenceType.SEQUENCE)
