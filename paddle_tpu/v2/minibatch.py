"""paddle.v2.minibatch (reference python/paddle/v2/minibatch.py) —
shared with the top-level batch module."""

from ..batch import batch   # noqa: F401

__all__ = ["batch"]
