"""v2 inference (reference python/paddle/v2/inference.py:24 Inference /
infer). The reference forwards batches through a GradientMachine in test
mode; here the output layer's topology is cloned for_test and run through
the fluid Executor's jit cache."""

import numpy as np

from .topology import Topology
from .parameters import Parameters
from ..fluid import executor as _executor
from ..fluid.data_feeder import DataFeeder

__all__ = ["infer", "Inference"]


class Inference(object):
    def __init__(self, parameters, output_layer=None, fileobj=None):
        if output_layer is None:
            raise ValueError("output_layer is required")
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters must be paddle.v2 Parameters")
        self.__topology__ = Topology(output_layer)
        self.__data_types__ = self.__topology__.data_type()
        self.__parameters__ = parameters
        self.__scope__ = _executor.Scope()
        self.__exe__ = _executor.Executor()
        with _executor.scope_guard(self.__scope__):
            self.__exe__.run(self.__topology__.startup_program)
        parameters.push_to_scope(self.__scope__)
        self.__program__ = self.__topology__.main_program.clone(
            for_test=True)

    def iter_infer(self, input, feeding=None):
        from .data_feeder import resolve_feed_order
        names = resolve_feed_order(
            [n for n, _ in self.__data_types__], feeding)
        feed_vars = [self.__program__.global_block().var(n) for n in names]
        feeder = DataFeeder(feed_list=feed_vars, program=self.__program__)
        fetch = list(self.__topology__.output_vars)
        with _executor.scope_guard(self.__scope__):
            yield self.__exe__.run(self.__program__,
                                   feed=feeder.feed(input),
                                   fetch_list=fetch)

    def iter_infer_field(self, field, **kwargs):
        if field != "value":
            raise ValueError("TPU inference exposes field='value' only")
        for result in self.iter_infer(**kwargs):
            yield [np.asarray(r) for r in result]

    def infer(self, input, field="value", flatten_result=True, **kwargs):
        """reference inference.py:76 — returns a single ndarray when the
        topology has one output, else a list."""
        results = []
        for res in self.iter_infer_field(field=field, input=input, **kwargs):
            results.append(res)
        outs = [np.concatenate([np.atleast_1d(r[i]) for r in results],
                               axis=0)
                for i in range(len(results[0]))]
        if flatten_result and len(outs) == 1:
            return outs[0]
        return outs


def infer(output_layer, parameters, input, feeding=None, field="value"):
    """reference inference.py module-level infer()"""
    inferer = Inference(output_layer=output_layer, parameters=parameters)
    return inferer.infer(field=field, input=input, feeding=feeding)
