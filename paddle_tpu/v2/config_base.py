"""v2 layer graph node base.

Reference: python/paddle/v2/config_base.py — there, v2 ``Layer`` objects
wrap trainer_config_helpers outputs and are stitched into a ModelConfig
protobuf that a C++ GradientMachine interprets. Here the declarative DSL is
kept, but realization is TPU-native: each node knows how to emit ops into a
fluid ``Program`` (which then lowers to one jitted XLA computation), so the
v2 API and the fluid API share a single execution engine.
"""

from ..fluid import unique_name

__all__ = ["Layer"]


def _apply_extra_attr(var, layer_attr):
    """Honor ExtraLayerAttribute on a built layer output (reference
    trainer_config_helpers/attrs.py:233): drop_rate wraps the output in
    dropout, error_clipping_threshold clips the BACKPROPAGATED error
    (reference ExtraLayerAttribute semantics -> fluid ErrorClipByValue,
    applied to this var's gradient by append_backward). `device` is
    accepted and ignored — placement belongs to the mesh."""
    from .attr import ExtraLayerAttribute
    if not isinstance(layer_attr, ExtraLayerAttribute) or var is None \
            or not hasattr(var, "dtype"):
        return var
    if layer_attr.error_clipping_threshold:
        from ..fluid.clip import ErrorClipByValue
        var.error_clip = ErrorClipByValue(
            max=float(layer_attr.error_clipping_threshold))
    if layer_attr.drop_rate:
        from ..fluid import layers as F
        var = F.dropout(var, dropout_prob=float(layer_attr.drop_rate))
    return var


class Layer(object):
    """A declarative node in a v2 topology DAG.

    ``parents`` are other Layers this node consumes. ``build_fn`` receives
    the already-built parent fluid Variables and must append ops to the
    current default program, returning the output Variable.
    """

    def __init__(self, name=None, parents=None, build_fn=None,
                 layer_type="layer", extra_parents=None,
                 build_with_ctx=False, layer_attr=None):
        self.name = name if name else unique_name.generate(layer_type)
        self.layer_type = layer_type
        self.__parents__ = list(parents or [])
        self.__extra_parents__ = list(extra_parents or [])
        self.__build_fn__ = build_fn
        self.__build_with_ctx__ = build_with_ctx
        self.__layer_attr__ = layer_attr

    def parents(self):
        return self.__parents__ + self.__extra_parents__

    def build(self, context):
        """Realize this node (and its ancestors) as fluid Variables.

        ``context`` maps id(Layer) -> fluid Variable and must be used under
        a ``fluid.program_guard``; memoization makes diamond-shaped DAGs
        emit each layer exactly once, mirroring the reference's
        __get_used_layers__ dedup (v2/layer.py:110).
        """
        key = id(self)
        if key in context:
            return context[key]
        parent_vars = [p.build(context) for p in self.__parents__]
        for extra in self.__extra_parents__:
            extra.build(context)
        if self.__build_with_ctx__:
            out = self.__build_fn__(context, *parent_vars)
        else:
            out = self.__build_fn__(*parent_vars)
        out = _apply_extra_attr(out, self.__layer_attr__)
        context[key] = out
        return out

    def __repr__(self):
        return "Layer(%s, type=%s)" % (self.name, self.layer_type)
