"""v2 Topology: realize a layer DAG as a fluid Program pair.

Reference: python/paddle/v2/topology.py — there Topology(output_layers)
trims and serializes a ModelConfig protobuf (v2/layer.py:263 parse_network)
for the C++ GradientMachine. Here the "model config" IS a fluid Program:
one build pass emits the ops, and proto() hands back the serialized Program
(the TPU stack's IR), so everything downstream (trainer, inference,
save/load) reuses the fluid machinery.
"""

import contextlib

from ..fluid import framework
from ..fluid import unique_name
from .config_base import Layer
from .data_type import InputType

__all__ = ["Topology"]


class Topology(object):
    def __init__(self, layers, extra_layers=None):
        if isinstance(layers, Layer):
            layers = [layers]
        if extra_layers is not None and not isinstance(extra_layers, list):
            extra_layers = [extra_layers]
        self.layers = list(layers)
        self.extra_layers = list(extra_layers or [])
        self.main_program = framework.Program()
        self.startup_program = framework.Program()
        self._var_of = {}
        # Build under a topology-private name generator: rebuilding the
        # same layer DAG (trainer / test / inference) must produce
        # IDENTICAL parameter names so one Parameters pool serves them all
        # (the reference gets this for free from explicit layer-name-based
        # protobuf naming, trainer_config_helpers wrap_name_default).
        self._name_gen = unique_name.UniqueNameGenerator()
        with self.name_guard():
            with framework.program_guard(self.main_program,
                                         self.startup_program):
                ctx = self._var_of
                self.output_vars = [l.build(ctx) for l in
                                    self.layers + self.extra_layers]
        self._data_layers = self._collect_data_layers()

    @contextlib.contextmanager
    def name_guard(self):
        """Continue this topology's private unique-name stream (used by the
        trainer when appending optimizer/metric ops to the built program)."""
        old = unique_name.switch(self._name_gen)
        try:
            yield
        finally:
            unique_name.switch(old)

    def _collect_data_layers(self):
        seen, order = set(), []

        def visit(layer):
            if id(layer) in seen:
                return
            seen.add(id(layer))
            for p in layer.parents():
                visit(p)
            if layer.layer_type == "data":
                order.append(layer)

        for l in self.layers + self.extra_layers:
            visit(l)
        return order

    def data_layers(self):
        """name -> data Layer, in dependency-discovery order (reference
        topology.py data_layers)."""
        return dict((l.name, l) for l in self._data_layers)

    def data_type(self):
        """[(name, InputType)] in feed order (reference topology.py:data_type
        — drives DataFeeder construction)."""
        return [(l.name, l.data_type) for l in self._data_layers]

    def var_for(self, layer):
        """fluid Variable realizing `layer` in this topology's program."""
        if id(layer) not in self._var_of:
            raise ValueError("layer %s is not part of this topology"
                             % layer.name)
        return self._var_of[id(layer)]

    def proto(self):
        """Serialized model config == serialized fluid main Program."""
        return self.main_program.serialize_to_string()
