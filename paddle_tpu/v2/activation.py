"""v2 activation objects (reference python/paddle/v2/activation.py, which
re-exports trainer_config_helpers.activations). Each carries the fluid
activation name applied by layer builders."""

__all__ = [
    "Base", "Tanh", "Sigmoid", "Softmax", "Identity", "Linear",
    "SequenceSoftmax", "Exp", "Relu", "BRelu", "SoftRelu", "STanh",
    "Abs", "Square", "Log", "SquareRootN",
]


class Base(object):
    fluid_act = None  # None = identity

    def __repr__(self):
        return self.__class__.__name__ + "()"


class Tanh(Base):
    fluid_act = "tanh"


class Sigmoid(Base):
    fluid_act = "sigmoid"


class Softmax(Base):
    fluid_act = "softmax"


class SequenceSoftmax(Base):
    fluid_act = "sequence_softmax"


class Identity(Base):
    fluid_act = None


Linear = Identity


class Exp(Base):
    fluid_act = "exp"


class Relu(Base):
    fluid_act = "relu"


class BRelu(Base):
    fluid_act = "brelu"


class SoftRelu(Base):
    fluid_act = "soft_relu"


class STanh(Base):
    fluid_act = "stanh"


class Abs(Base):
    fluid_act = "abs"


class Square(Base):
    fluid_act = "square"


class Log(Base):
    fluid_act = "log"


class SquareRootN(Base):
    fluid_act = "sqrt"
