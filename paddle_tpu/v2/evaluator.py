"""v2 evaluators (reference python/paddle/v2/evaluator.py, deriving from
trainer_config_helpers/evaluators.py). An evaluator attaches a metric
computation to the topology as an extra layer; pass it via
``SGD(extra_layers=...)`` or use the trainer's built-in classification
error tracking."""

from .config_base import Layer
from ..fluid import layers as F

__all__ = ["classification_error", "auc"]


def classification_error(input, label, name=None, top_k=1):
    """classification error rate metric node (v1
    classification_error_evaluator)."""

    def build(pv, lv):
        acc = F.accuracy(input=pv, label=lv, k=top_k)
        return F.scale(acc, scale=-1.0, bias=1.0)

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def auc(input, label, name=None):
    """streaming AUC metric node (v1 auc_evaluator)."""

    def build(pv, lv):
        out, _ = F.auc(input=pv, label=lv)
        return out

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")
