"""v2 evaluators (reference python/paddle/v2/evaluator.py, which strips
the ``_evaluator`` suffix off every trainer_config_helpers evaluator:
evaluators.py:18-35). An evaluator attaches a metric (or printer) node to
the topology; pass it via ``SGD(extra_layers=...)`` or fetch it like any
layer with ``paddle.infer``/event callbacks."""

from .config_base import Layer
from ..fluid import layers as F

__all__ = [
    "classification_error", "auc", "pnpair", "precision_recall",
    "ctc_error", "chunk", "sum", "column_sum", "value_printer",
    "gradient_printer", "maxid_printer", "maxframe_printer",
    "seqtext_printer", "classification_error_printer", "detection_map",
]


def classification_error(input, label, name=None, top_k=1):
    """classification error rate metric node (v1
    classification_error_evaluator)."""

    def build(pv, lv):
        acc = F.accuracy(input=pv, label=lv, k=top_k)
        return F.scale(acc, scale=-1.0, bias=1.0)

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def auc(input, label, name=None):
    """streaming AUC metric node (v1 auc_evaluator)."""

    def build(pv, lv):
        out, _, _ = F.auc(input=pv, label=lv)
        return out

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def pnpair(input, label, query_id, weight=None, name=None):
    """Positive/negative ranking-pair rate for learning-to-rank (v1
    pnpair_evaluator, reference metrics/positive_negative_pair_op.h):
    streaming [pos, neg, neu] pair counts over same-query items."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.initializer import Constant
    from ..fluid import unique_name as _un

    parents = [input, label, query_id] + ([weight] if weight else [])

    def build(pv, lv, qv, *rest):
        helper = LayerHelper("positive_negative_pair")
        gb = helper.main_program.global_block()
        accs = []
        for tag in ("pos", "neg", "neu"):
            v = gb.create_var(name=_un.generate("pnpair_" + tag),
                              shape=[1], dtype="float32",
                              persistable=True, stop_gradient=True)
            helper.set_variable_initializer(v, Constant(0.0))
            accs.append(v)
        inputs = {"Score": pv, "Label": lv, "QueryID": qv,
                  "AccumulatePositivePair": accs[0],
                  "AccumulateNegativePair": accs[1],
                  "AccumulateNeutralPair": accs[2]}
        if rest:
            inputs["Weight"] = rest[0]
        helper.append_op(
            type="positive_negative_pair", inputs=inputs,
            outputs={"PositivePair": accs[0], "NegativePair": accs[1],
                     "NeutralPair": accs[2]},
            attrs={"column": 0})
        # expose the running triple as one [3] node
        return F.concat([accs[0], accs[1], accs[2]], axis=0)

    return Layer(name=name, parents=parents, build_fn=build,
                 layer_type="evaluator")


def precision_recall(input, label, positive_label=None, weight=None,
                     name=None):
    """Streaming multi-class precision/recall/F1 (v1
    precision_recall_evaluator). Returns the [6] accumulated metric
    vector (macro P/R/F1 then micro P/R/F1); ``positive_label`` narrows
    macro averaging to one class in the reference — here the full macro
    vector is reported and the arg is accepted for config parity."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.initializer import Constant
    from ..fluid import unique_name as _un

    parents = [input, label] + ([weight] if weight else [])

    def build(pv, lv, *rest):
        helper = LayerHelper("precision_recall")
        gb = helper.main_program.global_block()
        class_num = int(pv.shape[-1])
        states = gb.create_var(name=_un.generate("precrec_states"),
                               shape=[class_num, 4], dtype="float32",
                               persistable=True, stop_gradient=True)
        helper.set_variable_initializer(states, Constant(0.0))
        idx = F.argmax(pv, axis=-1)
        batch_m = helper.create_variable_for_type_inference(
            "float32", stop_gradient=True)
        accum_m = helper.create_variable_for_type_inference(
            "float32", stop_gradient=True)
        inputs = {"Indices": idx, "Labels": lv, "StatesInfo": states}
        if rest:
            inputs["Weights"] = rest[0]
        helper.append_op(
            type="precision_recall", inputs=inputs,
            outputs={"BatchMetrics": batch_m, "AccumMetrics": accum_m,
                     "AccumStatesInfo": states},
            attrs={"class_number": class_num})
        return accum_m

    return Layer(name=name, parents=parents, build_fn=build,
                 layer_type="evaluator")


def ctc_error(input, label, name=None):
    """Normalized edit distance between decoded sequences and labels (v1
    ctc_error_evaluator, reference edit_distance_op.h)."""

    def build(pv, lv):
        # frame-level class scores arrive from the acoustic model (the
        # v1 evaluator decoded internally): greedy best-path decode —
        # merge repeats, drop blanks — then edit distance on token ids.
        # Already-decoded integer sequences pass through unchanged.
        from ..fluid import core as fcore
        ids = pv
        if fcore.convert_dtype_to_np(pv.dtype).kind == "f" and \
                len(pv.shape) >= 2 and int(pv.shape[-1]) > 1:
            ids = F.ctc_greedy_decoder(input=pv,
                                       blank=int(pv.shape[-1]) - 1)
        dist, _ = F.edit_distance(input=ids, label=lv, normalized=True)
        return F.mean(dist)

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def chunk(input, label, chunk_scheme, num_chunk_types,
          excluded_chunk_types=None, name=None):
    """Chunk-level F1 for sequence labeling (v1 chunk_evaluator,
    reference chunk_eval_op.h)."""

    def build(pv, lv):
        f1 = F.chunk_eval(input=pv, label=lv, chunk_scheme=chunk_scheme,
                          num_chunk_types=num_chunk_types,
                          excluded_chunk_types=excluded_chunk_types)[2]
        return f1

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def sum(input, name=None):
    """Sum of the input values over the batch (v1 sum_evaluator)."""

    def build(pv):
        return F.reduce_sum(pv)

    return Layer(name=name, parents=[input], build_fn=build,
                 layer_type="evaluator")


def column_sum(input, name=None):
    """Per-column sum over the batch (v1 column_sum_evaluator)."""

    def build(pv):
        return F.reduce_sum(pv, dim=0)

    return Layer(name=name, parents=[input], build_fn=build,
                 layer_type="evaluator")


def _printer(input, message, name, transform=None, print_phase="forward"):
    def build(pv):
        v = transform(pv) if transform else pv
        F.Print(v, message=message or (name or "eval"),
                print_phase=print_phase)
        return v

    return Layer(name=name, parents=[input], build_fn=build,
                 layer_type="evaluator")


def value_printer(input, name=None):
    """Print the layer's forward values (v1 value_printer_evaluator)."""
    return _printer(input, "value", name)


def gradient_printer(input, name=None):
    """Print the gradient flowing through this node during backward (v1
    gradient_printer_evaluator). The print op's registered print_grad
    dumps the incoming cotangent (reference print_op.cc print_phase
    'backward'), so gradients print when THIS NODE'S OUTPUT is used on
    the differentiated path — e.g. feed its return value into the cost.
    As a pure extra_layers leaf no backward reaches it (the reference's
    gserver hooked evaluators into its own backward pass; this engine's
    autodiff only visits ops on the loss path)."""
    def build(pv):
        return F.Print(pv, message=(name or "gradient"),
                       print_phase="backward")

    return Layer(name=name, parents=[input], build_fn=build,
                 layer_type="evaluator")


def maxid_printer(input, name=None):
    """Print the argmax id per sample (v1 maxid_printer_evaluator)."""
    return _printer(input, "maxid", name,
                    transform=lambda pv: F.argmax(pv, axis=-1))


def maxframe_printer(input, name=None):
    """Print each sequence's maximal frame (v1
    maxframe_printer_evaluator)."""
    return _printer(input, "maxframe", name,
                    transform=lambda pv: F.reduce_max(pv, dim=-1))


def seqtext_printer(input, name=None, result_file=None):
    """Print sequence token ids (v1 seqtext_printer_evaluator; the
    reference wrote to result_file — accepted for config parity, output
    goes to the log here)."""
    return _printer(input, "seqtext", name)


def classification_error_printer(input, label, name=None):
    """Print the per-batch classification error (v1
    classification_error_printer_evaluator)."""

    def build(pv, lv):
        acc = F.accuracy(input=pv, label=lv)
        err = F.scale(acc, scale=-1.0, bias=1.0)
        F.Print(err, message=name or "classification_error")
        return err

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")


def detection_map(input, label, overlap_threshold=0.5,
                  background_id=0, evaluate_difficult=False,
                  ap_type="11point", name=None):
    """Streaming detection mAP (v1 detection_map_evaluator, reference
    detection_map_op.cc). ``input`` is the detection output [[label,
    score, xmin, ymin, xmax, ymax]]; ``label`` the ground-truth boxes.
    Accumulator states are persistable (fluid.metrics.DetectionMAP's
    wiring), so one Inference machine reports the cumulative pass mAP."""
    from ..fluid.layer_helper import LayerHelper
    from ..fluid.initializer import Constant
    from ..fluid import unique_name as _un

    def build(pv, lv):
        helper = LayerHelper("detection_map_eval")
        gb = helper.main_program.global_block()

        def state(tag, shape, dtype):
            v = gb.create_var(name=_un.generate("dmap_" + tag),
                              shape=shape, dtype=dtype, persistable=True,
                              stop_gradient=True)
            helper.set_variable_initializer(v, Constant(0))
            return v

        states = [state("pos", [1, 2], "int32"),
                  state("tp", [1, 3], "float32"),
                  state("fp", [1, 3], "float32")]
        has_state = state("has", [1], "int32")
        m = F.detection_map(
            detect_res=pv, label=lv,
            background_label=background_id,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            has_state=has_state, input_states=states, out_states=states,
            ap_version="integral" if ap_type == "Integral" else ap_type)
        F.fill_constant(shape=[1], dtype="int32", value=1, out=has_state)
        return m

    return Layer(name=name, parents=[input, label], build_fn=build,
                 layer_type="evaluator")
