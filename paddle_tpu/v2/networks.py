"""v2 composed networks (reference python/paddle/v2/networks.py →
trainer_config_helpers/networks.py). The widely-used compositions,
expressed over the v2 layer DSL."""

from . import layer as L
from . import activation as A
from . import pooling as P

__all__ = [
    "sequence_conv_pool", "simple_lstm", "simple_img_conv_pool",
    "img_conv_bn_pool", "img_conv_group", "simple_gru", "bidirectional_gru",
    "text_conv_pool", "bidirectional_lstm", "vgg_16_network", "small_vgg",
    "inputs", "outputs",
]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, num_channel=None,
                         param_attr=None, shared_bias=True, name=None,
                         conv_stride=1, conv_padding=0, bias_attr=None,
                         pool_type=None):
    """conv + pool (trainer_config_helpers/networks.py
    simple_img_conv_pool)."""
    conv = L.img_conv(input=input, filter_size=filter_size,
                      num_filters=num_filters, num_channels=num_channel,
                      stride=conv_stride, padding=conv_padding, act=act,
                      param_attr=param_attr, bias_attr=bias_attr)
    return L.img_pool(input=conv, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max(), name=name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride, act=None, num_channel=None,
                     conv_stride=1, conv_padding=0, conv_param_attr=None,
                     conv_bias_attr=None, bn_param_attr=None,
                     bn_bias_attr=None, pool_type=None, name=None):
    conv = L.img_conv(input=input, filter_size=filter_size,
                      num_filters=num_filters, num_channels=num_channel,
                      stride=conv_stride, padding=conv_padding, act=None,
                      param_attr=conv_param_attr, bias_attr=conv_bias_attr)
    bn = L.batch_norm(input=conv, act=act, param_attr=bn_param_attr,
                      bias_attr=bn_bias_attr)
    return L.img_pool(input=bn, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max(), name=name)


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type=None, param_attr=None):
    """stacked convs (optionally +BN+dropout) then one pool — the VGG
    building block."""
    tmp = input
    if not isinstance(conv_with_batchnorm, (list, tuple)):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, (list, tuple)):
        conv_batchnorm_drop_rate = \
            [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = L.img_conv(
            input=tmp, filter_size=conv_filter_size, num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=conv_padding,
            act=None if conv_with_batchnorm[i] else conv_act,
            param_attr=param_attr)
        if conv_with_batchnorm[i]:
            tmp = L.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = L.dropout(input=tmp,
                                dropout_rate=conv_batchnorm_drop_rate[i])
    return L.img_pool(input=tmp, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """VGG-16 (trainer_config_helpers/networks.py vgg_16_network)."""
    tmp = input_image
    for i, (n, nf) in enumerate([(2, 64), (2, 128), (3, 256), (3, 512),
                                 (3, 512)]):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[nf] * n, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=A.Relu(), pool_stride=2)
    tmp = L.fc(input=tmp, size=4096, act=A.Relu())
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    tmp = L.fc(input=tmp, size=4096, act=A.Relu())
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    return L.fc(input=tmp, size=num_classes, act=A.Softmax())


def small_vgg(input_image, num_channels, num_classes=1000):
    tmp = input_image
    for i, (n, nf) in enumerate([(2, 64), (2, 128), (3, 256), (3, 512)]):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[nf] * n, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=A.Relu(), pool_stride=2)
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    tmp = L.fc(input=tmp, size=512, act=A.Relu())
    tmp = L.batch_norm(input=tmp, act=A.Relu())
    return L.fc(input=tmp, size=num_classes, act=A.Softmax())


def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None):
    """fc(4*size) + lstmemory (trainer_config_helpers simple_lstm)."""
    proj = L.fc(input=input, size=size * 4, act=None,
                param_attr=mat_param_attr, bias_attr=False)
    return L.lstmemory(input=proj, name=name, reverse=reverse, act=act,
                       gate_act=gate_act, state_act=state_act,
                       bias_attr=bias_param_attr,
                       param_attr=inner_param_attr)


def bidirectional_lstm(input, size, name=None, return_seq=False,
                       fwd_mat_param_attr=None, bwd_mat_param_attr=None):
    fwd = simple_lstm(input=input, size=size,
                      mat_param_attr=fwd_mat_param_attr)
    bwd = simple_lstm(input=input, size=size, reverse=True,
                      mat_param_attr=bwd_mat_param_attr)
    if return_seq:
        return L.concat(input=[fwd, bwd], name=name)
    return L.concat(input=[L.last_seq(input=fwd), L.first_seq(input=bwd)],
                    name=name)


def simple_gru(input, size, name=None, reverse=False, mixed_param_attr=None,
               gru_param_attr=None, gru_bias_attr=None, act=None,
               gate_act=None):
    proj = L.fc(input=input, size=size * 3, act=None,
                param_attr=mixed_param_attr, bias_attr=False)
    return L.grumemory(input=proj, name=name, reverse=reverse, act=act,
                       gate_act=gate_act, param_attr=gru_param_attr,
                       bias_attr=gru_bias_attr)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_mixed_param_attr=None, bwd_mixed_param_attr=None):
    fwd = simple_gru(input=input, size=size,
                     mixed_param_attr=fwd_mixed_param_attr)
    bwd = simple_gru(input=input, size=size, reverse=True,
                     mixed_param_attr=bwd_mixed_param_attr)
    if return_seq:
        return L.concat(input=[fwd, bwd], name=name)
    return L.concat(input=[L.last_seq(input=fwd), L.first_seq(input=bwd)],
                    name=name)


def text_conv_pool(input, context_len, hidden_size, name=None,
                   context_start=None, pool_type=None, fc_act=None,
                   fc_param_attr=None):
    """context window fc + sequence pooling (text CNN building block)."""
    fc = L.fc(input=input, size=hidden_size, act=fc_act,
              param_attr=fc_param_attr)
    return L.pooling(input=fc, pooling_type=pool_type or P.Max(), name=name)


sequence_conv_pool = text_conv_pool


def inputs(layers, *args):
    """Declare data layer order (trainer_config_helpers inputs())."""
    if not isinstance(layers, (list, tuple)):
        layers = [layers] + list(args)
    return list(layers)


def outputs(layers, *args):
    """Declare output layers (trainer_config_helpers outputs())."""
    if not isinstance(layers, (list, tuple)):
        layers = [layers] + list(args)
    return list(layers)
