"""v2 composed networks (reference python/paddle/v2/networks.py →
trainer_config_helpers/networks.py). The widely-used compositions,
expressed over the v2 layer DSL."""

from . import layer as L
from . import activation as A
from . import pooling as P

__all__ = [
    "sequence_conv_pool", "simple_lstm", "simple_img_conv_pool",
    "img_conv_bn_pool", "img_conv_group", "simple_gru", "bidirectional_gru",
    "text_conv_pool", "bidirectional_lstm", "vgg_16_network", "small_vgg",
    "inputs", "outputs",
]


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, num_channel=None,
                         param_attr=None, shared_bias=True, name=None,
                         conv_stride=1, conv_padding=0, bias_attr=None,
                         pool_type=None):
    """conv + pool (trainer_config_helpers/networks.py
    simple_img_conv_pool)."""
    conv = L.img_conv(input=input, filter_size=filter_size,
                      num_filters=num_filters, num_channels=num_channel,
                      stride=conv_stride, padding=conv_padding, act=act,
                      param_attr=param_attr, bias_attr=bias_attr)
    return L.img_pool(input=conv, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max(), name=name)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     pool_stride, act=None, num_channel=None,
                     conv_stride=1, conv_padding=0, conv_param_attr=None,
                     conv_bias_attr=None, bn_param_attr=None,
                     bn_bias_attr=None, pool_type=None, name=None):
    conv = L.img_conv(input=input, filter_size=filter_size,
                      num_filters=num_filters, num_channels=num_channel,
                      stride=conv_stride, padding=conv_padding, act=None,
                      param_attr=conv_param_attr, bias_attr=conv_bias_attr)
    bn = L.batch_norm(input=conv, act=act, param_attr=bn_param_attr,
                      bias_attr=bn_bias_attr)
    return L.img_pool(input=bn, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max(), name=name)


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type=None, param_attr=None):
    """stacked convs (optionally +BN+dropout) then one pool — the VGG
    building block."""
    tmp = input
    if not isinstance(conv_with_batchnorm, (list, tuple)):
        conv_with_batchnorm = [conv_with_batchnorm] * len(conv_num_filter)
    if not isinstance(conv_batchnorm_drop_rate, (list, tuple)):
        conv_batchnorm_drop_rate = \
            [conv_batchnorm_drop_rate] * len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = L.img_conv(
            input=tmp, filter_size=conv_filter_size, num_filters=nf,
            num_channels=num_channels if i == 0 else None,
            padding=conv_padding,
            act=None if conv_with_batchnorm[i] else conv_act,
            param_attr=param_attr)
        if conv_with_batchnorm[i]:
            tmp = L.batch_norm(input=tmp, act=conv_act)
            if conv_batchnorm_drop_rate[i]:
                tmp = L.dropout(input=tmp,
                                dropout_rate=conv_batchnorm_drop_rate[i])
    return L.img_pool(input=tmp, pool_size=pool_size, stride=pool_stride,
                      pool_type=pool_type or P.Max())


def vgg_16_network(input_image, num_channels, num_classes=1000):
    """VGG-16 (trainer_config_helpers/networks.py vgg_16_network)."""
    tmp = input_image
    for i, (n, nf) in enumerate([(2, 64), (2, 128), (3, 256), (3, 512),
                                 (3, 512)]):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[nf] * n, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=A.Relu(), pool_stride=2)
    tmp = L.fc(input=tmp, size=4096, act=A.Relu())
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    tmp = L.fc(input=tmp, size=4096, act=A.Relu())
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    return L.fc(input=tmp, size=num_classes, act=A.Softmax())


def small_vgg(input_image, num_channels, num_classes=1000):
    tmp = input_image
    for i, (n, nf) in enumerate([(2, 64), (2, 128), (3, 256), (3, 512)]):
        tmp = img_conv_group(
            input=tmp, conv_num_filter=[nf] * n, pool_size=2,
            num_channels=num_channels if i == 0 else None,
            conv_act=A.Relu(), pool_stride=2)
    tmp = L.dropout(input=tmp, dropout_rate=0.5)
    tmp = L.fc(input=tmp, size=512, act=A.Relu())
    tmp = L.batch_norm(input=tmp, act=A.Relu())
    return L.fc(input=tmp, size=num_classes, act=A.Softmax())


def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None):
    """fc(4*size) + lstmemory (trainer_config_helpers simple_lstm)."""
    proj = L.fc(input=input, size=size * 4, act=None,
                param_attr=mat_param_attr, bias_attr=False)
    return L.lstmemory(input=proj, name=name, reverse=reverse, act=act,
                       gate_act=gate_act, state_act=state_act,
                       bias_attr=bias_param_attr,
                       param_attr=inner_param_attr)


def bidirectional_lstm(input, size, name=None, return_seq=False,
                       fwd_mat_param_attr=None, bwd_mat_param_attr=None):
    fwd = simple_lstm(input=input, size=size,
                      mat_param_attr=fwd_mat_param_attr)
    bwd = simple_lstm(input=input, size=size, reverse=True,
                      mat_param_attr=bwd_mat_param_attr)
    if return_seq:
        return L.concat(input=[fwd, bwd], name=name)
    return L.concat(input=[L.last_seq(input=fwd), L.first_seq(input=bwd)],
                    name=name)


def simple_gru(input, size, name=None, reverse=False, mixed_param_attr=None,
               gru_param_attr=None, gru_bias_attr=None, act=None,
               gate_act=None):
    proj = L.fc(input=input, size=size * 3, act=None,
                param_attr=mixed_param_attr, bias_attr=False)
    return L.grumemory(input=proj, name=name, reverse=reverse, act=act,
                       gate_act=gate_act, param_attr=gru_param_attr,
                       bias_attr=gru_bias_attr)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_mixed_param_attr=None, bwd_mixed_param_attr=None):
    fwd = simple_gru(input=input, size=size,
                     mixed_param_attr=fwd_mixed_param_attr)
    bwd = simple_gru(input=input, size=size, reverse=True,
                     mixed_param_attr=bwd_mixed_param_attr)
    if return_seq:
        return L.concat(input=[fwd, bwd], name=name)
    return L.concat(input=[L.last_seq(input=fwd), L.first_seq(input=bwd)],
                    name=name)


def text_conv_pool(input, context_len, hidden_size, name=None,
                   context_start=None, pool_type=None, fc_act=None,
                   fc_param_attr=None):
    """context window fc + sequence pooling (text CNN building block)."""
    fc = L.fc(input=input, size=hidden_size, act=fc_act,
              param_attr=fc_param_attr)
    return L.pooling(input=fc, pooling_type=pool_type or P.Max(), name=name)


sequence_conv_pool = text_conv_pool


def inputs(layers, *args):
    """Declare data layer order (trainer_config_helpers inputs())."""
    if not isinstance(layers, (list, tuple)):
        layers = [layers] + list(args)
    return list(layers)


def outputs(layers, *args):
    """Declare output layers (trainer_config_helpers outputs())."""
    if not isinstance(layers, (list, tuple)):
        layers = [layers] + list(args)
    return list(layers)


# ---------------------------------------------------------------------------
# round-4 tail: step units, groups, separable conv, attention family
# (reference trainer_config_helpers/networks.py)
# ---------------------------------------------------------------------------

from .config_base import Layer as _Layer
from ..fluid import layers as F
from ..fluid import unique_name as _un


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None,
                   state_act=None, input_proj_bias_attr=None,
                   input_proj_layer_attr=None, lstm_bias_attr=None,
                   lstm_layer_attr=None):
    """One LSTM step for recurrent_group (reference networks.py:717):
    mixed(identity(input) + W*out_mem) -> lstm_step, with the cell state
    readable as '<name>_state'. `size` is required (this build cannot
    read a layer's width before the topology builds)."""
    if size is None:
        raise ValueError("lstmemory_unit needs an explicit size")
    name = name or _un.generate("lstm_unit")
    out_mem = out_memory if out_memory is not None else \
        L.memory(name=name, size=size)
    state_mem = L.memory(name="%s_state" % name, size=size)
    m = L.mixed(name="%s_input_recurrent" % name, size=size * 4,
                bias_attr=input_proj_bias_attr,
                layer_attr=input_proj_layer_attr,
                input=[L.identity_projection(input=input),
                       L.full_matrix_projection(input=out_mem,
                                                param_attr=param_attr)])
    lstm_out = L.lstm_step(name=name, input=m, state=state_mem,
                           size=size, bias_attr=lstm_bias_attr, act=act,
                           gate_act=gate_act, state_act=state_act,
                           layer_attr=lstm_layer_attr)
    L.get_output(name="%s_state" % name, input=lstm_out,
                 arg_name="state")
    return lstm_out


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None,
                    gate_act=None, state_act=None,
                    input_proj_bias_attr=None, input_proj_layer_attr=None,
                    lstm_bias_attr=None, lstm_layer_attr=None):
    """recurrent_group form of LSTM over a pre-projected (4*size) input
    (reference networks.py:836) — per-step states stay addressable."""
    name = name or _un.generate("lstm_group")

    def __lstm_step__(ipt):
        return lstmemory_unit(
            input=ipt, name=name, size=size, act=act, gate_act=gate_act,
            state_act=state_act, out_memory=out_memory,
            input_proj_bias_attr=input_proj_bias_attr,
            input_proj_layer_attr=input_proj_layer_attr,
            param_attr=param_attr, lstm_layer_attr=lstm_layer_attr,
            lstm_bias_attr=lstm_bias_attr)

    return L.recurrent_group(name="%s_recurrent_group" % name,
                             step=__lstm_step__, reverse=reverse,
                             input=input)


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_bias_attr=None, gru_param_attr=None, act=None,
             gate_act=None, gru_layer_attr=None, naive=False):
    """One GRU step for recurrent_group over a pre-projected (3*size)
    input (reference networks.py:940)."""
    if size is None:
        raise ValueError("gru_unit needs an explicit size")
    name = name or _un.generate("gru_unit")
    out_mem = L.memory(name=name, size=size, boot_layer=memory_boot)
    return L.gru_step(name=name, input=input, output_mem=out_mem,
                      size=size * 3, bias_attr=gru_bias_attr,
                      param_attr=gru_param_attr, act=act,
                      gate_act=gate_act, layer_attr=gru_layer_attr)


def gru_group(input, memory_boot=None, size=None, name=None,
              reverse=False, gru_bias_attr=None, gru_param_attr=None,
              act=None, gate_act=None, gru_layer_attr=None, naive=False):
    """recurrent_group form of GRU (reference networks.py:1002)."""
    name = name or _un.generate("gru_group")

    def __gru_step__(ipt):
        return gru_unit(
            input=ipt, name=name, memory_boot=memory_boot, size=size,
            gru_bias_attr=gru_bias_attr, gru_param_attr=gru_param_attr,
            act=act, gate_act=gate_act, gru_layer_attr=gru_layer_attr,
            naive=naive)

    return L.recurrent_group(name="%s_recurrent_group" % name,
                             step=__gru_step__, reverse=reverse,
                             input=input)


def simple_gru2(input, size, name=None, reverse=False,
                mixed_param_attr=None, mixed_bias_attr=None,
                gru_param_attr=None, gru_bias_attr=None, act=None,
                gate_act=None, mixed_layer_attr=None,
                gru_cell_attr=None):
    """fc(3*size) + gru_group (reference networks.py:1163 — same maths
    as simple_gru, grouped step-by-step)."""
    proj = L.fc(input=input, size=size * 3, act=None,
                param_attr=mixed_param_attr, bias_attr=mixed_bias_attr,
                layer_attr=mixed_layer_attr)
    return gru_group(input=proj, size=size, name=name, reverse=reverse,
                     gru_bias_attr=gru_bias_attr,
                     gru_param_attr=gru_param_attr, act=act,
                     gate_act=gate_act, gru_layer_attr=gru_cell_attr)


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, shared_bias=True,
                       layer_type="exconv", name=None):
    """Depthwise conv (groups == channels) + 1x1 pointwise mix
    (reference networks.py img_separable_conv; Xception)."""
    depthwise = L.img_conv(input=input, filter_size=filter_size,
                           num_filters=num_channels * depth_multiplier,
                           num_channels=num_channels, stride=stride,
                           padding=padding, groups=num_channels,
                           act=None, param_attr=param_attr,
                           bias_attr=bias_attr)
    return L.img_conv(input=depthwise, filter_size=1,
                      num_filters=num_out_channels,
                      num_channels=num_channels * depth_multiplier,
                      stride=1, padding=0, act=act,
                      param_attr=param_attr, bias_attr=bias_attr,
                      name=name)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None):
    """Additive (Bahdanau) attention context (reference
    networks.py:1400): e_j = v tanh(W s + U h_j), weights =
    softmax-over-sequence, context = sum_j w_j h_j. Widths come from the
    built vars, matching the size-free reference API."""
    from .attr import lower_param_attr as _lp

    def build(enc, proj, state):
        att = int(proj.shape[-1])
        s_proj = F.fc(state, size=att,
                      param_attr=_lp(transform_param_attr),
                      bias_attr=False)                  # [B, A]
        combined = F.elementwise_add(proj,
                                     F.unsqueeze(s_proj, axes=[1]))
        act_name = getattr(weight_act, "fluid_act", None) \
            if weight_act is not None else "tanh"
        if act_name:                   # fluid_act None == linear
            combined = getattr(F, act_name)(combined)
        v = F.create_parameter(shape=[att, 1], dtype="float32",
                               attr=_lp(softmax_param_attr))
        scores = F.matmul(combined, v)                  # [B, T, 1]
        weights = F.sequence_softmax(scores)
        return F.reduce_sum(F.elementwise_mul(enc, weights), dim=1)

    return L._remember(_Layer(
        name=name, parents=[encoded_sequence, encoded_proj,
                            decoder_state],
        build_fn=build, layer_type="simple_attention"))


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None):
    """Dot-product attention (reference networks.py:1498): e_j = s^T h_j
    over encoded_sequence, context = weighted sum of attended_sequence."""

    def build(enc, att, state):
        # matmul keeps the LoD companion (reduce_* ops drop it, which
        # would unmask the padded tail in the sequence softmax)
        scores = F.matmul(enc, F.unsqueeze(state, axes=[2]))  # [B, T, 1]
        weights = F.sequence_softmax(scores)
        return F.reduce_sum(F.elementwise_mul(att, weights), dim=1)

    return L._remember(_Layer(
        name=name, parents=[encoded_sequence, attended_sequence,
                            transformed_state],
        build_fn=build, layer_type="dot_product_attention"))


def multi_head_attention(query, key, value, key_proj_size, value_proj_size,
                         head_num, attention_type, softmax_param_attr=None,
                         name=None):
    """Multi-head attention (reference networks.py:1580): project q/k/v
    per head, score by dot-product or additive attention over the key
    sequence, concat the per-head weighted value sums."""
    if attention_type not in ("dot-product attention",
                              "additive attention"):
        raise ValueError("unknown attention_type %r" % attention_type)
    assert key_proj_size % head_num == 0
    assert value_proj_size % head_num == 0

    def build(qv, kv, vv):
        dk = key_proj_size // head_num
        dv = value_proj_size // head_num
        dq, dkv, dvv = (int(qv.shape[-1]), int(kv.shape[-1]),
                        int(vv.shape[-1]))
        heads = []
        for h in range(head_num):
            wq = F.create_parameter(shape=[dq, dk], dtype="float32")
            wk = F.create_parameter(shape=[dkv, dk], dtype="float32")
            wv = F.create_parameter(shape=[dvv, dv], dtype="float32")
            qh = F.matmul(qv, wq)                             # [B, dk]
            kh = F.matmul(kv, wk)                             # [B, T, dk]
            vh = F.matmul(vv, wv)                             # [B, T, dv]
            if attention_type == "dot-product attention":
                scores = F.scale(
                    F.matmul(kh, F.unsqueeze(qh, axes=[2])),
                    scale=1.0 / float(dk) ** 0.5)             # [B, T, 1]
            else:
                combined = F.tanh(
                    F.elementwise_add(kh, F.unsqueeze(qh, axes=[1])))
                from .attr import lower_param_attr as _lp
                va = F.create_parameter(shape=[dk, 1], dtype="float32",
                                        attr=_lp(softmax_param_attr))
                scores = F.matmul(combined, va)
            weights = F.sequence_softmax(scores)
            heads.append(F.reduce_sum(
                F.elementwise_mul(vh, weights), dim=1))       # [B, dv]
        return heads[0] if len(heads) == 1 else F.concat(heads, axis=1)

    return L._remember(_Layer(
        name=name, parents=[query, key, value], build_fn=build,
        layer_type="multi_head_attention"))


__all__ += [
    "lstmemory_unit", "lstmemory_group", "gru_unit", "gru_group",
    "simple_gru2", "img_separable_conv", "simple_attention",
    "dot_product_attention", "multi_head_attention",
]
