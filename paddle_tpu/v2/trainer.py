"""v2 SGD trainer (reference python/paddle/v2/trainer.py:37).

The reference SGD builds a C++ GradientMachine from the topology protobuf
and pumps ParameterUpdater callbacks around forward/backward. The TPU
build compiles the same topology's fluid Program (+ append_backward +
optimizer ops) into one jitted XLA step via the fluid Executor, and drives
the identical user contract: ``SGD(cost, parameters, update_equation)``
then ``train(reader, num_passes, event_handler, feeding)`` with
BeginPass/BeginIteration/EndIteration/EndPass events.
"""

import numpy as np

from . import event as v2_event
from .topology import Topology
from .parameters import Parameters
from ..fluid import executor as _executor
from ..fluid import clip as _clip
from ..fluid import layers as F
from ..fluid.data_feeder import DataFeeder
from ..fluid.framework import program_guard

__all__ = ["SGD"]


def default_event_handler(event):
    """reference trainer.py:26"""
    pass


class SGD(object):
    def __init__(self, cost, parameters, update_equation, extra_layers=None,
                 is_local=True, pserver_spec=None, use_etcd=True):
        if not isinstance(parameters, Parameters):
            raise TypeError("parameters must be paddle.v2 Parameters")
        self.__topology__ = Topology(cost, extra_layers=extra_layers)
        self.__parameters__ = parameters
        self.__update_equation__ = update_equation

        self._scope = _executor.Scope()
        self._exe = _executor.Executor()
        main = self.__topology__.main_program
        startup = self.__topology__.startup_program
        self._cost_var = self.__topology__.output_vars[0]
        with self.__topology__.name_guard():
            with program_guard(main, startup):
                # build inside the guard: lr schedules emit in-graph decay
                # ops that must land in THIS program
                fluid_opt = update_equation.to_fluid()
                clip = getattr(fluid_opt, "_v2_grad_clip", None)
                if clip is not None:
                    _clip.set_gradient_clip(clip, program=main)
                fluid_opt.minimize(self._cost_var)
                self._model_average = None
                ma = getattr(update_equation, "model_average", None)
                if ma is not None:
                    from ..fluid.optimizer import ModelAverage as FluidMA
                    self._model_average = FluidMA(
                        average_window_rate=ma.average_window,
                        min_average_window=(ma.min_average_window
                                            if ma.min_average_window
                                            is not None else 10000),
                        max_average_window=(ma.max_average_window
                                            if ma.max_average_window
                                            is not None else 10000))
            # metrics: when the cost is classification over (softmax, label),
            # track classification error like the reference's default
            # evaluator wiring
            self._metric_vars = {}
            cost_layer = (cost[0] if isinstance(cost, (list, tuple))
                          else cost)
            pl = cost_layer.parents()
            if (cost_layer.layer_type == "cost" and len(pl) >= 2
                    and pl[1].layer_type == "data"
                    and pl[1].data_type.type == 3):  # Index label
                pred = self.__topology__.var_for(pl[0])
                label = self.__topology__.var_for(pl[1])
                with program_guard(main, startup):
                    acc = F.accuracy(input=pred, label=label)
                self._metric_vars["classification_error_evaluator"] = acc
        # initialize scope: startup for non-param state, then the pool
        with _executor.scope_guard(self._scope):
            self._exe.run(startup)
        self.__parameters__.push_to_scope(self._scope)
        self._train_prog = main

    def get_topology_proto(self):
        return self.__topology__.proto()

    def save_parameter_to_tar(self, f):
        self.__sync_back__()
        self.__parameters__.to_tar(f)

    def __sync_back__(self):
        self.__parameters__.pull_from_scope(self._scope)

    def _feeder(self, feeding):
        from .data_feeder import resolve_feed_order
        names = resolve_feed_order(
            [n for n, _ in self.__topology__.data_type()], feeding)
        feed_vars = [self._train_prog.global_block().var(n) for n in names]
        return DataFeeder(feed_list=feed_vars, program=self._train_prog)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """reference trainer.py:137 — reader yields SAMPLES (not batches);
        compose with paddle.batch to form minibatches."""
        if event_handler is None:
            event_handler = default_event_handler
        feeder = self._feeder(feeding)
        fetch = [self._cost_var] + list(self._metric_vars.values())
        metric_names = list(self._metric_vars.keys())
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_costs, pass_metrics = [], []
            for batch_id, data_batch in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                with _executor.scope_guard(self._scope):
                    outs = self._exe.run(self._train_prog,
                                         feed=feeder.feed(data_batch),
                                         fetch_list=fetch)
                cost = float(np.asarray(outs[0]).ravel()[0])
                # accuracy fetch -> error rate, matching the reference's
                # classification_error_evaluator semantics
                metrics = dict(
                    (k, 1.0 - float(np.asarray(o).ravel()[0])
                     if k == "classification_error_evaluator"
                     else float(np.asarray(o).ravel()[0]))
                    for k, o in zip(metric_names, outs[1:]))
                pass_costs.append(cost)
                pass_metrics.append(metrics)
                event_handler(v2_event.EndForwardBackward(pass_id, batch_id))
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id, cost, evaluator=metrics))
            agg = {}
            if pass_metrics:
                for k in metric_names:
                    agg[k] = float(np.mean([m[k] for m in pass_metrics]))
            event_handler(v2_event.EndPass(pass_id, evaluator=agg))
        self.__sync_back__()

    def test(self, reader, feeding=None):
        """reference trainer.py:217 — evaluate on a reader, return
        TestResult(cost, metrics). Runs the forward program only (the
        topology's programs untouched by optimizer ops)."""
        from .data_feeder import resolve_feed_order
        topo = Topology(self.__topology__.layers)
        cost_var = topo.output_vars[0]
        scope = _executor.Scope()
        with _executor.scope_guard(scope):
            self._exe.run(topo.startup_program)
        if self._model_average is not None:
            # evaluate with the sliding-window averaged weights, like the
            # reference's ParameterUpdater apply/restore around testing
            with _executor.scope_guard(self._scope):
                with self._model_average.apply(executor=self._exe):
                    self.__parameters__.pull_from_scope(self._scope)
        else:
            self.__sync_back__()
        self.__parameters__.push_to_scope(scope)
        names = resolve_feed_order(
            [n for n, _ in topo.data_type()], feeding)
        feed_vars = [topo.main_program.global_block().var(n) for n in names]
        feeder = DataFeeder(feed_list=feed_vars, program=topo.main_program)
        test_prog = topo.main_program.clone(for_test=True)
        costs, count = [], 0
        for data_batch in reader():
            with _executor.scope_guard(scope):
                outs = self._exe.run(test_prog,
                                     feed=feeder.feed(data_batch),
                                     fetch_list=[cost_var])
            costs.append(float(np.asarray(outs[0]).ravel()[0])
                         * len(data_batch))
            count += len(data_batch)
        avg = sum(costs) / max(count, 1)
        return v2_event.TestResult(evaluator={}, cost=avg)
