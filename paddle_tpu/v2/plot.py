"""v2 training-curve plotter (reference python/paddle/v2/plot/plot.py
Ploter). Falls back to text output when matplotlib is unavailable or the
session is headless, like the reference's DISABLE_PLOT path."""

import os

__all__ = ["Ploter"]


class PlotData(object):
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = dict((t, PlotData()) for t in args)
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")
        self.__plot__ = None
        if not self.__plot_is_disabled__():
            try:
                import matplotlib.pyplot as plt
                self.__plot__ = plt
            except Exception:
                self.__plot__ = None

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert title in self.__plot_data__
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot__ is not None:
            titles = []
            for title in self.__args__:
                data = self.__plot_data__[title]
                if len(data.step) > 0:
                    self.__plot__.plot(data.step, data.value)
                    titles.append(title)
            self.__plot__.legend(titles, loc="upper left")
            if path:
                self.__plot__.savefig(path)
        else:
            for title in self.__args__:
                data = self.__plot_data__[title]
                if data.step:
                    print("%s: step %s value %.6f"
                          % (title, data.step[-1], data.value[-1]))

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()
