"""v2 layer DSL, TPU-native.

Reference: python/paddle/v2/layer.py (which renames the
trainer_config_helpers DSL — fc_layer→fc, data_layer→data, v2/layer.py:56
__convert_name__) and python/paddle/trainer_config_helpers/layers.py for
the underlying semantics. There, layer calls accrete a ModelConfig protobuf
run by the C++ GradientMachine (legacy/gserver); here each v2 layer is a
declarative ``Layer`` node that lazily emits fluid ops, so one topology
lowers to a single jitted XLA computation — the v1/v2/fluid APIs share the
TPU execution engine instead of carrying a second 139k-LoC interpreter
(SURVEY §2.8).

Sequence inputs ride the fluid LoD system (padded-dense + @LOD_LEN
companions), so `integer_value_sequence` data feeds ragged samples exactly
like the reference's sequence layers.
"""

from . import data_type as _dt
from . import pooling as _pooling
from .attr import lower_param_attr
from .config_base import Layer
from ..fluid import layers as F

__all__ = [
    "data", "fc", "embedding", "img_conv", "img_pool", "img_cmrnorm",
    "batch_norm", "dropout", "concat", "addto", "pooling", "first_seq",
    "last_seq", "max_id", "lstmemory", "grumemory", "expand",
    "seq_reshape", "trans", "scaling", "slope_intercept", "mixed",
    "full_matrix_projection", "identity_projection", "table_projection",
    "classification_cost", "cross_entropy_cost", "regression_cost",
    "square_error_cost", "mse_cost", "multi_binary_label_cross_entropy_cost",
    "huber_regression_cost", "rank_cost", "sum_cost", "crf", "crf_decoding",
    "ctc", "warp_ctc", "nce", "hsigmoid", "eos", "parse_network",
    "get_layer", "recurrent_group", "memory", "StaticInput",
    # round-4 gserver tail + projections/operators
    "dotmul_projection", "scaling_projection",
    "trans_full_matrix_projection", "slice_projection",
    "context_projection", "conv_projection", "dotmul_operator",
    "conv_operator", "cos_sim", "interpolation", "power",
    "sum_to_one_norm", "linear_comb", "bilinear_interp", "repeat",
    "seq_concat", "seq_slice", "pad", "rotate", "maxout", "norm",
    "cross_channel_norm",
    "sampling_id", "out_prod", "block_expand", "crop", "clip",
    "dot_prod", "l2_distance", "smooth_l1_cost", "multiplex", "prelu",
    "gated_unit", "scale_shift", "resize", "row_conv", "sub_seq",
    # round-4b gserver tail
    "row_l2_norm", "tensor", "conv_shift", "switch_order", "upsample",
    "spp", "kmax_seq_score", "scale_sub_region", "factorization_machine",
    "selective_fc", "printer", "priorbox", "multibox_loss",
    "detection_output", "roi_pool", "huber_classification_cost",
    "cross_entropy_with_selfnorm", "lambda_cost", "recurrent",
    "lstm_step", "gru_step", "gru_step_naive", "get_output",
    # generation machinery + 3D tail
    "BaseGeneratedInput", "GeneratedInput", "SubsequenceInput",
    "BeamInput", "beam_search", "cross_entropy_over_beam",
    "img_conv3d", "img_pool3d", "sub_nested_seq",
]

_name_to_layer = {}

# recurrent_group records every layer its step function creates (the
# reference collected step layers via the global config; memories may
# link to SIDE layers like get_output that no output reaches)
_capture_stack = []


def _remember(layer):
    _name_to_layer[layer.name] = layer
    if _capture_stack:
        _capture_stack[-1].append(layer)
    return layer


def get_layer(name):
    """reference v2/layer.py:325"""
    return _name_to_layer.get(name)


def _apply_act(var, act):
    if act is None:
        return var
    if isinstance(act, type):
        act = act()
    name = getattr(act, "fluid_act", None)
    if name is None:
        return var
    if name == "softmax":
        return F.softmax(var)
    if name == "sequence_softmax":
        return F.sequence_softmax(var)
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper(name)
    out = helper.create_variable_for_type_inference(var.dtype)
    helper.append_op(type=name, inputs={"X": var}, outputs={"Out": out})
    return out


def _seq_dim(tp):
    return tp.seq_type != _dt.SequenceType.NO_SEQUENCE


def data(name, type, height=None, width=None, depth=None,
         layer_attr=None):
    """v2 data layer (reference v2/layer.py:87 __data_layer__); `depth`
    gives the NCDHW volume shape for the 3D conv/pool tail."""
    tp = type

    def _lod_level():
        if tp.seq_type == _dt.SequenceType.SUB_SEQUENCE:
            return 2
        return 1 if _seq_dim(tp) else 0

    def build():
        if tp.type == _dt.DataType.Index:
            return F.data(name=name, shape=[1], dtype="int64",
                          lod_level=_lod_level())
        shape = [tp.dim]
        if height and width:
            vol = (depth or 1) * height * width
            ch = tp.dim // vol
            shape = [ch, depth, height, width] if depth \
                else [ch, height, width]
        return F.data(name=name, shape=shape, dtype="float32",
                      lod_level=_lod_level())

    layer = Layer(name=name, parents=[], build_fn=build, layer_type="data")
    layer.data_type = tp
    return _remember(layer)


def _single_input(input):
    if isinstance(input, (list, tuple)):
        if len(input) != 1:
            raise ValueError("this layer takes exactly one input")
        return input[0]
    return input


def fc(input, size, act=None, name=None, param_attr=None, bias_attr=None,
       layer_attr=None):
    """fc_layer (trainer_config_helpers/layers.py fc_layer)."""
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(*parents):
        outs = []
        for i, pv in enumerate(parents):
            pa = param_attr[i] if isinstance(param_attr, (list, tuple)) \
                else param_attr
            outs.append(F.fc(pv, size=size,
                             param_attr=lower_param_attr(pa),
                             bias_attr=False, num_flatten_dims=1))
        out = outs[0]
        for o in outs[1:]:
            out = F.elementwise_add(out, o)
        out = _add_bias(out, bias_attr, size)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=list(inputs), build_fn=build,
                           layer_type="fc", layer_attr=layer_attr))


def _add_bias(var, bias_attr, size):
    if bias_attr is False:
        return var
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper("bias", bias_attr=lower_param_attr(bias_attr),
                         act=None)
    return helper.append_bias_op(var)


def embedding(input, size, param_attr=None, layer_attr=None, name=None):
    def build(pv):
        return F.embedding(pv, size=[input.data_type.dim, size],
                           param_attr=lower_param_attr(param_attr))

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="embedding", layer_attr=layer_attr))


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, name=None, param_attr=None,
             bias_attr=None, groups=1, dilation=1, shared_biases=True,
             layer_attr=None, trans=False):
    def build(pv):
        conv = (F.conv2d_transpose if trans else F.conv2d)
        out = conv(pv, num_filters=num_filters, filter_size=filter_size,
                   stride=stride, padding=padding, dilation=dilation,
                   groups=groups, param_attr=lower_param_attr(param_attr),
                   bias_attr=lower_param_attr(bias_attr)
                   if bias_attr is not None else None)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="conv", layer_attr=layer_attr))


def img_pool(input, pool_size, num_channels=None, pool_type=None, stride=1,
             padding=0, name=None, ceil_mode=True, exclude_mode=True,
             layer_attr=None):
    ptype = pool_type or _pooling.Max()
    if isinstance(ptype, type):
        ptype = ptype()

    def build(pv):
        return F.pool2d(pv, pool_size=pool_size,
                        pool_type=ptype.img_pool_type, pool_stride=stride,
                        pool_padding=padding, ceil_mode=ceil_mode)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="pool", layer_attr=layer_attr))


def img_cmrnorm(input, size, scale=0.0128, power=0.75, name=None,
                num_channels=None, layer_attr=None):
    """local response normalization (img_cmrnorm_layer)."""

    def build(pv):
        return F.lrn(pv, n=size, alpha=scale, beta=power)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="norm", layer_attr=layer_attr))


def batch_norm(input, act=None, name=None, num_channels=None,
               bias_attr=None, param_attr=None, layer_attr=None,
               batch_norm_type=None, moving_average_fraction=0.9,
               use_global_stats=None, mean_var_names=None):
    def build(pv):
        out = F.batch_norm(pv, momentum=moving_average_fraction,
                           param_attr=lower_param_attr(param_attr),
                           bias_attr=lower_param_attr(bias_attr),
                           use_global_stats=bool(use_global_stats))
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="batch_norm", layer_attr=layer_attr))


def dropout(input, dropout_rate, name=None):
    def build(pv):
        return F.dropout(pv, dropout_prob=dropout_rate)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="dropout"))


def concat(input, act=None, name=None, layer_attr=None):
    def build(*parents):
        return _apply_act(F.concat(list(parents), axis=1), act)

    return _remember(Layer(name=name, parents=list(input), build_fn=build,
                           layer_type="concat", layer_attr=layer_attr))


def addto(input, act=None, name=None, bias_attr=None, layer_attr=None):
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(*parents):
        out = parents[0]
        for p in parents[1:]:
            out = F.elementwise_add(out, p)
        if bias_attr not in (None, False):
            out = _add_bias(out, bias_attr, None)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=list(inputs), build_fn=build,
                           layer_type="addto", layer_attr=layer_attr))


def pooling(input, pooling_type=None, name=None, bias_attr=None,
            agg_level=None, layer_attr=None):
    """sequence pooling over a LoD input (pooling_layer)."""
    ptype = pooling_type or _pooling.Max()
    if isinstance(ptype, type):
        ptype = ptype()

    def build(pv):
        return F.sequence_pool(pv, pool_type=ptype.seq_pool_type)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="seq_pool"))


def first_seq(input, name=None, agg_level=None, layer_attr=None):
    def build(pv):
        return F.sequence_first_step(pv)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="first_seq", layer_attr=layer_attr))


def last_seq(input, name=None, agg_level=None, layer_attr=None):
    def build(pv):
        return F.sequence_last_step(pv)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="last_seq", layer_attr=layer_attr))


def max_id(input, name=None, layer_attr=None):
    def build(pv):
        return F.argmax(pv, axis=-1)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="max_id"))


def expand(input, expand_as, name=None, agg_level=None, layer_attr=None):
    def build(pv, ref):
        return F.sequence_expand(pv, ref)

    return _remember(Layer(name=name, parents=[input, expand_as],
                           build_fn=build, layer_type="expand", layer_attr=layer_attr))


def seq_reshape(input, reshape_size, name=None, act=None, bias_attr=None,
                layer_attr=None):
    def build(pv):
        return _apply_act(F.sequence_reshape(pv, new_dim=reshape_size), act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="seq_reshape", layer_attr=layer_attr))


def trans(input, name=None, layer_attr=None):
    def build(pv):
        return F.transpose(pv, perm=[1, 0])

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="trans", layer_attr=layer_attr))


def scaling(input, weight, name=None, layer_attr=None):
    """row-wise scale of `input` by scalar-per-row `weight`."""

    def build(pv, wv):
        return F.elementwise_mul(pv, wv, axis=0)

    return _remember(Layer(name=name, parents=[input, weight],
                           build_fn=build, layer_type="scaling", layer_attr=layer_attr))


def slope_intercept(input, slope=1.0, intercept=0.0, name=None,
                    layer_attr=None):
    def build(pv):
        return F.scale(pv, scale=slope, bias=intercept)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="slope_intercept", layer_attr=layer_attr))


# ---------------------------------------------------------------------------
# mixed layer / projections — the v1 "mixed" aggregation form
# ---------------------------------------------------------------------------

class _Projection(object):
    def __init__(self, input, build_fn, size_parametric=False):
        self.input = input
        self.build_fn = build_fn
        # size-parametric projections (full_matrix/table/trans) default
        # their output width to the enclosing mixed_layer's `size`
        # (reference mixed_layer size inference)
        self.size_parametric = size_parametric


def full_matrix_projection(input, size=0, param_attr=None):
    def build(pv, mixed_size=0):
        return F.fc(pv, size=size or mixed_size,
                    param_attr=lower_param_attr(param_attr),
                    bias_attr=False)

    return _Projection(input, build, size_parametric=not size)


def identity_projection(input, offset=None, size=None):
    def build(pv):
        if offset is None:
            return pv
        end = offset + (size or (pv.shape[-1] - offset))
        return F.slice(pv, axes=[1], starts=[offset], ends=[end])

    return _Projection(input, build)


def table_projection(input, size=0, param_attr=None):
    def build(pv, mixed_size=0):
        return F.embedding(pv, size=[input.data_type.dim,
                                     size or mixed_size],
                           param_attr=lower_param_attr(param_attr))

    return _Projection(input, build, size_parametric=not size)


def dotmul_projection(input, param_attr=None):
    """out = x ⊙ w with a learned [1, D] weight (reference
    trainer_config_helpers DotMulProjection)."""
    def build(pv):
        w = F.create_parameter(
            shape=[1, int(pv.shape[-1])], dtype="float32",
            attr=lower_param_attr(param_attr))
        return F.elementwise_mul(pv, w)

    return _Projection(input, build)


def scaling_projection(input, param_attr=None):
    """out = w * x with a single learned scalar (ScalingProjection)."""
    def build(pv):
        w = F.create_parameter(shape=[1], dtype="float32",
                               attr=lower_param_attr(param_attr))
        return F.elementwise_mul(pv, w)

    return _Projection(input, build)


def trans_full_matrix_projection(input, size=0, param_attr=None):
    """out = x @ Wᵀ — the weight is stored transposed [size, in_dim]
    (TransposedFullMatrixProjection; weight-sharing with an fc going the
    other way)."""
    def build(pv, mixed_size=0):
        w = F.create_parameter(
            shape=[size or mixed_size, int(pv.shape[-1])],
            dtype="float32", attr=lower_param_attr(param_attr))
        return F.matmul(pv, w, transpose_y=True)

    return _Projection(input, build, size_parametric=not size)


def slice_projection(input, slices):
    """Concat of [start, end) column slices (SliceProjection)."""
    def build(pv):
        parts = [F.slice(pv, axes=[len(pv.shape) - 1],
                         starts=[s], ends=[e]) for s, e in slices]
        return parts[0] if len(parts) == 1 \
            else F.concat(parts, axis=len(pv.shape) - 1)

    return _Projection(input, build)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False):
    """Concat a sliding context window of sequence steps (reference
    ContextProjection — the word-window trick under v1 NLP configs).
    Dense realization: the padded-dense [B, T, D] encoding shifts along
    T with zero fill (sequence boundaries are row boundaries, so no
    cross-sequence leakage — the same zero-padding the reference applies
    at sequence edges when padding_attr is False)."""
    start = context_start if context_start is not None \
        else -(context_len // 2)

    def build(pv):
        # T-relative shifts only (the padded T is a runtime property):
        # past offsets slice [0, T-k) and zero-pad the front, future
        # offsets slice [k, T) and zero-pad the back
        outs = []
        for off in range(start, start + context_len):
            if off == 0:
                outs.append(pv)
            elif off < 0:
                body = F.slice(pv, axes=[1], starts=[0], ends=[off])
                outs.append(F.pad(body, paddings=[0, 0, -off, 0, 0, 0]))
            else:
                body = F.slice(pv, axes=[1], starts=[off],
                               ends=[1 << 30])
                outs.append(F.pad(body, paddings=[0, 0, 0, off, 0, 0]))
        # fluid LoD convention: feature concat on a ragged var is axis 1
        # (the concat op shifts past the padded time dim itself)
        return F.concat(outs, axis=1)

    return _Projection(input, build)


class _Operator(object):
    """A mixed_layer operator: multiple inputs, no own parameters
    (reference trainer_config_helpers Operator)."""

    def __init__(self, inputs, build_fn):
        self.inputs = list(inputs)
        self.build_fn = build_fn


def dotmul_operator(a=None, b=None, scale=1.0, **kwargs):
    """out = scale * (a ⊙ b) (DotMulOperator)."""
    a = a if a is not None else kwargs.get("x")
    b = b if b is not None else kwargs.get("y")

    def build(av, bv):
        out = F.elementwise_mul(av, bv)
        return F.scale(out, scale=scale) if scale != 1.0 else out

    return _Operator([a, b], build)


def conv_operator(img, filter, filter_size, num_filters,
                  num_channels=None, stride=1, padding=0,
                  filter_size_y=None, stride_y=None, padding_y=None):
    """Convolve `img` with a DYNAMIC filter produced by another layer
    (ConvOperator): the filter values come from `filter`'s output, not a
    parameter — conv2d's Filter slot is an ordinary input var here, so
    this is a direct lowering."""
    fy = filter_size_y or filter_size
    nc = num_channels

    def build(iv, fv):
        # fv is [B, num_filters*c*fy*fx]: PER-SAMPLE filters (the
        # reference ConvOperator's dynamic-filter semantics) — lowered
        # to the feature-group trick, not a batchless reshape
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper("conv_operator")
        out = helper.create_variable_for_type_inference(iv.dtype)
        helper.append_op(
            type="conv2d_dynamic_filter",
            inputs={"Input": [iv], "Filter": [fv]},
            outputs={"Output": [out]},
            attrs={"num_filters": num_filters,
                   "filter_size_y": fy, "filter_size_x": filter_size,
                   "strides": [stride_y or stride, stride],
                   "paddings": [padding_y or padding, padding]},
            infer_shape=False)
        return out

    return _Operator([img, filter], build)


def conv_projection(input, filter_size, num_filters, num_channels=None,
                    stride=1, padding=0, groups=1, param_attr=None,
                    trans=False):
    """Convolution as a mixed_layer projection (ConvProjection): own
    filter parameter, outputs summed with the other projections."""
    def build(pv):
        conv = F.conv2d_transpose if trans else F.conv2d
        return conv(pv, num_filters=num_filters, filter_size=filter_size,
                    stride=stride, padding=padding, groups=groups,
                    param_attr=lower_param_attr(param_attr),
                    bias_attr=False)

    return _Projection(input, build)


def mixed(size=0, name=None, input=None, act=None, bias_attr=None,
          layer_attr=None):
    """mixed_layer: sum of projections and operators
    (trainer_config_helpers mixed_layer). Projections carry their own
    parameters (full_matrix/table/dotmul/scaling/trans/context/conv);
    operators combine multiple layer outputs (dotmul/conv)."""
    projs = input if isinstance(input, (list, tuple)) else [input]
    parents = []
    arity = []
    for p in projs:
        if isinstance(p, _Operator):
            parents.extend(p.inputs)
            arity.append(len(p.inputs))
        else:
            parents.append(p.input)
            arity.append(1)

    def build(*parent_vars):
        outs, i = [], 0
        for p, n in zip(projs, arity):
            if getattr(p, "size_parametric", False) and size:
                outs.append(p.build_fn(*parent_vars[i:i + n],
                                       mixed_size=size))
            else:
                outs.append(p.build_fn(*parent_vars[i:i + n]))
            i += n
        out = outs[0]
        for o in outs[1:]:
            out = F.elementwise_add(out, o)
        if bias_attr not in (None, False):
            out = _add_bias(out, bias_attr, size)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="mixed", layer_attr=layer_attr))


# ---------------------------------------------------------------------------
# recurrent memories
# ---------------------------------------------------------------------------

def lstmemory(input, name=None, reverse=False, act=None, gate_act=None,
              state_act=None, bias_attr=None, param_attr=None,
              layer_attr=None):
    """LSTM over a pre-projected (4*size) sequence input, like the
    reference lstmemory (trainer_config_helpers layers.py; the projection
    convention is the v1 contract — use networks.simple_lstm for the
    fused projection+lstm form)."""

    def build(pv):
        size = pv.shape[-1] // 4
        h, _ = F.dynamic_lstm(
            pv, size=size * 4, is_reverse=reverse,
            param_attr=lower_param_attr(param_attr),
            bias_attr=lower_param_attr(bias_attr),
            gate_activation=getattr(gate_act, "fluid_act", None) or "sigmoid",
            cell_activation=getattr(state_act, "fluid_act", None) or "tanh",
            candidate_activation=getattr(act, "fluid_act", None) or "tanh")
        return h

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="lstmemory"))


def grumemory(input, name=None, reverse=False, act=None, gate_act=None,
              bias_attr=None, param_attr=None, layer_attr=None):
    """GRU over a pre-projected (3*size) sequence input."""

    def build(pv):
        size = pv.shape[-1] // 3
        return F.dynamic_gru(
            pv, size=size, is_reverse=reverse,
            param_attr=lower_param_attr(param_attr),
            bias_attr=lower_param_attr(bias_attr),
            gate_activation=getattr(gate_act, "fluid_act", None) or "sigmoid",
            candidate_activation=getattr(act, "fluid_act", None) or "tanh")

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="grumemory"))


# ---------------------------------------------------------------------------
# costs
# ---------------------------------------------------------------------------

def classification_cost(input, label, weight=None, name=None,
                        evaluator=None, layer_attr=None):
    """cross-entropy over a softmax output layer (v1 classification_cost).
    `input` is expected to already carry Softmax activation, matching the
    reference convention."""

    def build(pv, lv, *rest):
        ce = F.cross_entropy(pv, lv)
        if rest:
            ce = F.elementwise_mul(ce, rest[0], axis=0)
        return F.mean(ce)

    parents = [input, label] + ([weight] if weight is not None else [])
    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="cost"))


def cross_entropy_cost(input, label, name=None, coeff=1.0, weight=None,
                       layer_attr=None):
    def build(pv, lv, *rest):
        ce = F.cross_entropy(pv, lv)
        if rest:
            ce = F.elementwise_mul(ce, rest[0], axis=0)
        out = F.mean(ce)
        return F.scale(out, scale=coeff) if coeff != 1.0 else out

    parents = [input, label] + ([weight] if weight is not None else [])
    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="cost"))


def square_error_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    def build(pv, lv):
        out = F.mean(F.square_error_cost(pv, lv))
        return F.scale(out, scale=coeff) if coeff != 1.0 else out

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost"))


mse_cost = square_error_cost
regression_cost = square_error_cost


def multi_binary_label_cross_entropy_cost(input, label, name=None,
                                          coeff=1.0, layer_attr=None):
    def build(pv, lv):
        return F.mean(F.sigmoid_cross_entropy_with_logits(pv, lv))

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost"))


def huber_regression_cost(input, label, name=None, delta=1.0, coeff=1.0,
                          layer_attr=None):
    def build(pv, lv):
        return F.mean(F.huber_loss(pv, lv, delta=delta))

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost"))


def rank_cost(left, right, label, name=None, weight=None, coeff=1.0,
              layer_attr=None):
    def build(lv, rv, labv):
        return F.mean(F.margin_rank_loss(labv, lv, rv, margin=0.0))

    return _remember(Layer(name=name, parents=[left, right, label],
                           build_fn=build, layer_type="cost"))


def sum_cost(input, name=None, layer_attr=None):
    def build(pv):
        return F.reduce_sum(pv)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="cost"))


def crf(input, label, size=None, name=None, param_attr=None,
        layer_attr=None):
    """linear-chain CRF cost (crf_layer)."""

    def build(pv, lv):
        from ..fluid.layers import loss as L
        ll = L.linear_chain_crf(pv, lv,
                                param_attr=lower_param_attr(param_attr))
        return F.mean(ll)

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="crf"))


def crf_decoding(input, size=None, label=None, name=None, param_attr=None,
                 layer_attr=None):
    def build(pv, *rest):
        from ..fluid.layers import loss as L
        return L.crf_decoding(pv, param_attr=lower_param_attr(param_attr),
                              label=rest[0] if rest else None)

    parents = [input] + ([label] if label is not None else [])
    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="crf_decoding"))


def ctc(input, label, size=None, name=None, norm_by_times=False,
        layer_attr=None):
    def build(pv, lv):
        from ..fluid.layers import loss as L
        return F.mean(L.warpctc(pv, lv, norm_by_times=norm_by_times))

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="ctc"))


warp_ctc = ctc


def nce(input, label, num_classes, name=None, param_attr=None,
        bias_attr=None, num_neg_samples=10, layer_attr=None):
    def build(pv, lv):
        from ..fluid.layers import loss as L
        return F.mean(L.nce(pv, lv, num_classes,
                            param_attr=lower_param_attr(param_attr),
                            bias_attr=lower_param_attr(bias_attr),
                            num_neg_samples=num_neg_samples))

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="nce"))


def hsigmoid(input, label, num_classes, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    def build(pv, lv):
        from ..fluid.layers import loss as L
        return F.mean(L.hsigmoid(pv, lv, num_classes,
                                 param_attr=lower_param_attr(param_attr),
                                 bias_attr=lower_param_attr(bias_attr)))

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="hsigmoid"))


def eos(input, eos_id, name=None, layer_attr=None):
    def build(pv):
        const = F.fill_constant([1], "int64", eos_id)
        return F.cast(F.equal(pv, const), "float32")

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="eos"))


# ---------------------------------------------------------------------------
# gserver layer tail (VERDICT r3 #5): the commonly-used long tail of
# paddle/legacy/gserver/layers/ Layer classes, lowered to fluid ops.
# ---------------------------------------------------------------------------

def cos_sim(a, b, scale=1, size=1, name=None, layer_attr=None):
    """CosSimLayer (gserver/layers/CosSimLayer.cpp)."""
    def build(av, bv):
        out = F.cos_sim(av, bv)
        return F.scale(out, scale=float(scale)) if scale != 1 else out

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="cos_sim", layer_attr=layer_attr))


def interpolation(input, weight, name=None, layer_attr=None):
    """w*a + (1-w)*b over input=[a, b] (InterpolationLayer)."""
    a, b = input

    def build(wv, av, bv):
        return F.elementwise_add(
            F.elementwise_mul(av, wv, axis=0),
            F.elementwise_mul(
                bv, F.scale(wv, scale=-1.0, bias=1.0), axis=0))

    return _remember(Layer(name=name, parents=[weight, a, b],
                           build_fn=build, layer_type="interpolation",
                           layer_attr=layer_attr))


def power(input, weight, name=None, layer_attr=None):
    """x ** w with a per-sample scalar exponent (PowerLayer)."""
    def build(pv, wv):
        return F.elementwise_pow(pv, wv, axis=0)

    return _remember(Layer(name=name,
                           parents=[_single_input(input), weight],
                           build_fn=build, layer_type="power",
                           layer_attr=layer_attr))


def sum_to_one_norm(input, name=None, layer_attr=None):
    """Row-normalize to sum 1 (SumToOneNormLayer)."""
    def build(pv):
        s = F.reduce_sum(pv, dim=-1, keep_dim=True)
        return F.elementwise_div(pv, s)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="sum_to_one_norm",
                           layer_attr=layer_attr))


def linear_comb(weights, vectors, size=None, name=None, layer_attr=None):
    """out_j = sum_i w_i * vec[i*size+j] (LinearCombLayer /
    convex_comb)."""
    def build(wv, vv):
        m = int(wv.shape[-1])
        d = size or int(vv.shape[-1]) // m
        v3 = F.reshape(vv, shape=[-1, m, d])
        w3 = F.reshape(wv, shape=[-1, m, 1])
        return F.reduce_sum(F.elementwise_mul(v3, w3), dim=1)

    return _remember(Layer(name=name, parents=[weights, vectors],
                           build_fn=build, layer_type="linear_comb",
                           layer_attr=layer_attr))


def bilinear_interp(input, out_size_x, out_size_y, num_channels=None,
                    name=None, layer_attr=None):
    """BilinearInterpLayer -> resize_bilinear."""
    def build(pv):
        return F.resize_bilinear(pv, out_shape=[out_size_y, out_size_x])

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="bilinear_interp",
                           layer_attr=layer_attr))


def repeat(input, num_repeats, as_row_vector=True, act=None, name=None,
           layer_attr=None):
    """Tile features num_repeats times (FeatureMapExpand/RepeatLayer:
    as_row_vector repeats [a b] -> [a b a b]; otherwise interleaves
    [a a b b])."""
    def build(pv):
        if as_row_vector:
            out = F.concat([pv] * num_repeats,
                           axis=len(pv.shape) - 1)
        else:
            last = int(pv.shape[-1])
            e = F.unsqueeze(pv, axes=[len(pv.shape)])
            e = F.expand(e, expand_times=[1] * len(pv.shape)
                         + [num_repeats])
            out = F.reshape(e, shape=[-1, last * num_repeats])
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="repeat",
                           layer_attr=layer_attr))


def seq_concat(a, b, act=None, name=None, layer_attr=None,
               bias_attr=None):
    """Concatenate two sequences time-wise (SequenceConcatLayer)."""
    def build(av, bv):
        return _apply_act(F.sequence_concat([av, bv]), act)

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="seq_concat",
                           layer_attr=layer_attr))


def seq_slice(input, starts=None, ends=None, name=None):
    """SequenceSliceLayer -> sequence_slice (offset/length form)."""
    parents = [_single_input(input)]
    if starts is not None:
        parents.append(starts)
    if ends is not None:
        parents.append(ends)

    def build(pv, *rest):
        i = 0
        sv = ev = None
        if starts is not None:
            sv = rest[i]
            i += 1
        if ends is not None:
            ev = rest[i]
        if sv is None:
            sv = F.fill_constant_batch_size_like(pv, shape=[-1, 1],
                                                 dtype="int64", value=0)
        if ev is None:
            from ..fluid.layers.sequence import _sequence_length
            length = _sequence_length(pv)
            ev = F.cast(F.reshape(length, shape=[-1, 1]), "int64")
        offset = F.cast(sv, "int64")
        length = F.elementwise_sub(F.cast(ev, "int64"), offset)
        return F.sequence_slice(pv, offset=offset, length=length)

    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="seq_slice"))


def pad(input, pad_c=None, pad_h=None, pad_w=None, name=None,
        layer_attr=None):
    """PadLayer: zero-pad channel/height/width of [N, C, H, W]."""
    pc = list(pad_c or [0, 0])
    ph = list(pad_h or [0, 0])
    pw = list(pad_w or [0, 0])

    def build(pv):
        return F.pad(pv, paddings=[0, 0] + pc + ph + pw)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="pad",
                           layer_attr=layer_attr))


def rotate(input, height, width, name=None, layer_attr=None):
    """RotateLayer: 90-degree CCW rotation of each [C, H, W] map."""
    def build(pv):
        x = F.reshape(pv, shape=[-1, int(pv.shape[-1]) // (height * width),
                                 height, width])
        x = F.transpose(x, perm=[0, 1, 3, 2])
        x = F.reverse(x, axis=[2])
        return F.reshape(x, shape=[-1, int(pv.shape[-1])])

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="rotate",
                           layer_attr=layer_attr))


def maxout(input, groups, num_channels=None, name=None, layer_attr=None):
    """MaxOutLayer -> maxout op."""
    def build(pv):
        return F.maxout(pv, groups=groups)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="maxout",
                           layer_attr=layer_attr))


def cross_channel_norm(input, name=None, param_attr=None,
                       layer_attr=None):
    """CrossChannelNormLayer (the SSD conv4_3 normalizer,
    reference layers.py:1377): L2-normalize across the channel axis at
    each spatial position, then scale by a LEARNED per-channel factor
    (SSD initializes it to 20 via param_attr)."""
    def build(pv):
        out = F.l2_normalize(pv, axis=1)
        channels = int(pv.shape[1])
        from ..fluid.initializer import Constant
        scale = F.create_parameter(
            shape=[channels], dtype="float32",
            attr=lower_param_attr(param_attr),
            default_initializer=Constant(1.0))
        return F.elementwise_mul(out, scale, axis=1)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="norm",
                           layer_attr=layer_attr))


def norm(input, norm_type="cmrnorm-projection", channels=1, size=None,
         name=None, param_attr=None, layer_attr=None, **kw):
    """The v1 Norm-config dispatcher: cross-channel-norm is the learned
    SSD normalizer; cmrnorm-projection is local response normalization
    (img_cmrnorm)."""
    if norm_type == "cross-channel-norm":
        return cross_channel_norm(input, name=name,
                                  param_attr=param_attr,
                                  layer_attr=layer_attr)

    def build(pv):
        if norm_type in ("cmrnorm-projection", "cmrnorm"):
            return F.lrn(pv, n=size or 5,
                         alpha=kw.get("scale", 1e-4),
                         beta=kw.get("power", 0.75))
        return F.l2_normalize(pv, axis=1)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="norm",
                           layer_attr=layer_attr))


def sampling_id(input, name=None, layer_attr=None):
    """SamplingIdLayer -> sampling_id op."""
    def build(pv):
        return F.sampling_id(pv)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="sampling_id",
                           layer_attr=layer_attr))


def out_prod(a, b, name=None, layer_attr=None):
    """Outer product per row (OuterProdLayer): [B,M] x [B,N] ->
    [B, M*N]."""
    def build(av, bv):
        m, n = int(av.shape[-1]), int(bv.shape[-1])
        o = F.matmul(F.reshape(av, shape=[-1, m, 1]),
                     F.reshape(bv, shape=[-1, 1, n]))
        return F.reshape(o, shape=[-1, m * n])

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="out_prod", layer_attr=layer_attr))


def block_expand(input, block_x, block_y, stride_x=None, stride_y=None,
                 padding_x=0, padding_y=0, num_channels=None, name=None,
                 layer_attr=None):
    """BlockExpandLayer -> im2sequence (image patches to sequence)."""
    def build(pv):
        return F.im2sequence(
            pv, filter_size=[block_y, block_x],
            stride=[stride_y or block_y, stride_x or block_x],
            padding=[padding_y, padding_x, padding_y, padding_x])

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="block_expand",
                           layer_attr=layer_attr))


def crop(input, offset, shape=None, axis=2, name=None, layer_attr=None):
    """CropLayer: crop input (optionally to a reference layer's shape)."""
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(pv, *ref):
        ndim = len(pv.shape)
        tgt = list(shape) if shape is not None else \
            [int(d) for d in ref[0].shape]
        # offset/shape anchor at `axis` (reference CropLayer crop_axis);
        # dims before it keep their full extent (non-positive entry)
        full_tgt = tgt if len(tgt) == ndim else \
            ([0] * axis + tgt + [0] * ndim)[:ndim]
        full_off = ([0] * axis + list(offset) + [0] * ndim)[:ndim]
        return F.crop(pv, shape=full_tgt, offsets=full_off)

    return _remember(Layer(name=name, parents=list(inputs),
                           build_fn=build, layer_type="crop",
                           layer_attr=layer_attr))


def clip(input, min, max, name=None, layer_attr=None):
    """ClipLayer -> clip op."""
    def build(pv):
        return F.clip(pv, min=float(min), max=float(max))

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="clip",
                           layer_attr=layer_attr))


def dot_prod(a, b, name=None, layer_attr=None):
    """Row-wise dot product (DotProdLayer)."""
    def build(av, bv):
        return F.reduce_sum(F.elementwise_mul(av, bv), dim=-1,
                            keep_dim=True)

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="dot_prod", layer_attr=layer_attr))


def l2_distance(a, b, name=None, layer_attr=None):
    """Row-wise euclidean distance (L2DistanceLayer)."""
    def build(av, bv):
        d = F.elementwise_sub(av, bv)
        return F.sqrt(F.reduce_sum(F.elementwise_mul(d, d), dim=-1,
                                   keep_dim=True))

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="l2_distance",
                           layer_attr=layer_attr))


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    """SmoothL1CostLayer -> smooth_l1 op."""
    def build(pv, lv):
        out = F.mean(F.smooth_l1(pv, lv))
        return F.scale(out, scale=coeff) if coeff != 1.0 else out

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost",
                           layer_attr=layer_attr))


def multiplex(input, name=None, layer_attr=None):
    """MultiplexLayer: input[0] is the per-row selector into
    input[1:]."""
    index = input[0]
    choices = list(input[1:])

    def build(iv, *cvs):
        return F.multiplex(list(cvs), F.cast(iv, "int32"))

    return _remember(Layer(name=name, parents=[index] + choices,
                           build_fn=build, layer_type="multiplex",
                           layer_attr=layer_attr))


def prelu(input, partial_sum=1, param_attr=None, name=None,
          layer_attr=None):
    """PReluLayer. The reference's partial_sum groups elements sharing
    one slope (layers.py:6790): 1 = element-wise, elements-per-channel
    = channel-wise, all elements = one shared slope. Mapped onto the
    fluid prelu modes element/channel/all respectively; other group
    sizes have no fluid equivalent and are rejected."""
    def build(pv):
        import numpy as _np
        dims = [int(d) for d in pv.shape[1:]]
        nelem = int(_np.prod(dims)) if dims else 1
        per_channel = (nelem // dims[0]) if dims else 1
        if partial_sum == 1:
            mode = "element"
        elif partial_sum == nelem:
            mode = "all"
        elif dims and partial_sum == per_channel:
            mode = "channel"
        else:
            raise ValueError(
                "prelu: partial_sum=%d does not match element-wise (1), "
                "channel-wise (%d) or shared (%d) grouping for input "
                "shape %s" % (partial_sum, per_channel, nelem,
                              tuple(pv.shape)))
        return F.prelu(pv, mode=mode,
                       param_attr=lower_param_attr(param_attr))

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="prelu",
                           layer_attr=layer_attr))


def gated_unit(input, size, act=None, name=None, gate_attr=None,
               gate_param_attr=None, gate_bias_attr=None,
               inproj_attr=None, inproj_param_attr=None,
               inproj_bias_attr=None, layer_attr=None):
    """GatedRecurrentUnit-style gating: fc(x) * sigmoid(fc_gate(x))
    (gated_unit_layer)."""
    def build(pv):
        proj = F.fc(pv, size=size,
                    param_attr=lower_param_attr(inproj_param_attr),
                    bias_attr=lower_param_attr(inproj_bias_attr)
                    if inproj_bias_attr is not None else None)
        proj = _apply_act(proj, act)
        gate = F.fc(pv, size=size, act="sigmoid",
                    param_attr=lower_param_attr(gate_param_attr),
                    bias_attr=lower_param_attr(gate_bias_attr)
                    if gate_bias_attr is not None else None)
        return F.elementwise_mul(proj, gate)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="gated_unit",
                           layer_attr=layer_attr))


def scale_shift(input, name=None, param_attr=None, bias_attr=None):
    """w * x + b with scalar w, b (ScaleShiftLayer)."""
    def build(pv):
        w = F.create_parameter(shape=[1], dtype="float32",
                               attr=lower_param_attr(param_attr))
        out = F.elementwise_mul(pv, w)
        if bias_attr is not False:
            b = F.create_parameter(shape=[1], dtype="float32",
                                   attr=lower_param_attr(bias_attr),
                                   is_bias=True)
            out = F.elementwise_add(out, b)
        return out

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="scale_shift"))


def resize(input, size, name=None, layer_attr=None):
    """ResizeLayer: reinterpret rows as [-1, size]."""
    def build(pv):
        return F.reshape(pv, shape=[-1, size])

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="resize",
                           layer_attr=layer_attr))


def row_conv(input, context_len, act=None, name=None, param_attr=None,
             layer_attr=None):
    """RowConvLayer -> row_conv op (lookahead convolution)."""
    def build(pv):
        return _apply_act(
            F.row_conv(pv, future_context_size=context_len,
                       param_attr=lower_param_attr(param_attr)), act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="row_conv",
                           layer_attr=layer_attr))


def sub_seq(input, offsets, sizes, act=None, bias_attr=None, name=None):
    """SubSequenceLayer: per-sequence [offset, offset+size) slice."""
    def build(pv, ov, sv):
        return _apply_act(F.sequence_slice(
            pv, offset=F.cast(F.reshape(ov, shape=[-1, 1]), "int64"),
            length=F.cast(F.reshape(sv, shape=[-1, 1]), "int64")), act)

    return _remember(Layer(name=name, parents=[input, offsets, sizes],
                           build_fn=build, layer_type="sub_seq"))


# ---------------------------------------------------------------------------
# recurrent_group — the v1/v2 custom-RNN construct
# ---------------------------------------------------------------------------

class StaticInput(object):
    """Unrolled (per-sequence constant) input to recurrent_group
    (trainer_config_helpers layers.py StaticInput)."""

    def __init__(self, input, is_seq=False, size=None):
        if is_seq:
            raise NotImplementedError(
                "sequence-typed StaticInput: the padded-dense encoding "
                "keeps batch order fixed; pass the sequence as a normal "
                "input instead")
        self.input = input
        self.size = size


class _Memory(Layer):
    """Marker node for `memory(name=...)` inside a step function; resolved
    by recurrent_group into a DynamicRNN state slot."""

    def __init__(self, link_name, size, boot_layer=None,
                 boot_with_const_id=None, is_seq=False):
        self.link_name = link_name
        self.size = size
        self.boot_layer = boot_layer
        # build_fn is never used directly — recurrent_group seeds the
        # context with this node's state var before the step DAG builds
        super(_Memory, self).__init__(
            name="@mem@" + link_name, parents=[],
            build_fn=lambda: (_ for _ in ()).throw(RuntimeError(
                "memory() used outside recurrent_group")),
            layer_type="memory")


def memory(name, size, boot_layer=None, is_seq=False, **kwargs):
    """Previous-timestep output of the step layer called `name`
    (trainer_config_helpers memory()); initial value is zeros or
    `boot_layer`'s (batch-sized) output."""
    if is_seq:
        raise NotImplementedError(
            "sequence-level memory (is_seq=True) is not supported — the "
            "padded-dense scan carries fixed-rank state")
    # boot_bias=False/None means "no boot bias" — exactly the zero-boot
    # we implement, so accept it. Everything else changes semantics when
    # present at all (boot_with_const_id=0 is a real word id), so only
    # None counts as "not passed".
    if kwargs.pop("boot_bias", None) not in (None, False):
        raise NotImplementedError("memory(): boot_bias is not supported")
    if "boot_with_const_id" in kwargs \
            and kwargs["boot_with_const_id"] is not None:
        raise NotImplementedError(
            "memory(): boot_with_const_id is not supported")
    kwargs.pop("boot_with_const_id", None)
    unsupported = sorted(k for k, v in kwargs.items() if v is not None)
    if unsupported:
        raise NotImplementedError(
            "memory(): unsupported v1 arguments %s" % unsupported)
    return _Memory(name, size, boot_layer=boot_layer)


class _StepSlot(Layer):
    """Per-timestep view of a recurrent_group input inside the step DAG."""

    def __init__(self, kind, source):
        self.kind = kind            # "seq" | "static"
        self.source = source
        super(_StepSlot, self).__init__(
            parents=[], layer_type="step_input",
            build_fn=lambda: (_ for _ in ()).throw(RuntimeError(
                "step input used outside recurrent_group")))


def recurrent_group(step, input, reverse=False, name=None, **kwargs):
    """Run `step` over every timestep of the sequence inputs
    (trainer_config_helpers recurrent_group -> here fluid DynamicRNN ->
    one `recurrent` op lowered to a masked lax.scan).

    `step` executes ONCE, eagerly, at DSL time over placeholder nodes —
    v2 layers are lazy, so this only discovers the step DAG (and its
    `memory` declarations); ops are emitted when a Topology builds."""
    if kwargs:
        raise NotImplementedError(
            "recurrent_group: unsupported v1 arguments %s"
            % sorted(kwargs))
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def _slot_of(i):
        if isinstance(i, StaticInput):
            return _StepSlot("static", i.input)
        if isinstance(i, SubsequenceInput):
            return _StepSlot("subseq", i.input)
        return _StepSlot("seq", i)

    slots = [_slot_of(i) for i in inputs]
    kinds = set(s.kind for s in slots)
    if "subseq" in kinds and "seq" in kinds:
        # the reference rejected mixed nesting levels among group inputs
        # (all sequence inputs must share the outer iteration structure)
        raise NotImplementedError(
            "recurrent_group: SubsequenceInput cannot be mixed with "
            "single-level sequence inputs — the group iterates the OUTER "
            "level; wrap every sequence input as SubsequenceInput or use "
            "StaticInput for per-group constants")
    if reverse and "subseq" in kinds:
        raise NotImplementedError("reverse=True with SubsequenceInput")
    _capture_stack.append([])
    try:
        outs = step(*slots)
    finally:
        created = _capture_stack.pop()
    out_layers = list(outs) if isinstance(outs, (list, tuple)) else [outs]

    # discover memory leaves + every node reachable from the outputs
    memories, seen, order = [], set(), []

    def scan(l):
        if id(l) in seen:
            return
        seen.add(id(l))
        for p in l.parents():
            scan(p)
        if isinstance(l, _Memory):
            memories.append(l)
        order.append(l)

    for o in out_layers:
        scan(o)

    # resolve memory links NOW, against the step DAG itself — the global
    # name registry is mutable and a later layer may reuse the name.
    # Side layers created in the step but unreachable from its outputs
    # (get_output state taps) resolve too.
    by_name = {}
    for l in order + created:
        by_name.setdefault(l.name, l)
    links = {}
    for m in memories:
        link = by_name.get(m.link_name)
        if link is None:
            raise ValueError(
                "memory(name=%r) does not link to any layer produced "
                "inside this step function" % m.link_name)
        links[id(m)] = link
        # a SIDE link (unreachable from the outputs, e.g. a get_output
        # state tap) joins the step DAG traversal so its own memories
        # and outer references get the same treatment as output paths
        if id(link) not in seen:
            scan(link)

    # nodes NOT downstream of a slot/memory are OUTER references the user
    # pulled into the step (v1's implicit read-only link): build them in
    # the enclosing block and close over their values, never re-emit
    # their ops (a data layer re-emitted inside the scan is unfeedable)
    _mark_memo = {}

    def mark_internal(l):
        if id(l) in _mark_memo:
            return _mark_memo[id(l)]
        if isinstance(l, (_StepSlot, _Memory)):
            _mark_memo[id(l)] = True
            return True
        # evaluate EVERY parent (no any() short-circuit) so all internal
        # nodes get marked; memoize both verdicts or diamond-shaped
        # outer DAGs re-traverse exponentially
        _mark_memo[id(l)] = False   # cycle guard; overwritten below
        verdict = any([mark_internal(p) for p in l.parents()])
        _mark_memo[id(l)] = verdict
        return verdict

    for o in out_layers:
        mark_internal(o)
    for m in memories:
        mark_internal(links[id(m)])
    internal = {k for k, v in _mark_memo.items() if v}
    outer_refs, _outer_seen = [], set()
    for c in order:
        if id(c) not in internal:
            continue
        for p in c.parents():
            if id(p) not in internal and id(p) not in _outer_seen:
                _outer_seen.add(id(p))
                outer_refs.append(p)

    parents = [s.source for s in slots]
    boot_parents = [m.boot_layer for m in memories
                    if m.boot_layer is not None] + outer_refs

    def build(ctx, *parent_vars):
        from ..fluid.layer_helper import LayerHelper

        def _to_outer(v):
            helper = LayerHelper("nested_to_outer")
            out = helper.create_variable_for_type_inference(v.dtype)
            lmat = helper.create_variable_for_type_inference("int32")
            out.lod_level = 1
            lmat.lod_level = 1
            helper.append_op(type="nested_to_outer", inputs={"X": v},
                             outputs={"Out": out, "OutLens": lmat},
                             infer_shape=False)
            # ragged build-shape convention is PACKED rank-2 (runtime
            # arrays are padded rank-3/4) — keep it so downstream shape
            # inference sees the usual [rows, D] view
            out.shape = tuple(v.shape)
            lmat.shape = (-1, 1)
            return out, lmat

        subseq_lmats = {}
        seq_vars = []
        for s, v in zip(slots, parent_vars):
            if s.kind == "seq":
                seq_vars.append(v)
            elif s.kind == "subseq":
                if reverse:
                    raise NotImplementedError(
                        "reverse=True with SubsequenceInput")
                ov, lmat = _to_outer(v)
                subseq_lmats[id(s)] = lmat
                seq_vars.append(ov)
        if reverse:
            seq_vars = [F.sequence_reverse(v) for v in seq_vars]
        if not seq_vars:
            raise ValueError("recurrent_group needs >=1 sequence input")
        # batch-sized zero inits derive from a per-sequence view of the
        # first sequence input (parent block, before the step block
        # opens); computed lazily — boot_layer-only groups skip it
        head = None
        inits = []
        for m in memories:
            if m.boot_layer is not None:
                inits.append(ctx[id(m.boot_layer)])
            else:
                if head is None:
                    head = F.sequence_first_step(seq_vars[0])
                inits.append(F.fill_constant_batch_size_like(
                    input=head, shape=[-1, m.size], dtype="float32",
                    value=0.0))

        drnn = F.DynamicRNN()
        with drnn.block():
            step_ctx = dict()
            # outer references close over their parent-block values
            for l in outer_refs:
                step_ctx[id(l)] = ctx[id(l)]
            si = iter(seq_vars)
            for s, v in zip(slots, parent_vars):
                if s.kind == "seq":
                    step_ctx[id(s)] = drnn.step_input(next(si))
                elif s.kind == "subseq":
                    xs = drnn.step_input(next(si))  # [B_outer, T, D]
                    ls = drnn.step_input(
                        subseq_lmats[id(s)])        # [B_outer]
                    helper = LayerHelper("attach_lod")
                    ragged = helper.create_variable_for_type_inference(
                        xs.dtype)
                    ragged.lod_level = 1
                    helper.append_op(type="attach_lod",
                                     inputs={"X": xs, "Lens": ls},
                                     outputs={"Out": ragged},
                                     infer_shape=False)
                    ragged.shape = tuple(xs.shape)   # packed [rows, D]
                    step_ctx[id(s)] = ragged
                else:
                    step_ctx[id(s)] = drnn.static_input(v)
            mem_vars = {}
            for m, init in zip(memories, inits):
                mem_vars[id(m)] = drnn.memory(init=init)
                step_ctx[id(m)] = mem_vars[id(m)]
            out_vars = [o.build(step_ctx) for o in out_layers]
            for m in memories:
                link = links[id(m)]
                if id(link) not in step_ctx:
                    link.build(step_ctx)     # side layer (state tap)
                drnn.update_memory(mem_vars[id(m)],
                                   step_ctx[id(link)])
            for ov in out_vars:
                drnn.output(ov)
        result = drnn()
        result_list = result if isinstance(result, list) else [result]
        if reverse:
            result_list = [F.sequence_reverse(v) for v in result_list]
        return result_list[0] if len(result_list) == 1 else result_list

    group = _remember(Layer(name=name, parents=parents,
                            extra_parents=boot_parents, build_fn=build,
                            build_with_ctx=True, layer_type="recurrent"))
    if len(out_layers) == 1:
        return group
    return [_remember(Layer(parents=[group],
                            build_fn=lambda lst, _i=i: lst[_i],
                            layer_type="recurrent_out"))
            for i in range(len(out_layers))]


def parse_network(output_layers, extra_layers=None):
    """Build the fluid Program realizing `output_layers` (reference
    v2/layer.py:263 parse_network returns the trimmed ModelConfig; here the
    Program pair IS the config)."""
    from .topology import Topology
    if not isinstance(output_layers, (list, tuple)):
        output_layers = [output_layers]
    return Topology(output_layers, extra_layers=extra_layers).proto()


# ---------------------------------------------------------------------------
# round-4b gserver tail: the rest of the reference v1 __all__ surface
# (reference trainer_config_helpers/layers.py; legacy/gserver/layers/)
# ---------------------------------------------------------------------------

def _append_raw_op(op_type, inputs, attrs=None, dtype="float32",
                   lod_out=False, n_outs=1, infer_shape=True):
    """Emit one registry op from a v2 builder (for ops with no public
    fluid layer — the v1-only gserver semantics)."""
    from ..fluid.layer_helper import LayerHelper
    helper = LayerHelper(op_type)
    outs = [helper.create_variable_for_type_inference(dtype)
            for _ in range(n_outs)]
    if lod_out:
        for o in outs:
            o.lod_level = 1
    out_slots = {"Out": outs[0]} if n_outs == 1 else \
        {"Out%d" % i: o for i, o in enumerate(outs)}
    helper.append_op(type=op_type, inputs=inputs, outputs=out_slots,
                     attrs=attrs or {}, infer_shape=infer_shape)
    return outs[0] if n_outs == 1 else outs


def row_l2_norm(input, name=None, layer_attr=None):
    """RowL2NormLayer: x / ||x||_2 per row."""
    def build(pv):
        return F.l2_normalize(pv, axis=-1)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="row_l2_norm",
                           layer_attr=layer_attr))


def tensor(a, b, size, act=None, name=None, param_attr=None,
           bias_attr=None, layer_attr=None):
    """TensorLayer: out_k = a W_k b^T (bilinear tensor product,
    reference tensor_layer). W stored [da, size*db] so the contraction
    is one MXU matmul + a broadcast multiply."""
    def build(av, bv):
        da, db = int(av.shape[-1]), int(bv.shape[-1])
        w = F.create_parameter(shape=[da, size * db], dtype="float32",
                               attr=lower_param_attr(param_attr))
        proj = F.matmul(av, w)                       # [B, size*db]
        proj = F.reshape(proj, shape=[-1, size, db])
        out = F.reduce_sum(
            F.elementwise_mul(proj, F.reshape(bv, shape=[-1, 1, db])),
            dim=-1)                                  # [B, size]
        if bias_attr is not False:
            out = _add_bias(out, bias_attr, size)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="tensor", layer_attr=layer_attr))


def conv_shift(a, b, name=None, layer_attr=None):
    """ConvShiftLayer: circular correlation
    c[i] = sum_j a[i+j-(N-1)/2] b[j], N odd (reference conv_shift_layer).
    N is static (b's width), so the shifts unroll into N adds."""
    def build(av, bv):
        n = int(bv.shape[-1])
        m = int(av.shape[-1])
        half = (n - 1) // 2
        total = None
        for j in range(n):
            shift = j - half
            # circular shift of a by `shift` via two static slices
            k = shift % m
            if k == 0:
                rolled = av
            else:
                left = F.slice(av, axes=[1], starts=[k], ends=[m])
                right = F.slice(av, axes=[1], starts=[0], ends=[k])
                rolled = F.concat([left, right], axis=1)
            bj = F.slice(bv, axes=[1], starts=[j], ends=[j + 1])
            term = F.elementwise_mul(rolled, bj)
            total = term if total is None else \
                F.elementwise_add(total, term)
        return total

    return _remember(Layer(name=name, parents=[a, b], build_fn=build,
                           layer_type="conv_shift", layer_attr=layer_attr))


def switch_order(input, reshape_axis=None, act=None, name=None,
                 layer_attr=None):
    """SwitchOrderLayer: NCHW -> NHWC (reference switch_order_layer)."""
    def build(pv):
        return _apply_act(F.transpose(pv, perm=[0, 2, 3, 1]), act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="switch_order",
                           layer_attr=layer_attr))


def upsample(input, scale=None, scale_y=None, upsample_size=None,
             upsample_size_y=None, pad_out_x=False, pad_out_y=False,
             name=None, layer_attr=None):
    """UpsampleLayer as nearest-neighbor resize by integer scale. The
    reference's unpool-with-mask form pairs with max_pool_with_mask
    (legacy UpsampleLayer.cpp); the resize semantics cover the common
    segmentation-decoder use — use fluid.layers.unpool for mask-exact
    unpooling."""
    def build(pv):
        sy = scale_y or scale
        h, w = int(pv.shape[2]), int(pv.shape[3])
        if upsample_size:
            out_hw = [upsample_size_y or upsample_size, upsample_size]
        else:
            out_hw = [h * sy, w * scale]
        return F.resize_nearest(pv, out_shape=out_hw)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="upsample",
                           layer_attr=layer_attr))


def spp(input, pyramid_height=None, num_channels=None, pool_type=None,
        name=None, layer_attr=None):
    """SpatialPyramidPoolLayer: concat max/avg pools at pyramid levels
    1x1 .. 2^(h-1) bins (reference spp_layer)."""
    ptype = pool_type or _pooling.Max()
    if isinstance(ptype, type):
        ptype = ptype()

    def build(pv):
        h, w = int(pv.shape[2]), int(pv.shape[3])
        c = int(pv.shape[1])
        reduce = F.reduce_max if ptype.img_pool_type == "max" \
            else F.reduce_mean
        outs = []
        for lvl in range(pyramid_height):
            bins = 2 ** lvl
            # exact bin boundaries (floor start, ceil end) — works for
            # any h/w, matching the reference's adaptive binning
            cells = []
            for bi in range(bins):
                h0, h1 = bi * h // bins, -(-(bi + 1) * h // bins)
                for bj in range(bins):
                    w0, w1 = bj * w // bins, -(-(bj + 1) * w // bins)
                    cell = F.slice(pv, axes=[2, 3], starts=[h0, w0],
                                   ends=[h1, w1])
                    cells.append(reduce(cell, dim=[2, 3]))  # [B, C]
            lvl_out = F.stack(cells, axis=2)                # [B, C, bins^2]
            outs.append(F.reshape(lvl_out, shape=[-1, c * bins * bins]))
        return F.concat(outs, axis=1)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="spp",
                           layer_attr=layer_attr))


def kmax_seq_score(input, beam_size=1, name=None):
    """KmaxSeqScoreLayer: indices of the beam_size highest scores within
    each sequence's valid prefix (ops/sequence_ops.py kmax_seq_score —
    padded positions never outrank real ones)."""
    def build(pv):
        attrs = {"beam_size": int(beam_size)}
        if getattr(pv, "lod_level", 0) >= 2:
            # nested ranking has a data-dependent group count — run on
            # the host path (the reference layer is CPU-only too)
            attrs["force_host"] = True
        out = _append_raw_op("kmax_seq_score", {"X": pv}, attrs,
                             dtype="int64", infer_shape=False)
        out.shape = (-1, int(beam_size))
        return out

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="kmax_seq_score"))


def scale_sub_region(input, indices, value, name=None):
    """ScaleSubRegionLayer: scale the per-sample [c1,c2,h1,h2,w1,w2]
    box (1-based inclusive) by `value` (ops/vision_ops.py
    scale_sub_region)."""
    def build(pv, iv):
        return _append_raw_op(
            "scale_sub_region", {"X": pv, "Indices": iv},
            {"value": float(value)}, dtype=pv.dtype)

    return _remember(Layer(name=name, parents=[input, indices],
                           build_fn=build, layer_type="scale_sub_region"))


def factorization_machine(input, factor_size, act=None, name=None,
                          param_attr=None, layer_attr=None):
    """FactorizationMachineLayer: second-order FM interactions
    0.5 * sum((xV)^2 - (x^2)(V^2)) (Rendle 2010; reference
    factorization_machine)."""
    def build(pv):
        d = int(pv.shape[-1])
        v = F.create_parameter(shape=[d, factor_size], dtype="float32",
                               attr=lower_param_attr(param_attr))
        xv2 = F.square(F.matmul(pv, v))
        x2v2 = F.matmul(F.square(pv), F.square(v))
        out = F.scale(F.reduce_sum(
            F.elementwise_sub(xv2, x2v2), dim=-1, keep_dim=True),
            scale=0.5)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="factorization_machine",
                           layer_attr=layer_attr))


def selective_fc(input, size, select=None, act=None, name=None,
                 pass_generation=False, has_selected_colums=True,
                 mul_ratio=0.02, param_attr=None, bias_attr=None,
                 layer_attr=None):
    """SelectiveFullyConnectedLayer: fc whose output is restricted to the
    columns marked in `select`. The reference skips the un-selected
    columns' FLOPs on CPU; on the MXU the full matmul is the fast path,
    so this computes fc then masks — identical semantics."""
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(*vs):
        pvs, sv = vs[:-1], vs[-1]
        outs = [F.fc(pv, size=size, param_attr=lower_param_attr(param_attr),
                     bias_attr=False) for pv in pvs]
        out = outs[0]
        for o in outs[1:]:
            out = F.elementwise_add(out, o)
        if bias_attr is not False:
            out = _add_bias(out, bias_attr, size)
        out = _apply_act(out, act)
        return F.elementwise_mul(out, F.cast(sv, "float32"))

    parents = list(inputs) + [select]
    if select is None:
        raise ValueError("selective_fc requires a select input (a 0/1 "
                         "mask layer over the output columns)")
    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="selective_fc",
                           layer_attr=layer_attr))


def printer(input, format=None, name=None):
    """PrintLayer -> Print op (passthrough)."""
    inputs = input if isinstance(input, (list, tuple)) else [input]

    def build(*vs):
        outs = [F.Print(v, message=format or "") for v in vs]
        return outs[0] if len(outs) == 1 else outs

    return _remember(Layer(name=name, parents=list(inputs),
                           build_fn=build, layer_type="printer"))


def priorbox(input, image, aspect_ratio, variance, min_size, max_size=None,
             name=None):
    """PriorBoxLayer -> fluid prior_box; returns the [prior, 8] layout the
    v1 detection stack consumed (4 box + 4 variance columns)."""
    def build(pv, iv):
        box, var = F.prior_box(
            pv, iv, min_sizes=list(min_size),
            max_sizes=list(max_size) if max_size else None,
            aspect_ratios=list(aspect_ratio), variance=list(variance),
            flip=True)
        b = F.reshape(box, shape=[-1, 4])
        v = F.reshape(var, shape=[-1, 4])
        return F.concat([b, v], axis=1)

    return _remember(Layer(name=name, parents=[input, image],
                           build_fn=build, layer_type="priorbox"))


def multibox_loss(input_loc, input_conf, priorbox, label, num_classes,
                  overlap_threshold=0.5, neg_pos_ratio=3.0,
                  neg_overlap=0.5, background_id=0, name=None):
    """MultiBoxLossLayer -> fluid ssd_loss over the mbox head tensors."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) \
        else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) \
        else [input_conf]

    def build(*vs):
        n_loc = len(locs)
        loc_vs = list(vs[:n_loc])
        conf_vs = list(vs[n_loc:n_loc + len(confs)])
        pb_v, lbl_v = vs[-2], vs[-1]
        loc = loc_vs[0] if len(loc_vs) == 1 else F.concat(loc_vs, axis=1)
        conf = conf_vs[0] if len(conf_vs) == 1 \
            else F.concat(conf_vs, axis=1)
        # v1 packed [prior, 8] -> fluid (boxes [P,4], variances [P,4])
        pb = F.slice(pb_v, axes=[1], starts=[0], ends=[4])
        pbv = F.slice(pb_v, axes=[1], starts=[4], ends=[8])
        gt_box = F.slice(lbl_v, axes=[1], starts=[1], ends=[5])
        gt_lbl = F.cast(F.slice(lbl_v, axes=[1], starts=[0], ends=[1]),
                        "int64")
        loc = F.reshape(loc, shape=[0, -1, 4])
        conf = F.reshape(conf, shape=[0, -1, num_classes])
        loss = F.ssd_loss(loc, conf, gt_box, gt_lbl, pb, pbv,
                          overlap_threshold=overlap_threshold,
                          neg_pos_ratio=neg_pos_ratio,
                          neg_overlap=neg_overlap,
                          background_label=background_id)
        return F.mean(loss)

    return _remember(Layer(name=name,
                           parents=locs + confs + [priorbox, label],
                           build_fn=build, layer_type="multibox_loss"))


def detection_output(input_loc, input_conf, priorbox, num_classes,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     confidence_threshold=0.01, background_id=0,
                     name=None):
    """DetectionOutputLayer -> fluid detection_output (decode + NMS)."""
    locs = input_loc if isinstance(input_loc, (list, tuple)) \
        else [input_loc]
    confs = input_conf if isinstance(input_conf, (list, tuple)) \
        else [input_conf]

    def build(*vs):
        n_loc = len(locs)
        loc_vs = list(vs[:n_loc])
        conf_vs = list(vs[n_loc:n_loc + len(confs)])
        pb_v = vs[-1]
        loc = loc_vs[0] if len(loc_vs) == 1 else F.concat(loc_vs, axis=1)
        conf = conf_vs[0] if len(conf_vs) == 1 \
            else F.concat(conf_vs, axis=1)
        pb = F.slice(pb_v, axes=[1], starts=[0], ends=[4])
        pbv = F.slice(pb_v, axes=[1], starts=[4], ends=[8])
        loc = F.reshape(loc, shape=[0, -1, 4])
        # conf stays logits: F.detection_output softmaxes internally
        # (fluid/layers/detection.py)
        conf = F.reshape(conf, shape=[0, -1, num_classes])
        return F.detection_output(
            loc, conf, pb, pbv, nms_threshold=nms_threshold,
            nms_top_k=nms_top_k, keep_top_k=keep_top_k,
            score_threshold=confidence_threshold,
            background_label=background_id)

    return _remember(Layer(name=name, parents=locs + confs + [priorbox],
                           build_fn=build, layer_type="detection_output"))


def roi_pool(input, rois, pooled_width, pooled_height, spatial_scale,
             num_channels=None, name=None):
    """ROIPoolLayer -> fluid roi_pool."""
    def build(pv, rv):
        return F.roi_pool(pv, rv, pooled_height=pooled_height,
                          pooled_width=pooled_width,
                          spatial_scale=spatial_scale)

    return _remember(Layer(name=name, parents=[input, rois],
                           build_fn=build, layer_type="roi_pool"))


def huber_classification_cost(input, label, name=None, coeff=1.0,
                              layer_attr=None):
    """Modified Huber loss for binary classification over a real score f
    and label y in {0,1} -> y' in {-1,1}: max(0, 1-y'f)^2 for y'f >= -1,
    else -4 y'f (reference huber_classification_cost)."""
    def build(pv, lv):
        yp = F.scale(F.cast(lv, "float32"), scale=2.0, bias=-1.0)
        a = F.elementwise_mul(pv, yp)
        hinge_sq = F.square(F.relu(F.scale(a, scale=-1.0, bias=1.0)))
        linear = F.scale(a, scale=-4.0)
        big = F.cast(F.less_than(a, F.fill_constant_batch_size_like(
            a, shape=[-1, 1], dtype="float32", value=-1.0)), "float32")
        per = F.elementwise_add(
            F.elementwise_mul(linear, big),
            F.elementwise_mul(hinge_sq, F.scale(big, scale=-1.0,
                                                bias=1.0)))
        out = F.mean(per)
        return F.scale(out, scale=coeff) if coeff != 1.0 else out

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost",
                           layer_attr=layer_attr))


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1,
                                layer_attr=None):
    """Self-normalizing CE (reference cross_entropy_with_selfnorm): the
    input is UNNORMALIZED positive scores; cost = CE(softmax(x), y) +
    alpha * log(Z)^2 pushes the normalizer Z toward 1 so inference can
    skip the softmax."""
    def build(pv, lv):
        z = F.reduce_sum(pv, dim=-1, keep_dim=True)
        prob = F.elementwise_div(pv, z)
        ce = F.cross_entropy(prob, lv)
        selfnorm = F.scale(F.square(F.log(z)),
                           scale=softmax_selfnorm_alpha)
        out = F.mean(F.elementwise_add(ce, selfnorm))
        return F.scale(out, scale=coeff) if coeff != 1.0 else out

    return _remember(Layer(name=name, parents=[input, label],
                           build_fn=build, layer_type="cost",
                           layer_attr=layer_attr))


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    """LambdaRank LTR cost (ops/loss_ops.py lambda_rank; reference
    lambda_cost — input: per-item model scores over a sequence, score:
    relevance labels)."""
    def build(pv, sv):
        raw = _append_raw_op(
            "lambda_rank",
            {"Score": F.reshape(pv, shape=[0, -1]) if
             len(pv.shape) > 2 else pv,
             "Label": F.reshape(sv, shape=[0, -1]) if
             len(sv.shape) > 2 else sv},
            {"NDCG_num": int(NDCG_num)}, infer_shape=False)
        raw.shape = (-1, 1)
        return F.mean(raw)

    return _remember(Layer(name=name, parents=[input, score],
                           build_fn=build, layer_type="cost",
                           layer_attr=layer_attr))


def recurrent(input, act=None, bias_attr=None, param_attr=None,
              reverse=False, name=None, layer_attr=None):
    """Elman recurrent_layer over a pre-projected sequence: h_t =
    act(x_t + h_{t-1} W) (ops/sequence_ops.py simple_rnn)."""
    def build(pv):
        h = int(pv.shape[-1])
        w = F.create_parameter(shape=[h, h], dtype="float32",
                               attr=lower_param_attr(param_attr))
        ins = {"Input": pv, "Weight": w}
        if bias_attr is not False:
            from ..fluid.layer_helper import LayerHelper
            helper = LayerHelper("simple_rnn",
                                 bias_attr=lower_param_attr(bias_attr))
            b = helper.create_parameter(attr=helper.bias_attr,
                                        shape=[1, h], dtype="float32",
                                        is_bias=True)
            ins["Bias"] = b
        if act is None:
            fluid_act = "tanh"        # the v1 recurrent_layer default
        else:
            a = act() if isinstance(act, type) else act
            # fluid_act None == linear (v2/activation.py) -> identity
            fluid_act = getattr(a, "fluid_act", None) or "identity"
        out = _append_raw_op(
            "simple_rnn", ins,
            {"activation": fluid_act,
             "is_reverse": bool(reverse)},
            lod_out=True, infer_shape=False)
        out.shape = tuple(pv.shape)
        out.lod_level = max(getattr(pv, "lod_level", 0), 1)
        return out

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="recurrent_plain",
                           layer_attr=layer_attr))


def lstm_step(input, state, size=None, act=None, gate_act=None,
              state_act=None, bias_attr=None, name=None, layer_attr=None):
    """LstmStepLayer for recurrent_group: the pure cell arithmetic over a
    pre-projected [B, 4H] input and the cell-state memory. The hidden
    output is returned; get_output(layer, 'state') reads the new cell."""
    layer = Layer(name=name, parents=[input, state], build_fn=None,
                  build_with_ctx=True, layer_type="lstm_step",
                  layer_attr=layer_attr)

    def build(ctx, iv, sv):
        from ..fluid.layer_helper import LayerHelper
        helper = LayerHelper("lstm_step")
        h = helper.create_variable_for_type_inference(iv.dtype)
        c = helper.create_variable_for_type_inference(iv.dtype)
        helper.append_op(type="lstm_unit",
                         inputs={"X": iv, "C_prev": sv},
                         outputs={"H": h, "C": c},
                         attrs={"forget_bias": 0.0}, infer_shape=False)
        h.shape = tuple(sv.shape)
        c.shape = tuple(sv.shape)
        ctx[(id(layer), "state")] = c
        return h

    layer.__build_fn__ = build
    return _remember(layer)


def gru_step(input, output_mem, size=None, act=None, gate_act=None,
             bias_attr=None, param_attr=None, name=None, layer_attr=None):
    """GruStepLayer for recurrent_group: one GRU update over a
    pre-projected [B, 3H] input and the previous output memory."""
    def _resolve(a, default):
        if a is None:
            return default
        a = a() if isinstance(a, type) else a
        # fluid_act None == linear (v2/activation.py) -> identity
        return getattr(a, "fluid_act", None) or "identity"

    def build(iv, mv):
        sz = size or int(mv.shape[-1]) * 3
        out, _, _ = F.gru_unit(
            iv, mv, sz, param_attr=lower_param_attr(param_attr),
            bias_attr=lower_param_attr(bias_attr),
            activation=_resolve(act, "tanh"),
            gate_activation=_resolve(gate_act, "sigmoid"))
        if out.shape is None:
            out.shape = tuple(mv.shape)
        return out

    return _remember(Layer(name=name, parents=[input, output_mem],
                           build_fn=build, layer_type="gru_step",
                           layer_attr=layer_attr))


gru_step_naive = gru_step


def get_output(input, arg_name, name=None, layer_attr=None):
    """GetOutputLayer: read a named secondary output of a layer (e.g.
    the 'state' cell of lstm_step)."""
    src = _single_input(input)

    def build(ctx, _pv):
        key = (id(src), arg_name)
        if key not in ctx:
            raise ValueError(
                "layer %s has no secondary output %r" % (src.name,
                                                         arg_name))
        return ctx[key]

    return _remember(Layer(name=name, parents=[src], build_fn=build,
                           build_with_ctx=True, layer_type="get_output",
                           layer_attr=layer_attr))


# ---------------------------------------------------------------------------
# v1 generation machinery: GeneratedInput + beam_search (reference
# trainer_config_helpers/layers.py:4282-4600), cross_entropy_over_beam,
# and the 3D conv/pool tail
# ---------------------------------------------------------------------------

class BaseGeneratedInput(object):
    """reference layers.py:4282."""

    def __init__(self):
        self.bos_id = None
        self.eos_id = None


class GeneratedInput(BaseGeneratedInput):
    """The generated-word slot of a beam_search step: each timestep feeds
    the embedding (shared table `embedding_name`) of the previously
    selected word (reference layers.py:4294)."""

    def __init__(self, size, embedding_name, embedding_size):
        super(GeneratedInput, self).__init__()
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class SubsequenceInput(object):
    """Nested-sequence input to recurrent_group (reference
    layers.py:4257): the group iterates the OUTER level — step s sees
    the s-th inner sequence of each outer group as a level-1 ragged
    var. Lowered via the nested_to_outer re-batching op (host path; the
    reference's nested machinery was CPU-side too) + an in-block
    attach_lod that restores the inner lengths per step."""

    def __init__(self, input):
        self.input = input


def _var_layer(var, name=None):
    """Wrap an already-built fluid var as a v2 Layer node (for handing
    per-timestep vars to user step functions)."""
    return Layer(name=name, parents=[], build_fn=lambda: var,
                 layer_type="var")


def _beam_expand(var, beam_size):
    """[B, ...] -> [B*W, ...] with each row repeated W times (rows
    grouped per source, row i -> rows i*W .. i*W+W-1); handles any rank
    (a [B, T, D] attention-encoder sequence expands per row too)."""
    rest = [int(d) for d in var.shape[1:]]
    x = F.unsqueeze(var, axes=[1])                     # [B, 1, ...]
    x = F.expand(x, expand_times=[1, beam_size] + [1] * len(rest))
    out = F.reshape(x, shape=[-1] + rest)
    out.shape = tuple([-1] + rest)
    return out


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=500,
                name=None, num_results_per_sample=None):
    """v1 sequence generation (reference layers.py:4485): drive `step`
    (a v1 layer function using memory() for decoder state) with the
    embedding of the previously generated word, expanding a dense
    beam_size-wide frontier for max_length unrolled steps, then
    backtrack with beam_search_decode. Each timestep rebuilds the step
    DAG under a fixed-prefix name guard so parameters are shared across
    timesteps (the v1 recurrent machinery's weight sharing); memories
    are gathered by beam parent pointers between steps.

    Returns the generated id sequences; get_output(layer, 'scores')
    reads the per-hypothesis log-probabilities."""
    from ..fluid import unique_name as fluid_unique_name

    if num_results_per_sample is not None and \
            int(num_results_per_sample) != int(beam_size):
        raise NotImplementedError(
            "num_results_per_sample=%r: the decode emits all beam_size "
            "hypotheses per source, ranked best-first — slice the first "
            "k sequences of each source's group from the LoD result "
            "(per-source truncation inside the graph needs LoD-aware "
            "sub-sequence selection)" % (num_results_per_sample,))
    inputs = input if isinstance(input, (list, tuple)) else [input]
    gen = [i for i in inputs if isinstance(i, BaseGeneratedInput)]
    if len(gen) != 1:
        raise ValueError("beam_search needs exactly one GeneratedInput")
    gen = gen[0]
    statics = [i for i in inputs if not isinstance(i, BaseGeneratedInput)]
    static_layers = [s.input if isinstance(s, StaticInput) else s
                     for s in statics]
    W = int(beam_size)

    out = Layer(name=name, parents=list(static_layers), build_fn=None,
                build_with_ctx=True, layer_type="beam_search")

    def build(ctx, *static_vars):
        beam_statics = [_beam_expand(v, W) for v in static_vars]
        anchor = beam_statics[0] if beam_statics else None
        if anchor is None:
            raise ValueError(
                "beam_search needs at least one static input to size "
                "the batch (the encoder context)")
        pre_ids = F.fill_constant_batch_size_like(
            anchor, shape=[-1, 1], dtype="int64", value=bos_id)
        pre_scores = F.fill_constant_batch_size_like(
            anchor, shape=[-1, 1], dtype="float32", value=0.0)

        mem_vals = {}            # link_name -> current beam-rows var
        step_ids, step_scores, step_parents = [], [], []
        for t in range(max_length):
            word_emb = F.embedding(
                pre_ids, size=[gen.size, gen.embedding_size],
                param_attr=_fluid_param_attr(gen.embedding_name))
            word_emb = F.reshape(word_emb,
                                 shape=[-1, gen.embedding_size])
            word_emb.shape = (-1, gen.embedding_size)
            with fluid_unique_name.guard("@beamgen@"):
                step_ctx = dict(ctx)
                # bind step args in the declared input order: the
                # GeneratedInput slot gets this step's word embedding
                # (v1 substitutes it in place, layers.py:4570)
                args = []
                static_it = iter(beam_statics)
                for i in inputs:
                    if isinstance(i, BaseGeneratedInput):
                        args.append(_var_layer(word_emb))
                    else:
                        args.append(_var_layer(next(static_it)))
                # capture every layer the step creates: memories may
                # link to SIDE layers unreachable from the step's output
                # (get_output state taps, e.g. an LSTM decoder's cell) —
                # the same treatment recurrent_group gives its links
                _capture_stack.append([])
                try:
                    out_layer = step(*args)
                finally:
                    created = _capture_stack.pop()
                if isinstance(out_layer, (list, tuple)):
                    out_layer = out_layer[0]
                # collect the step DAG; seed memory markers with current
                # values (zeros at t=0 unless boot_layer, beam-expanded)
                all_nodes = {}

                def _collect(node):
                    if id(node) in all_nodes:
                        return
                    all_nodes[id(node)] = node
                    for p in node.parents():
                        _collect(p)

                _collect(out_layer)
                mems = [n for n in all_nodes.values()
                        if isinstance(n, _Memory)]
                for n in created:
                    if isinstance(n, _Memory) and id(n) not in all_nodes:
                        all_nodes[id(n)] = n
                        mems.append(n)
                # link resolution across the step DAG AND side layers
                link_by_name = {}
                for n in list(all_nodes.values()) + created:
                    if not isinstance(n, _Memory):
                        link_by_name.setdefault(n.name, n)
                side_links = []
                for m in mems:
                    link = link_by_name.get(m.link_name)
                    if link is not None and id(link) not in all_nodes:
                        _collect(link)
                        side_links.append(link)
                for node in mems:
                    if node.link_name not in mem_vals:
                        if node.boot_layer is not None:
                            boot = node.boot_layer.build(step_ctx)
                            # a boot derived from the step's own args
                            # (the _var_layer wrappers) is already
                            # beam-row-aligned; only outer layers need
                            # the per-source -> per-beam expansion
                            boot_nodes = {}

                            def _bc(n):
                                if id(n) in boot_nodes:
                                    return
                                boot_nodes[id(n)] = n
                                for p in n.parents():
                                    _bc(p)

                            _bc(node.boot_layer)
                            from_args = any(
                                n.layer_type == "var"
                                for n in boot_nodes.values())
                            mem_vals[node.link_name] = boot if from_args \
                                else _beam_expand(boot, W)
                        else:
                            mem_vals[node.link_name] = \
                                F.fill_constant_batch_size_like(
                                    anchor, shape=[-1, node.size],
                                    dtype="float32", value=0.0)
                    step_ctx[id(node)] = mem_vals[node.link_name]
                probs_var = out_layer.build(step_ctx)
                # side links build AFTER the output: the shared prefix
                # is cached in step_ctx, only the tap itself is emitted
                for link in side_links:
                    link.build(step_ctx)
                # the new memory values are the step layers named by the
                # memory links
                for m in mems:
                    link = link_by_name.get(m.link_name)
                    if link is not None and id(link) in step_ctx:
                        mem_vals[m.link_name] = step_ctx[id(link)]

            log_probs = F.log(probs_var)
            accu = F.elementwise_add(log_probs, pre_scores, axis=0)
            if t == 0:
                accu = F.elementwise_add(
                    accu, F.beam_slot_mask(anchor, W), axis=0)
            sel_ids, sel_scores, parent_idx = F.beam_search(
                pre_ids, pre_scores, None, accu, beam_size=W,
                end_id=eos_id, return_parent_idx=True)
            step_ids.append(sel_ids)
            step_scores.append(sel_scores)
            step_parents.append(parent_idx)
            pre_ids, pre_scores = sel_ids, sel_scores
            for k in list(mem_vals):
                shape = mem_vals[k].shape
                mem_vals[k] = F.gather(mem_vals[k], parent_idx)
                if mem_vals[k].shape is None:
                    mem_vals[k].shape = shape

        ids_arr = F.stack([F.reshape(i, shape=[-1]) for i in step_ids],
                          axis=0)
        scores_arr = F.stack([F.reshape(s, shape=[-1])
                              for s in step_scores], axis=0)
        parents_arr = F.stack(step_parents, axis=0)
        sent_ids, sent_scores = F.beam_search_decode(
            ids_arr, scores_arr, beam_size=W, end_id=eos_id,
            parent_idx=parents_arr)
        ctx[(id(out), "scores")] = sent_scores
        return sent_ids

    out.__build_fn__ = build
    return _remember(out)


def _fluid_param_attr(name):
    from ..fluid.param_attr import ParamAttr as FluidParamAttr
    return FluidParamAttr(name=name)


class BeamInput(object):
    """One beam for cross_entropy_over_beam: candidate scores [B, C],
    selected candidate ids [B, C], gold id [B, 1] (reference
    layers.py:6441)."""

    def __init__(self, candidate_scores, selected_candidates, gold):
        self.candidate_scores = candidate_scores
        self.selected_candidates = selected_candidates
        self.gold = gold


def cross_entropy_over_beam(input, name=None):
    """Beam-aware CE (reference layers.py:6478 / CrossEntropyOverBeam):
    for each beam, -log P(gold | candidates) under a softmax over the
    candidate scores; a gold that fell off the beam contributes the
    floor probability (-log eps) rather than an error."""
    beams = input if isinstance(input, (list, tuple)) else [input]
    parents = []
    for b in beams:
        parents += [b.candidate_scores, b.selected_candidates, b.gold]

    def build(*vs):
        total = None
        for i in range(0, len(vs), 3):
            scores, cand, gold = vs[i], vs[i + 1], vs[i + 2]
            p = F.softmax(scores)
            hit = F.cast(F.equal(F.cast(cand, "int64"),
                                 F.cast(gold, "int64")), "float32")
            p_gold = F.reduce_sum(F.elementwise_mul(p, hit), dim=-1,
                                  keep_dim=True)
            loss = F.scale(F.log(F.scale(p_gold, bias=1e-10)),
                           scale=-1.0)
            total = loss if total is None else \
                F.elementwise_add(total, loss)
        return F.mean(total)

    return _remember(Layer(name=name, parents=parents, build_fn=build,
                           layer_type="cost"))


def img_conv3d(input, filter_size, num_filters, num_channels=None,
               stride=1, padding=0, act=None, groups=1, dilation=1,
               param_attr=None, bias_attr=None, name=None,
               layer_attr=None, trans=False):
    """Img3DConvLayer -> fluid conv3d (NCDHW)."""
    def build(pv):
        out = F.conv3d(pv, num_filters=num_filters,
                       filter_size=filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       param_attr=lower_param_attr(param_attr),
                       bias_attr=lower_param_attr(bias_attr)
                       if bias_attr is not None else None)
        return _apply_act(out, act)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="conv3d",
                           layer_attr=layer_attr))


def img_pool3d(input, pool_size, num_channels=None, pool_type=None,
               stride=1, padding=0, name=None, ceil_mode=True,
               layer_attr=None):
    """Img3DPoolLayer -> fluid pool3d (NCDHW)."""
    ptype = pool_type or _pooling.Max()
    if isinstance(ptype, type):
        ptype = ptype()

    def build(pv):
        return F.pool3d(pv, pool_size=pool_size,
                        pool_type=ptype.img_pool_type,
                        pool_stride=stride, pool_padding=padding,
                        ceil_mode=ceil_mode)

    return _remember(Layer(name=name, parents=[_single_input(input)],
                           build_fn=build, layer_type="pool3d",
                           layer_attr=layer_attr))


def sub_nested_seq(input, selected_indices, name=None):
    """SubNestedSequenceLayer (reference sub_nested_seq_layer): select
    per-outer-group inner sequences of a nested (lod_level-2) input by
    the LOCAL indices produced by kmax_seq_score
    (ops/sequence_ops.py sub_nested_seq; host-path op, like the
    reference's CPU-only layer)."""
    def build(pv, iv):
        out = _append_raw_op("sub_nested_seq",
                             {"X": pv, "Indices": iv},
                             dtype=pv.dtype, lod_out=True,
                             infer_shape=False)
        out.shape = tuple(pv.shape)
        out.lod_level = 2
        return out

    return _remember(Layer(name=name, parents=[input, selected_indices],
                           build_fn=build, layer_type="sub_nested_seq"))
