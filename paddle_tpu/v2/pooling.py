"""v2 pooling type objects (reference python/paddle/v2/pooling.py →
trainer_config_helpers.poolings). ``seq_pool_type`` drives fluid
sequence_pool; ``img_pool_type`` drives fluid pool2d."""

__all__ = ["BasePool", "Max", "Avg", "Sum", "SquareRootN", "CudnnMax",
           "CudnnAvg"]


class BasePool(object):
    seq_pool_type = None
    img_pool_type = None

    def __repr__(self):
        return self.__class__.__name__ + "()"


class Max(BasePool):
    seq_pool_type = "max"
    img_pool_type = "max"


class Avg(BasePool):
    seq_pool_type = "average"
    img_pool_type = "avg"


# cudnn variants are aliases on TPU — one XLA pooling lowering serves both
CudnnMax = Max
CudnnAvg = Avg


class Sum(BasePool):
    seq_pool_type = "sum"
    img_pool_type = "avg"


class SquareRootN(BasePool):
    seq_pool_type = "sqrt"
    img_pool_type = "avg"
