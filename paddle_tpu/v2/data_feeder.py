"""v2 DataFeeder (reference python/paddle/v2/data_feeder.py): converts
reader minibatches into feed form given the topology's data types and an
optional ``feeding`` name->column mapping. Thin adapter over the fluid
DataFeeder (the dense/LoD conversion lives there)."""

from ..fluid.data_feeder import DataFeeder as _FluidFeeder

__all__ = ["DataFeeder"]


class DataFeeder(object):
    def __init__(self, data_types, feeding=None):
        self.data_types = list(data_types)
        names = [n for n, _ in self.data_types]
        if feeding is not None:
            if isinstance(feeding, dict):
                names = [kv[0] for kv in
                         sorted(feeding.items(), key=lambda kv: kv[1])]
            else:
                names = list(feeding)
        self.feed_order = names

    def __call__(self, data_batch, program=None):
        feeder = _FluidFeeder(feed_list=self.feed_order, program=program)
        return feeder.feed(data_batch)
