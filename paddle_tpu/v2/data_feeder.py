"""v2 DataFeeder (reference python/paddle/v2/data_feeder.py): converts
reader minibatches into feed dicts given the topology's data types and an
optional ``feeding`` name->column mapping. Standalone: conversion is
driven purely by the declared InputTypes (the reference's
DataProviderConverter), no Program needed."""

import numpy as np

from . import data_type as _dt
from ..fluid.lod import LoDTensor

__all__ = ["DataFeeder", "resolve_feed_order"]


def resolve_feed_order(names, feeding):
    """Shared feeding-spec resolution (trainer/inference/feeder all accept
    the same ``feeding``): None keeps the topology's data order; a dict
    maps name -> sample column index; a list gives the order directly."""
    if feeding is None:
        return list(names)
    if isinstance(feeding, dict):
        return [kv[0] for kv in sorted(feeding.items(),
                                       key=lambda kv: kv[1])]
    return list(feeding)


class DataFeeder(object):
    def __init__(self, data_types, feeding=None):
        self.data_types = list(data_types)
        self._type_of = dict(self.data_types)
        self.feed_order = resolve_feed_order(
            [n for n, _ in self.data_types], feeding)

    def __call__(self, data_batch):
        return self.feed(data_batch)

    def feed(self, data_batch):
        """data_batch: list of sample tuples in feed_order column order.
        Returns {name: ndarray | LoDTensor} in the fluid executor's feed
        format."""
        columns = list(zip(*data_batch))
        if len(columns) < len(self.feed_order):
            raise ValueError(
                "each sample must have %d slots (feed order %s), got %d"
                % (len(self.feed_order), self.feed_order, len(columns)))
        out = {}
        for name, col in zip(self.feed_order, columns):
            tp = self._type_of.get(name)
            if tp is None:
                raise KeyError("no data type declared for feed %r" % name)
            out[name] = self._convert(tp, col)
        return out

    @staticmethod
    def _convert(tp, col):
        is_seq = tp.seq_type != _dt.SequenceType.NO_SEQUENCE
        is_nested = tp.seq_type == _dt.SequenceType.SUB_SEQUENCE
        if is_nested:
            # sample = list of inner sequences -> 2-level LoD
            from ..fluid.lod import nested_samples_to_lod_tensor
            dtype = np.int64 if tp.type == _dt.DataType.Index \
                else np.float32
            return nested_samples_to_lod_tensor(col, dtype)
        if tp.type == _dt.DataType.Index:
            if is_seq:
                lens = [len(s) for s in col]
                flat = np.concatenate(
                    [np.asarray(s, dtype=np.int64).reshape(-1, 1)
                     for s in col])
                t = LoDTensor(flat)
                t.set_recursive_sequence_lengths([lens])
                return t
            return np.asarray(col, dtype=np.int64).reshape(-1, 1)
        # dense (sparse vectors densify — the TPU-native encoding)
        if tp.type in (_dt.DataType.SparseNonValue, _dt.DataType.SparseValue):
            col = [DataFeeder._densify(s, tp) for s in col]
        if is_seq:
            lens = [len(s) for s in col]
            flat = np.concatenate(
                [np.asarray(s, dtype=np.float32).reshape(len(s), -1)
                 for s in col])
            t = LoDTensor(flat)
            t.set_recursive_sequence_lengths([lens])
            return t
        return np.asarray(col, dtype=np.float32)

    @staticmethod
    def _densify(sample, tp):
        dense = np.zeros(tp.dim, dtype=np.float32)
        if tp.type == _dt.DataType.SparseNonValue:
            dense[np.asarray(sample, dtype=np.int64)] = 1.0
        else:
            for idx, val in sample:
                dense[int(idx)] = float(val)
        return dense
