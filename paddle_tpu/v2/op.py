"""v2 layer arithmetic (reference python/paddle/v2/op.py): operator
overloading + unary math on Layer nodes — exp/log/abs/sigmoid/tanh/
square/relu/sqrt plus +, -, unary neg, and scalar *."""

from .config_base import Layer
from . import layer as v2_layer

__all__ = ["exp", "log", "abs", "sigmoid", "tanh", "square", "relu",
           "sqrt"]


def _unary(op_name):
    def impl(one):
        def build(pv):
            from ..fluid.layer_helper import LayerHelper
            helper = LayerHelper(op_name)
            out = helper.create_variable_for_type_inference(pv.dtype)
            helper.append_op(type=op_name, inputs={"X": pv},
                             outputs={"Out": out})
            return out

        return Layer(parents=[one], build_fn=build, layer_type=op_name)

    impl.__name__ = op_name
    return impl


exp = _unary("exp")
log = _unary("log")
abs = _unary("abs")
sigmoid = _unary("sigmoid")
tanh = _unary("tanh")
square = _unary("square")
relu = _unary("relu")
sqrt = _unary("sqrt")


def _add(self, other):
    if isinstance(other, Layer):
        return v2_layer.addto([self, other])
    return _slope(self, 1.0, float(other))


def _neg(self):
    return _slope(self, -1.0, 0.0)


def _sub(self, other):
    if isinstance(other, Layer):
        return v2_layer.addto([self, _neg(other)])
    return _slope(self, 1.0, -float(other))


def _rsub(self, other):
    return _slope(_sub(self, other), -1.0, 0.0)


def _mul(self, other):
    if isinstance(other, Layer):
        raise TypeError("layer * layer is not defined; use "
                        "fluid elementwise_mul via a custom layer")
    return _slope(self, float(other), 0.0)


def _slope(one, slope, intercept):
    return v2_layer.slope_intercept(one, slope=slope, intercept=intercept)


Layer.__add__ = _add
Layer.__radd__ = _add
Layer.__neg__ = _neg
Layer.__sub__ = _sub
Layer.__rsub__ = _rsub
Layer.__mul__ = _mul
Layer.__rmul__ = _mul
