"""v2 Parameters: numpy-facing parameter pool shared by trainer/inference.

Reference: python/paddle/v2/parameters.py — Parameters wraps per-parameter
numpy views synced into C++ GradientMachines (parameters.py:272
append_gradient_machine). Here the pool syncs with fluid Scopes instead:
trainer/inference push the pool into a scope before running and pull it
back after, so one Parameters object can hop between topologies exactly
like the reference's (create:27, to_tar:328, from_tar:358).
"""

import struct
import tarfile
import io as _io

import numpy as np

from ..fluid import executor as _executor
from .topology import Topology

__all__ = ["Parameters", "create"]


def create(layers):
    """Create Parameters for the topology rooted at `layers` (reference
    parameters.py:27). Runs the startup program once to materialize
    initialized values."""
    topo = layers if isinstance(layers, Topology) else Topology(layers)
    params = Parameters()
    params.init_from_topology(topo)
    return params


class Parameters(object):
    def __init__(self):
        self.__param_dict__ = {}    # name -> np.ndarray
        self.__shapes__ = {}

    # -- construction ------------------------------------------------------
    def init_from_topology(self, topology):
        scope = _executor.Scope()
        exe = _executor.Executor()
        with _executor.scope_guard(scope):
            exe.run(topology.startup_program)
        for block in topology.main_program.blocks:
            for var in block.vars.values():
                if getattr(var, "persistable", False):
                    val = scope.get(var.name)
                    if val is not None:
                        self.__param_dict__[var.name] = np.asarray(val)
                        self.__shapes__[var.name] = tuple(
                            np.asarray(val).shape)
        return self

    # -- mapping interface (reference parameters.py:108-:260) --------------
    def keys(self):
        return list(self.__param_dict__.keys())

    def names(self):
        return self.keys()

    def has_key(self, key):
        return key in self.__param_dict__

    def __contains__(self, key):
        return self.has_key(key)

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self.__param_dict__)

    def __getitem__(self, key):
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)

    def get(self, parameter_name):
        if parameter_name not in self.__param_dict__:
            raise KeyError("no parameter %s" % parameter_name)
        return self.__param_dict__[parameter_name]

    def get_shape(self, key):
        if key in self.__shapes__:
            return self.__shapes__[key]
        return tuple(self.get(key).shape)

    def set(self, parameter_name, value):
        value = np.asarray(value)
        if parameter_name in self.__shapes__:
            want = self.__shapes__[parameter_name]
            if tuple(value.shape) != tuple(want):
                raise ValueError(
                    "shape mismatch for %s: expect %s got %s"
                    % (parameter_name, want, value.shape))
        self.__param_dict__[parameter_name] = value
        self.__shapes__[parameter_name] = tuple(value.shape)

    # -- scope sync (the TPU-native analogue of append_gradient_machine) --
    def push_to_scope(self, scope):
        for name, val in self.__param_dict__.items():
            scope.set(name, val)

    def pull_from_scope(self, scope, names=None):
        for name in (names if names is not None else self.keys()):
            val = scope.get(name)
            if val is not None:
                self.__param_dict__[name] = np.asarray(val)

    # -- serialization (reference parameters.py:296-:400) ------------------
    def serialize(self, name, f):
        """Single-parameter binary: u32 version, u32 elem size, u64 rank,
        rank*u64 dims, raw fp32 data — self-describing like the reference's
        Parameter header."""
        arr = np.asarray(self.get(name), dtype=np.float32)
        f.write(struct.pack("<IIQ", 0, 4, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack("<Q", d))
        f.write(arr.tobytes())

    def deserialize(self, name, f):
        _, _, rank = struct.unpack("<IIQ", f.read(16))
        shape = tuple(struct.unpack("<Q", f.read(8))[0]
                      for _ in range(rank))
        count = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(f.read(4 * count),
                            dtype=np.float32).reshape(shape)
        self.set(name, arr.copy())

    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode="w") as tar:
            for name in self.keys():
                buf = _io.BytesIO()
                self.serialize(name, buf)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, _io.BytesIO(data))

    @staticmethod
    def from_tar(f):
        params = Parameters()
        with tarfile.open(fileobj=f, mode="r") as tar:
            for member in tar.getmembers():
                buf = tar.extractfile(member)
                params.__param_dict__[member.name] = None
                params.deserialize(member.name, buf)
        return params

    def init_from_tar(self, f, exclude_params=None):
        """Overwrite matching parameters from a tar (reference :386)."""
        exclude = set(exclude_params or [])
        other = Parameters.from_tar(f)
        for name in other.keys():
            if name in exclude:
                continue
            if name in self.__param_dict__:
                self.set(name, other.get(name))
