"""v2 Parameters: numpy-facing parameter pool shared by trainer/inference.

Reference: python/paddle/v2/parameters.py — Parameters wraps per-parameter
numpy views synced into C++ GradientMachines (parameters.py:272
append_gradient_machine). Here the pool syncs with fluid Scopes instead:
trainer/inference push the pool into a scope before running and pull it
back after, so one Parameters object can hop between topologies exactly
like the reference's (create:27, to_tar:328, from_tar:358).
"""

import struct
import tarfile
import io as _io

import numpy as np

from ..fluid import executor as _executor
from .topology import Topology

__all__ = ["Parameters", "create"]


# -- minimal ParameterConfig protobuf wire codec ---------------------------
# proto/ParameterConfig.proto:34 — required string name = 1, required
# uint64 size = 2, repeated uint64 dims = 9. Hand-encoded (protobuf wire
# format: varints + length-delimited fields) because the image has no
# generated bindings for the reference protos; unknown fields written by
# the reference (learning_rate, momentum, ...) are skipped on read.

def _varint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(data, pos):
    result = shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated ParameterConfig varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _encode_param_config(name, arr):
    raw = name.encode("utf-8")
    out = b"\x0a" + _varint(len(raw)) + raw          # field 1: name
    out += b"\x10" + _varint(int(arr.size))          # field 2: size
    for d in arr.shape:
        out += b"\x48" + _varint(int(d))             # field 9: dims
    return out


def _decode_param_config(data):
    name, size, dims, pos = None, 0, [], 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(data, pos)
            if field == 2:
                size = val
            elif field == 9:
                dims.append(val)
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            if pos + ln > len(data):
                raise ValueError("truncated ParameterConfig field")
            if field == 1:
                name = data[pos:pos + ln].decode("utf-8")
            pos += ln
        elif wire == 1:
            pos += 8
        elif wire == 5:
            pos += 4
        else:
            raise ValueError("bad ParameterConfig wire type %d" % wire)
    if name is None:
        raise ValueError("ParameterConfig missing required name field")
    return name, size, tuple(dims)


def create(layers):
    """Create Parameters for the topology rooted at `layers` (reference
    parameters.py:27). Runs the startup program once to materialize
    initialized values."""
    topo = layers if isinstance(layers, Topology) else Topology(layers)
    params = Parameters()
    params.init_from_topology(topo)
    return params


class Parameters(object):
    def __init__(self):
        self.__param_dict__ = {}    # name -> np.ndarray
        self.__shapes__ = {}

    # -- construction ------------------------------------------------------
    def init_from_topology(self, topology):
        scope = _executor.Scope()
        exe = _executor.Executor()
        with _executor.scope_guard(scope):
            exe.run(topology.startup_program)
        for block in topology.main_program.blocks:
            for var in block.vars.values():
                if getattr(var, "persistable", False):
                    val = scope.get(var.name)
                    if val is not None:
                        self.__param_dict__[var.name] = np.asarray(val)
                        self.__shapes__[var.name] = tuple(
                            np.asarray(val).shape)
        return self

    # -- mapping interface (reference parameters.py:108-:260) --------------
    def keys(self):
        return list(self.__param_dict__.keys())

    def names(self):
        return self.keys()

    def has_key(self, key):
        return key in self.__param_dict__

    def __contains__(self, key):
        return self.has_key(key)

    def __iter__(self):
        return iter(self.keys())

    def __len__(self):
        return len(self.__param_dict__)

    def __getitem__(self, key):
        return self.get(key)

    def __setitem__(self, key, value):
        self.set(key, value)

    def get(self, parameter_name):
        if parameter_name not in self.__param_dict__:
            raise KeyError("no parameter %s" % parameter_name)
        return self.__param_dict__[parameter_name]

    def get_shape(self, key):
        if key in self.__shapes__:
            return self.__shapes__[key]
        return tuple(self.get(key).shape)

    def set(self, parameter_name, value):
        value = np.asarray(value)
        if parameter_name in self.__shapes__:
            want = self.__shapes__[parameter_name]
            if tuple(value.shape) != tuple(want):
                raise ValueError(
                    "shape mismatch for %s: expect %s got %s"
                    % (parameter_name, want, value.shape))
        self.__param_dict__[parameter_name] = value
        self.__shapes__[parameter_name] = tuple(value.shape)

    # -- scope sync (the TPU-native analogue of append_gradient_machine) --
    def push_to_scope(self, scope):
        for name, val in self.__param_dict__.items():
            scope.set(name, val)

    def pull_from_scope(self, scope, names=None):
        for name in (names if names is not None else self.keys()):
            val = scope.get(name)
            if val is not None:
                self.__param_dict__[name] = np.asarray(val)

    # -- serialization (reference parameters.py:296-:400) ------------------
    # The on-disk format IS the reference's: each tar holds a raw-payload
    # member per parameter (header u32 version=0, u32 elem_size=4, u64
    # NUM_ELEMENTS, then raw fp32 — parameters.py:306) plus a
    # '<name>.protobuf' member carrying a ParameterConfig message
    # (proto/ParameterConfig.proto:34) whose `dims` field recovers the
    # shape at load time. The config codec below hand-writes the protobuf
    # wire format for the fields this framework uses (name=1, size=2,
    # dims=9) and skips unknown fields, so reference-produced model tars
    # load here and tars written here load in the reference.

    def serialize(self, name, f):
        arr = np.asarray(self.get(name), dtype=np.float32)
        f.write(struct.pack("<IIQ", 0, 4, int(arr.size)))
        f.write(arr.tobytes())

    def deserialize(self, name, f):
        version, elem_size, count = struct.unpack("<IIQ", f.read(16))
        if version != 0 or elem_size != 4:
            raise ValueError(
                "parameter %r: unsupported header (version=%d elem_size=%d)"
                " — not a v2 model tar produced by this framework or the "
                "reference" % (name, version, elem_size))
        arr = np.frombuffer(f.read(4 * count), dtype=np.float32)
        if arr.size != count:
            raise ValueError("parameter %r: truncated payload" % name)
        self.set(name, arr.reshape(self.get_shape(name)).copy())

    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode="w") as tar:
            def add(name, data):
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, _io.BytesIO(data))
            for name in self.keys():
                buf = _io.BytesIO()
                self.serialize(name, buf)
                add(name, buf.getvalue())
                add("%s.protobuf" % name, _encode_param_config(
                    name, np.asarray(self.get(name))))

    @staticmethod
    def from_tar(f):
        params = Parameters()
        with tarfile.open(fileobj=f, mode="r") as tar:
            # pass 1: ParameterConfig members give names + shapes
            for member in tar.getmembers():
                if member.name.endswith(".protobuf"):
                    name, size, dims = _decode_param_config(
                        tar.extractfile(member).read())
                    if not dims:
                        # configs without dims: a true scalar when size
                        # is 1 (our 0-d round-trip), else a flat vector
                        dims = () if int(size) == 1 else (int(size),)
                    params.__param_dict__[name] = None
                    params.__shapes__[name] = tuple(int(d) for d in dims)
            if not params.__shapes__:
                raise ValueError(
                    "model tar has no ParameterConfig ('.protobuf') "
                    "members — not a v2 model tar (reference "
                    "parameters.py to_tar writes one per parameter)")
            # pass 2: extract each configured payload BY NAME (reference
            # from_tar:381 — unrelated tar members are ignored, and a
            # config without its payload is an error here, not a silent
            # None entry)
            for name in list(params.__param_dict__):
                try:
                    payload = tar.extractfile(name)
                except KeyError:
                    payload = None
                if payload is None:
                    raise ValueError(
                        "model tar is missing the payload member for "
                        "parameter %r (has only its .protobuf config)"
                        % name)
                params.deserialize(name, payload)
        return params

    def init_from_tar(self, f, exclude_params=None):
        """Overwrite matching parameters from a tar (reference :386)."""
        exclude = set(exclude_params or [])
        other = Parameters.from_tar(f)
        for name in other.keys():
            if name in exclude:
                continue
            if name in self.__param_dict__:
                self.set(name, other.get(name))
