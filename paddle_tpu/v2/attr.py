"""v2 attribute objects (reference python/paddle/v2/attr.py →
trainer_config_helpers.attrs.ParameterAttribute/ExtraLayerAttribute).
``Param`` maps onto fluid ``ParamAttr``; ``Extra`` keeps the same knob
names (drop_rate etc.) and is honored where meaningful."""

from ..fluid.param_attr import ParamAttr
from ..fluid import initializer as _init
from ..fluid import regularizer as _reg

__all__ = ["Param", "Extra", "Hook", "HookAttribute",
           "ParameterAttribute", "ExtraLayerAttribute",
           "ExtraAttr", "ParamAttr"]


class ParameterAttribute(object):
    """v2-style parameter attribute; ``to_fluid(name)`` lowers it."""

    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=1.0,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, initializer=None,
                 update_hooks=None):
        self.name = name
        self.update_hooks = update_hooks
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.gradient_clipping_threshold = gradient_clipping_threshold
        self.sparse_update = sparse_update
        self.initializer = initializer

    def to_fluid(self, name=None):
        init = self.initializer
        if init is None:
            if self.initial_max is not None or self.initial_min is not None:
                lo = self.initial_min if self.initial_min is not None else 0.0
                hi = self.initial_max if self.initial_max is not None else 1.0
                init = _init.Uniform(low=lo, high=hi)
            elif self.initial_std is not None or self.initial_mean is not None:
                init = _init.Normal(
                    loc=self.initial_mean or 0.0,
                    scale=self.initial_std
                    if self.initial_std is not None else 1.0)
        reg = None
        if self.l2_rate:
            reg = _reg.L2Decay(self.l2_rate)
        elif self.l1_rate:
            reg = _reg.L1Decay(self.l1_rate)
        return ParamAttr(
            name=self.name or name,
            initializer=init,
            regularizer=reg,
            learning_rate=self.learning_rate,
            trainable=not self.is_static)


class ExtraLayerAttribute(object):
    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


Param = ParameterAttribute
Extra = ExtraLayerAttribute
ExtraAttr = ExtraLayerAttribute


def lower_param_attr(attr, default_name=None):
    """Accept None | ParameterAttribute | fluid ParamAttr | False."""
    if attr is None or attr is False:
        return attr
    if isinstance(attr, ParameterAttribute):
        return attr.to_fluid(default_name)
    return attr


class HookAttribute(object):
    """Parameter update hook (reference trainer_config_helpers/attrs.py:59
    HookAttribute; v2 re-exports it as Hook). Accepted via
    ParameterAttribute(update_hooks=...) for config parity — the
    'pruning' schedule itself (zeroing the smallest-magnitude
    sparsity_ratio fraction during training, the reference's
    ParameterPruningHook) is not executed by this engine."""

    def __init__(self, type, sparsity_ratio=None):
        self.type = type
        self.sparsity_ratio = sparsity_ratio
        if self.sparsity_ratio is not None:
            assert 0 <= self.sparsity_ratio <= 1, \
                "sparsity_ratio must be in [0, 1]"


Hook = HookAttribute
