"""v2 image utilities (reference python/paddle/v2/image.py): numpy-side
preprocessing used by the v2 image models. cv2-free: PIL-style ops are
implemented directly on numpy arrays."""

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "to_chw",
    "center_crop", "random_crop",
    "left_right_flip", "simple_transform", "load_and_transform",
    "batch_images_from_tar",
]


def load_image(file_path, is_color=True):
    """Decode an image file to an HWC uint8 array. Supports the formats the
    stdlib can decode (PPM/PGM via manual parse); for arbitrary JPEG/PNG the
    caller should hand in arrays directly (zero-egress image: no cv2)."""
    with open(file_path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _parse_pnm(data):
    parts = data.split(None, 4)
    magic, w, h, maxval = parts[0], int(parts[1]), int(parts[2]), \
        int(parts[3])
    raw = parts[4]
    ch = 3 if magic == b"P6" else 1
    arr = np.frombuffer(raw, dtype=np.uint8, count=w * h * ch)
    return arr.reshape(h, w, ch) if ch == 3 else arr.reshape(h, w)


def _resize_bilinear(im, out_h, out_w):
    h, w = im.shape[:2]
    ys = (np.arange(out_h) + 0.5) * h / out_h - 0.5
    xs = (np.arange(out_w) + 0.5) * w / out_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None]
    wx = np.clip(xs - x0, 0, 1)[None, :]
    if im.ndim == 2:
        im = im[:, :, None]
    top = im[y0][:, x0] * (1 - wx)[..., None] + \
        im[y0][:, x1] * wx[..., None]
    bot = im[y1][:, x0] * (1 - wx)[..., None] + \
        im[y1][:, x1] * wx[..., None]
    out = top * (1 - wy)[..., None] + bot * wy[..., None]
    return out.squeeze().astype(im.dtype)


def resize_short(im, size):
    """Resize so the shorter edge equals `size` (reference image.py
    resize_short)."""
    h, w = im.shape[:2]
    if h > w:
        return _resize_bilinear(im, int(h * size / w), size)
    return _resize_bilinear(im, size, int(w * size / h))


def to_chw(im, order=(2, 0, 1)):
    if im.ndim == 2:
        im = im[:, :, None]
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True, rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = rng.randint(0, h - size + 1)
    w_start = rng.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None, rng=None):
    """resize_short -> crop(+flip when training) -> CHW -> mean-subtract
    (reference image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if (rng or np.random).randint(2) == 1:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, dtype=np.float32)
        im -= mean.reshape(-1, 1, 1) if mean.ndim == 1 else mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    raise NotImplementedError(
        "tar batching requires the dataset cache layout; use the "
        "paddle_tpu.dataset readers instead")


def load_image_bytes(bytes, is_color=True):  # noqa: A002 (reference name)
    """Decode an image from an in-memory bytes buffer (reference
    v2/image.py:111 load_image_bytes) — same format support as
    load_image (PPM/PGM via the stdlib-only parser)."""
    if bytes[:2] in (b"P5", b"P6"):
        return _parse_pnm(bytes)
    raise ValueError("unsupported image format; pass numpy arrays instead")
