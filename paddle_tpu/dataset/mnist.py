"""MNIST reader creators (reference python/paddle/dataset/mnist.py).

Samples: (image float32[784] scaled to [-1, 1], label int in [0, 10)).
Synthetic digits are class-conditional gaussian blobs — separable enough
that the convergence tests in tests/book can actually learn."""

import numpy as np

from . import common

__all__ = ["train", "test", "convert"]

IMAGE_DIM = 784
CLASS_NUM = 10
TRAIN_SIZE = 2048
TEST_SIZE = 512


def _make(split, size):
    rng = common.split_rng("mnist", split)
    protos = common.split_rng("mnist", "protos").randn(
        CLASS_NUM, IMAGE_DIM).astype(np.float32)
    labels = rng.randint(0, CLASS_NUM, size)
    imgs = (0.6 * protos[labels] +
            0.4 * rng.randn(size, IMAGE_DIM)).astype(np.float32)
    imgs = np.tanh(imgs)  # into [-1, 1] like the reference normalization
    return imgs, labels


def _creator(split, size):
    def reader():
        imgs, labels = _make(split, size)
        for i in range(size):
            yield imgs[i], int(labels[i])

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)
def convert(path):
    """Write the readers as recordio shards (reference mnist.py:133)."""
    common.convert(path, train(), 1000, "minist_train")
    common.convert(path, test(), 1000, "minist_test")
