"""UCI housing reader creators (reference python/paddle/dataset/
uci_housing.py). Samples: (features float32[13], price float32[1]) from a
fixed linear model + noise, feature-normalized like the reference."""

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

FEATURE_DIM = 13
TRAIN_SIZE = 404
TEST_SIZE = 102


def _make(split, size):
    rng = common.split_rng("uci_housing", split)
    w = common.split_rng("uci_housing", "model").randn(FEATURE_DIM, 1)
    x = rng.randn(size, FEATURE_DIM).astype(np.float32)
    y = (x.dot(w) + 0.1 * rng.randn(size, 1) + 22.5).astype(np.float32)
    return x, y


def _creator(split, size):
    def reader():
        x, y = _make(split, size)
        for i in range(size):
            yield x[i], y[i]

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)
