"""MQ2007 learning-to-rank reader (reference
python/paddle/dataset/mq2007.py): format="pairwise" yields (label,
left_features, right_features); "listwise" yields (relevance_list,
feature_list); "pointwise" yields (score, features). 46-dim LETOR
features per query-document pair."""

import numpy as np

from . import common

__all__ = ["train", "test"]

FEATURE_DIM = 46
TRAIN_QUERIES = 128
TEST_QUERIES = 32
DOCS_PER_QUERY = (5, 20)


def _gen_query(rng):
    n = int(rng.randint(*DOCS_PER_QUERY))
    rel = rng.randint(0, 3, n)              # LETOR relevance in {0,1,2}
    feats = rng.rand(n, FEATURE_DIM).astype(np.float32)
    # relevance-correlated feature block keeps ranking learnable
    feats[:, :5] += rel[:, None] * 0.5
    return rel, feats


def _creator(split, n_queries, format):
    def reader():
        rng = common.split_rng("mq2007", split)
        for _ in range(n_queries):
            rel, feats = _gen_query(rng)
            if format == "pointwise":
                for i in range(len(rel)):
                    yield float(rel[i]), feats[i]
            elif format == "pairwise":
                for i in range(len(rel)):
                    for j in range(len(rel)):
                        if rel[i] > rel[j]:
                            yield np.array([1.0], np.float32), feats[i], \
                                feats[j]
            elif format == "listwise":
                yield (np.asarray(rel, np.float32),
                       np.asarray(feats, np.float32))
            else:
                raise ValueError("format must be pointwise|pairwise|"
                                 "listwise")

    return reader


def train(format="pairwise"):
    return _creator("train", TRAIN_QUERIES, format)


def test(format="pairwise"):
    return _creator("test", TEST_QUERIES, format)
