"""IMDB sentiment reader creators (reference python/paddle/dataset/imdb.py).

Samples: (word-id sequence, label in {0,1}); `word_dict()` returns the
vocab. Synthetic reviews are Markov-ish draws where some word ids are
polarity-biased, so bag-of-words/LSTM models can learn the split."""

import numpy as np

from . import common

__all__ = ["train", "test", "word_dict", "build_dict", "convert"]

VOCAB_SIZE = 5148  # matches the reference's imdb.word_dict() size order
TRAIN_SIZE = 1024
TEST_SIZE = 256
MIN_LEN, MAX_LEN = 8, 120


def word_dict():
    """word -> id; the last two ids are <unk> like the reference."""
    return {"w%d" % i: i for i in range(VOCAB_SIZE)}


def _creator(split, size):
    def reader():
        rng = common.split_rng("imdb", split)
        # polarity-biased word banks
        pos_bank = np.arange(0, VOCAB_SIZE // 3)
        neg_bank = np.arange(VOCAB_SIZE // 3, 2 * VOCAB_SIZE // 3)
        neutral = np.arange(2 * VOCAB_SIZE // 3, VOCAB_SIZE)
        for _ in range(size):
            label = int(rng.randint(0, 2))
            n = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            bank = pos_bank if label == 1 else neg_bank
            biased = rng.choice(bank, n)
            neutral_draw = rng.choice(neutral, n)
            mask = rng.rand(n) < 0.7
            words = np.where(mask, biased, neutral_draw)
            yield [int(w) for w in words], label

    return reader


def train(word_idx=None):
    return _creator("train", TRAIN_SIZE)


def test(word_idx=None):
    return _creator("test", TEST_SIZE)


def build_dict(pattern=None, cutoff=None):
    """Vocabulary builder (reference imdb.py build_dict walked the raw
    corpus; the synthetic corpus's vocab is word_dict itself)."""
    return word_dict()


def convert(path):
    """Write the readers as recordio shards (reference imdb.py)."""
    from . import common
    w = word_dict()
    common.convert(path, train(w), 1000, "imdb_train")
    common.convert(path, test(w), 1000, "imdb_test")
