"""WMT16 en<->de translation reader (reference
python/paddle/dataset/wmt16.py): train/test/validation yield
(src_ids, trg_ids, trg_ids_next) with BPE-sized vocabs; get_dict(lang,
size) returns the word->id map. <s>=0, <e>=1, <unk>=2 like the
reference (:57-:59)."""

import numpy as np

from . import common

__all__ = ["train", "test", "validation", "get_dict",
           "fetch", "convert"]

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220
TRAIN_SIZE = 2048
TEST_SIZE = 256
MIN_LEN, MAX_LEN = 4, 50


def get_dict(lang, dict_size, reverse=False):
    total = TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS
    dict_size = min(dict_size, total)
    d = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(3, dict_size):
        d["%s%d" % (lang, i)] = i
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def _creator(split, size, src_dict_size, trg_dict_size, src_lang):
    src_v = min(src_dict_size,
                TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS)
    trg_v = min(trg_dict_size,
                TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS)

    def reader():
        rng = common.split_rng("wmt16", split)
        for _ in range(size):
            n_src = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            n_trg = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            src = [0] + [int(v) for v in rng.randint(3, src_v, n_src)] + [1]
            trg_body = [int(v) for v in rng.randint(3, trg_v, n_trg)]
            trg = [0] + trg_body
            trg_next = trg_body + [1]
            yield src, trg, trg_next

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("train", TRAIN_SIZE, src_dict_size, trg_dict_size,
                    src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("test", TEST_SIZE, src_dict_size, trg_dict_size,
                    src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _creator("val", TEST_SIZE, src_dict_size, trg_dict_size,
                    src_lang)


def fetch():
    """reference wmt16.py fetch: pre-download the corpus. The synthetic
    corpus is generated in-process, so this is a no-op that exists for
    script parity."""
    return None


def convert(path, src_dict_size=3000, trg_dict_size=3000, src_lang="en"):
    """Write the readers as recordio shards (reference wmt16.py)."""
    common.convert(path, train(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_train")
    common.convert(path, test(src_dict_size, trg_dict_size, src_lang),
                   1000, "wmt16_test")
