"""CoNLL-2005 semantic-role-labeling reader (reference
python/paddle/dataset/conll05.py). Samples are the 9 features the
reference reader_creator yields (:150): word sequence, the five
predicate-context sequences (ctx_n2..ctx_p2, each the context token
repeated per position), predicate sequence, mark sequence (1 inside the
predicate span), and the BIO label sequence. get_dict() returns
(word_dict, verb_dict, label_dict) like the reference (:205)."""

import numpy as np

from . import common

__all__ = ["test", "get_dict", "get_embedding", "convert"]

WORD_DICT_LEN = 44068       # reference Wikipedia-corpus vocab order
VERB_DICT_LEN = 3162
LABEL_DICT_LEN = 67         # BIO tags over the role label set
TEST_SIZE = 256
MIN_LEN, MAX_LEN = 5, 40


def get_dict():
    word_dict = {"w%d" % i: i for i in range(WORD_DICT_LEN)}
    verb_dict = {"v%d" % i: i for i in range(VERB_DICT_LEN)}
    label_dict = {"l%d" % i: i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """reference :218 returns the path of a pretrained embedding file; in
    synthetic mode there is none."""
    return common.download("conll05st/emb", "conll05st", None)


def test():
    def reader():
        rng = common.split_rng("conll05", "test")
        for _ in range(TEST_SIZE):
            n = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            words = rng.randint(0, WORD_DICT_LEN, n)
            pred_pos = int(rng.randint(0, n))
            pred = int(rng.randint(0, VERB_DICT_LEN))

            def ctx(off):
                p = min(max(pred_pos + off, 0), n - 1)
                return [int(words[p])] * n

            mark = [1 if i == pred_pos else 0 for i in range(n)]
            labels = rng.randint(0, LABEL_DICT_LEN, n)
            yield ([int(w) for w in words], ctx(-2), ctx(-1), ctx(0),
                   ctx(1), ctx(2), [pred] * n, mark,
                   [int(l) for l in labels])

    return reader


def convert(path):
    """Write the test reader as recordio shards (reference conll05.py)."""
    common.convert(path, test(), 1000, "conl105_test")
