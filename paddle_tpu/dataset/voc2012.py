"""PASCAL VOC2012 segmentation reader (reference
python/paddle/dataset/voc2012.py): train/test/val yield (image,
label_map) — CHW float32 image + HW int32 per-pixel class map in
[0, 21) (20 classes + background)."""

import numpy as np

from . import common

__all__ = ["train", "test", "val"]

NUM_CLASSES = 21
TRAIN_SIZE = 128
TEST_SIZE = 32
H = W = 128


def _creator(split, size):
    def reader():
        rng = common.split_rng("voc2012", split)
        for _ in range(size):
            img = rng.rand(3, H, W).astype(np.float32)
            # blocky segmentation mask: a few rectangles per image
            seg = np.zeros((H, W), np.int32)
            for _ in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, NUM_CLASSES))
                y0, x0 = rng.randint(0, H // 2), rng.randint(0, W // 2)
                y1 = y0 + int(rng.randint(8, H // 2))
                x1 = x0 + int(rng.randint(8, W // 2))
                seg[y0:y1, x0:x1] = cls
                img[:, y0:y1, x0:x1] += cls / float(NUM_CLASSES)
            yield img, seg

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)


def val():
    return _creator("val", TEST_SIZE)
