"""Shared dataset plumbing (reference python/paddle/dataset/common.py).

`download`/`md5file` exist for API parity; with no network egress they only
resolve already-present files. Synthetic generation is deterministic per
(dataset, split) so train/test don't overlap and runs are reproducible.
"""

import hashlib
import os

import numpy as np

__all__ = ["DATA_HOME", "download", "md5file", "split_rng",
           "split", "cluster_files_reader", "convert",
           "synthetic_mode", "is_synthetic"]

DATA_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

_synthetic = [True]


def synthetic_mode(on=True):
    _synthetic[0] = bool(on)


def is_synthetic():
    return _synthetic[0]


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve a dataset file. Network egress is unavailable: the file must
    already exist under DATA_HOME (or synthetic mode serves generated
    data and nothing is fetched)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        return filename
    if _synthetic[0]:
        return None
    raise RuntimeError(
        "dataset file %s not present and downloads are disabled" % filename)


def split_rng(name, split):
    """Deterministic generator per (dataset, split)."""
    seed = int(hashlib.md5(("%s/%s" % (name, split)).encode())
               .hexdigest()[:8], 16)
    return np.random.RandomState(seed)


def _sharded(reader, line_count, dump):
    """Accumulate reader items into line_count-sized chunks and hand each
    to dump(idx, chunk). Returns the dump results (one per shard)."""
    assert line_count >= 1
    files, buf, idx = [], [], 0
    for item in reader():
        buf.append(item)
        if len(buf) == line_count:
            files.append(dump(idx, buf))
            buf, idx = [], idx + 1
    if buf:
        files.append(dump(idx, buf))
    return files


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    """Shard a reader into pickle files of `line_count` items each
    (reference dataset/common.py:137). Returns the file list."""
    import pickle
    dumper = dumper or pickle.dump

    def dump(idx, buf):
        path = suffix % idx
        with open(path, "wb") as f:
            dumper(buf, f)
        return path

    return _sharded(reader, line_count, dump)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    """Reader over this trainer's shard of `split(...)` files (reference
    dataset/common.py:175): file i belongs to trainer i % trainer_count."""
    import glob
    import pickle
    loader = loader or pickle.load

    def reader():
        paths = sorted(glob.glob(files_pattern))
        for i, path in enumerate(paths):
            if i % trainer_count != trainer_id:
                continue
            with open(path, "rb") as f:
                for item in loader(f):
                    yield item

    return reader


def convert(output_path, reader, line_count, name_prefix):
    """Write a reader out as recordio shards of pickled records
    (reference dataset/common.py:210). Uses the native recordio writer
    when built, the pyrio fallback otherwise. Returns the file list."""
    import os
    import pickle
    from ..native import RecordIOWriter

    def dump(idx, buf):
        path = os.path.join(output_path, "%s-%05d" % (name_prefix, idx))
        w = RecordIOWriter(path)
        for item in buf:
            w.write(pickle.dumps(item))
        w.close()
        return path

    return _sharded(reader, line_count, dump)
