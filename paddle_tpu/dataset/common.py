"""Shared dataset plumbing (reference python/paddle/dataset/common.py).

`download`/`md5file` exist for API parity; with no network egress they only
resolve already-present files. Synthetic generation is deterministic per
(dataset, split) so train/test don't overlap and runs are reproducible.
"""

import hashlib
import os

import numpy as np

__all__ = ["DATA_HOME", "download", "md5file", "split_rng",
           "synthetic_mode", "is_synthetic"]

DATA_HOME = os.path.expanduser(os.environ.get(
    "PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

_synthetic = [True]


def synthetic_mode(on=True):
    _synthetic[0] = bool(on)


def is_synthetic():
    return _synthetic[0]


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve a dataset file. Network egress is unavailable: the file must
    already exist under DATA_HOME (or synthetic mode serves generated
    data and nothing is fetched)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1])
    if os.path.exists(filename):
        return filename
    if _synthetic[0]:
        return None
    raise RuntimeError(
        "dataset file %s not present and downloads are disabled" % filename)


def split_rng(name, split):
    """Deterministic generator per (dataset, split)."""
    seed = int(hashlib.md5(("%s/%s" % (name, split)).encode())
               .hexdigest()[:8], 16)
    return np.random.RandomState(seed)
