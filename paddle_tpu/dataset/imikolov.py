"""PTB (imikolov) language-model reader (reference
python/paddle/dataset/imikolov.py): build_dict() -> vocab; train/test
yield n-gram tuples (NGRAM) or (cur_seq, next_seq) pairs (SEQ)."""

import numpy as np

from . import common

__all__ = ["train", "test", "build_dict", "DataType", "convert"]

VOCAB = 2074         # reference build_dict default min_word_freq=50 order
TRAIN_SIZE = 2048
TEST_SIZE = 256


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    d = {"w%d" % i: i for i in range(VOCAB - 2)}
    d["<unk>"] = VOCAB - 2
    d["<e>"] = VOCAB - 1
    return d


def _creator(split, size, word_idx, n, data_type):
    vocab = max(word_idx.values()) + 1 if word_idx else VOCAB

    def reader():
        rng = common.split_rng("imikolov", split)
        for _ in range(size):
            if data_type == DataType.NGRAM:
                assert n > 1
                yield tuple(int(v) for v in rng.randint(0, vocab, n))
            else:
                ln = int(rng.randint(3, 30))
                seq = rng.randint(0, vocab, ln + 1)
                yield ([int(v) for v in seq[:-1]],
                       [int(v) for v in seq[1:]])

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _creator("train", TRAIN_SIZE, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _creator("test", TEST_SIZE, word_idx, n, data_type)
def convert(path):
    """Write the readers as recordio shards (reference imikolov.py)."""
    from . import common
    N = 5
    word_dict = build_dict()
    common.convert(path, train(word_dict, N), 1000, "imikolov_train")
    common.convert(path, test(word_dict, N), 1000, "imikolov_test")
