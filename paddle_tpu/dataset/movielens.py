"""MovieLens reader creators (reference python/paddle/dataset/movielens.py).

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, score). Synthetic preferences come from a low-rank user x movie
model so recommender tests converge."""

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table", "get_movie_title_dict", "movie_categories",
           "user_info", "movie_info", "convert",
           "MovieInfo", "UserInfo"]

USER_NUM = 944
MOVIE_NUM = 1683
JOB_NUM = 21
CATEGORY_NUM = 18
TITLE_VOCAB = 1000
age_table = [1, 18, 25, 35, 45, 50, 56]
TRAIN_SIZE = 2048
TEST_SIZE = 512


def max_user_id():
    return USER_NUM - 1


def max_movie_id():
    return MOVIE_NUM - 1


def max_job_id():
    return JOB_NUM - 1


def _creator(split, size):
    def reader():
        rng = common.split_rng("movielens", split)
        model = common.split_rng("movielens", "model")
        u_emb = model.randn(USER_NUM, 8)
        m_emb = model.randn(MOVIE_NUM, 8)
        for _ in range(size):
            u = int(rng.randint(1, USER_NUM))
            m = int(rng.randint(1, MOVIE_NUM))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, JOB_NUM))
            cats = [int(c) for c in
                    rng.choice(CATEGORY_NUM, rng.randint(1, 4),
                               replace=False)]
            title = [int(t) for t in rng.randint(0, TITLE_VOCAB,
                                                 rng.randint(1, 6))]
            raw = u_emb[u].dot(m_emb[m]) * 0.5 + 3.0
            score = float(np.clip(round(raw + 0.3 * rng.randn()), 1, 5))
            yield u, gender, age, job, m, cats, title, score

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)


class MovieInfo(object):
    """Movie id, title and categories (reference movielens.py:48)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo(object):
    """User id, gender, age bucket and job (reference movielens.py:75)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


CATEGORIES = ["Action", "Adventure", "Animation", "Children's", "Comedy",
              "Crime", "Documentary", "Drama", "Fantasy", "Film-Noir",
              "Horror", "Musical", "Mystery", "Romance", "Sci-Fi",
              "Thriller", "War", "Western"]
CATEGORIES_DICT = {c: i for i, c in enumerate(CATEGORIES)}
# procedurally generated titles: "movie <id>" per synthetic movie
MOVIE_TITLE_DICT = {"movie": 0}
MOVIE_TITLE_DICT.update({str(i): i + 1 for i in range(MOVIE_NUM)})

_MOVIE_INFO = None
_USER_INFO = None


def _meta():
    """Deterministic synthetic metadata consistent with the rating
    readers' id ranges (the reference parsed movies.dat/users.dat)."""
    global _MOVIE_INFO, _USER_INFO
    if _MOVIE_INFO is None:
        rng = common.split_rng("movielens", "meta")
        _MOVIE_INFO = {}
        for m in range(1, MOVIE_NUM):
            cats = [CATEGORIES[c] for c in
                    rng.choice(CATEGORY_NUM, rng.randint(1, 4),
                               replace=False)]
            _MOVIE_INFO[m] = MovieInfo(m, cats, "movie %d" % m)
        _USER_INFO = {}
        for u in range(1, USER_NUM):
            _USER_INFO[u] = UserInfo(
                u, "M" if rng.randint(0, 2) else "F",
                age_table[rng.randint(0, len(age_table))],
                rng.randint(0, JOB_NUM))
    return _MOVIE_INFO, _USER_INFO


def get_movie_title_dict():
    """Movie title vocabulary (reference movielens.py:178)."""
    return MOVIE_TITLE_DICT


def movie_categories():
    """Category name -> id (reference movielens.py:225)."""
    return CATEGORIES_DICT


def user_info():
    """user id -> UserInfo (reference movielens.py:233)."""
    return _meta()[1]


def movie_info():
    """movie id -> MovieInfo (reference movielens.py:241)."""
    return _meta()[0]


def convert(path):
    """Write the readers as recordio shards (reference movielens.py)."""
    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
