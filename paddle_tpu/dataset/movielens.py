"""MovieLens reader creators (reference python/paddle/dataset/movielens.py).

Samples: (user_id, gender_id, age_id, job_id, movie_id, category_ids,
title_ids, score). Synthetic preferences come from a low-rank user x movie
model so recommender tests converge."""

import numpy as np

from . import common

__all__ = ["train", "test", "max_user_id", "max_movie_id", "max_job_id",
           "age_table"]

USER_NUM = 944
MOVIE_NUM = 1683
JOB_NUM = 21
CATEGORY_NUM = 18
TITLE_VOCAB = 1000
age_table = [1, 18, 25, 35, 45, 50, 56]
TRAIN_SIZE = 2048
TEST_SIZE = 512


def max_user_id():
    return USER_NUM - 1


def max_movie_id():
    return MOVIE_NUM - 1


def max_job_id():
    return JOB_NUM - 1


def _creator(split, size):
    def reader():
        rng = common.split_rng("movielens", split)
        model = common.split_rng("movielens", "model")
        u_emb = model.randn(USER_NUM, 8)
        m_emb = model.randn(MOVIE_NUM, 8)
        for _ in range(size):
            u = int(rng.randint(1, USER_NUM))
            m = int(rng.randint(1, MOVIE_NUM))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, len(age_table)))
            job = int(rng.randint(0, JOB_NUM))
            cats = [int(c) for c in
                    rng.choice(CATEGORY_NUM, rng.randint(1, 4),
                               replace=False)]
            title = [int(t) for t in rng.randint(0, TITLE_VOCAB,
                                                 rng.randint(1, 6))]
            raw = u_emb[u].dot(m_emb[m]) * 0.5 + 3.0
            score = float(np.clip(round(raw + 0.3 * rng.randn()), 1, 5))
            yield u, gender, age, job, m, cats, title, score

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)
