"""Dataset loaders (reference python/paddle/dataset/).

The reference downloads mnist/cifar/imdb/... to ~/.cache and exposes
`train()/test()` reader creators. This environment has no network egress,
so each dataset is generated *procedurally and deterministically* with the
same sample types/shapes/vocab APIs — drop-in for the training scripts and
tests; swap `paddle_tpu.dataset.common.synthetic_mode(False)` + a data dir
to use real files laid out the same way.
"""

from . import common
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb
from . import wmt14
from . import wmt16
from . import movielens
from . import conll05
from . import flowers
from . import imikolov
from . import mq2007
from . import sentiment
from . import voc2012

__all__ = ["common", "mnist", "cifar", "uci_housing", "imdb", "wmt14",
           "wmt16", "movielens", "conll05", "flowers", "imikolov",
           "mq2007", "sentiment", "voc2012"]
