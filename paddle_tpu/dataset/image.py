"""Image preprocessing helpers under the dataset package (reference
python/paddle/dataset/image.py — the same functions the v2 package
exposes as paddle.v2.image; one implementation, both import paths)."""

from ..v2.image import *  # noqa: F401,F403
from ..v2.image import __all__  # noqa: F401
