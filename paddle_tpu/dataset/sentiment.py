"""NLTK movie-reviews sentiment reader (reference
python/paddle/dataset/sentiment.py): get_word_dict() -> vocab;
train()/test() yield (word-id list, label in {0,1})."""

import numpy as np

from . import common

__all__ = ["train", "test", "get_word_dict", "convert"]

VOCAB = 39768          # reference movie_reviews vocab order
TRAIN_SIZE = 1600      # reference: 80% of 2000 docs
TEST_SIZE = 400
MIN_LEN, MAX_LEN = 10, 200


def get_word_dict():
    return {"w%d" % i: i for i in range(VOCAB)}


def _creator(split, size):
    def reader():
        rng = common.split_rng("sentiment", split)
        third = VOCAB // 3
        for _ in range(size):
            label = int(rng.randint(0, 2))
            n = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            bank_lo = 0 if label else third
            biased = rng.randint(bank_lo, bank_lo + third, n)
            neutral = rng.randint(2 * third, VOCAB, n)
            words = np.where(rng.rand(n) < 0.7, biased, neutral)
            yield [int(w) for w in words], label

    return reader


def train():
    return _creator("train", TRAIN_SIZE)


def test():
    return _creator("test", TEST_SIZE)


def convert(path):
    """Write the readers as recordio shards (reference sentiment.py)."""
    common.convert(path, train(), 1000, "sentiment_train")
    common.convert(path, test(), 1000, "sentiment_test")
