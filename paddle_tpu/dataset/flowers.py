"""Oxford-102 flowers reader (reference python/paddle/dataset/flowers.py):
train/test/valid yield (image, label) where image is the mapper output —
by default a float32 CHW array ready for conv nets — and label is in
[0, 102)."""

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]

NUM_CLASSES = 102
TRAIN_SIZE = 512
TEST_SIZE = 128
IMG_SHAPE = (3, 224, 224)


def _creator(split, size, cycle=False):
    def reader():
        while True:
            rng = common.split_rng("flowers", split)
            for _ in range(size):
                label = int(rng.randint(0, NUM_CLASSES))
                # class-conditioned mean keeps the task learnable
                img = (rng.rand(*IMG_SHAPE).astype(np.float32) * 0.5
                       + label / float(NUM_CLASSES))
                yield img, label
            if not cycle:
                return

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    """mapper/buffered_size/use_xmap exist for reference API parity; the
    synthetic samples are already mapper-shaped CHW float arrays."""
    return _creator("train", TRAIN_SIZE, cycle)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator("test", TEST_SIZE, cycle)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _creator("val", TEST_SIZE)
