"""WMT14 en-fr reader creators (reference python/paddle/dataset/wmt14.py).

Samples: (src ids, trg ids with <s>, trg ids shifted with <e>). Synthetic
"translation" pairs are id-mapped sequences (trg = f(src)) so seq2seq
models have real signal. START=0, END=1, UNK=2 like the reference."""

import numpy as np

from . import common

__all__ = ["train", "test", "N", "START", "END", "UNK",
           "get_dict", "convert"]

N = 30  # default dict size knob in the reference API
START, END, UNK = 0, 1, 2
TRAIN_SIZE = 512
TEST_SIZE = 128
MIN_LEN, MAX_LEN = 4, 16


def _creator(split, size, dict_size):
    def reader():
        rng = common.split_rng("wmt14", split)
        shift = 7  # fixed "translation" mapping
        for _ in range(size):
            n = int(rng.randint(MIN_LEN, MAX_LEN + 1))
            src = rng.randint(3, dict_size, n)
            trg = (src + shift - 3) % (dict_size - 3) + 3
            src_ids = [int(w) for w in src]
            trg_in = [START] + [int(w) for w in trg]
            trg_out = [int(w) for w in trg] + [END]
            yield src_ids, trg_in, trg_out

    return reader


def train(dict_size):
    return _creator("train", TRAIN_SIZE, dict_size)


def test(dict_size):
    return _creator("test", TEST_SIZE, dict_size)


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict) consistent with the synthetic id streams
    (reference wmt14.py get_dict: id->word when reverse=True)."""
    def one(prefix):
        words = {0: "<s>", 1: "<e>", 2: "<unk>"}
        words.update({i: "%s%d" % (prefix, i) for i in range(3, dict_size)})
        if reverse:
            return words
        return {w: i for i, w in words.items()}
    return one("src"), one("trg")


def convert(path):
    """Write the readers as recordio shards (reference wmt14.py)."""
    common.convert(path, train(N), 1000, "wmt14_train")
    common.convert(path, test(N), 1000, "wmt14_test")
