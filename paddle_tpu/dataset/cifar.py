"""CIFAR reader creators (reference python/paddle/dataset/cifar.py).

Samples: (image float32[3072] in [0, 1], label). train10/test10 = CIFAR-10,
train100/test100 = CIFAR-100."""

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100", "convert"]

IMAGE_DIM = 3 * 32 * 32
TRAIN_SIZE = 2048
TEST_SIZE = 512


def _creator(split, size, class_num):
    def reader():
        rng = common.split_rng("cifar%d" % class_num, split)
        protos = common.split_rng("cifar%d" % class_num, "protos").randn(
            class_num, IMAGE_DIM).astype(np.float32)
        labels = rng.randint(0, class_num, size)
        imgs = 0.5 * (1.0 + np.tanh(
            0.6 * protos[labels] + 0.4 * rng.randn(size, IMAGE_DIM)))
        imgs = imgs.astype(np.float32)
        for i in range(size):
            yield imgs[i], int(labels[i])

    return reader


def train10():
    return _creator("train", TRAIN_SIZE, 10)


def test10():
    return _creator("test", TEST_SIZE, 10)


def train100():
    return _creator("train", TRAIN_SIZE, 100)


def test100():
    return _creator("test", TEST_SIZE, 100)
def convert(path):
    """Write the cifar-10 readers as recordio shards (reference
    cifar.py convert)."""
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
