"""Device-mesh helpers — the TPU analogue of the reference's device lists +
NCCLContextMap (platform/nccl_helper.h:82, parallel_executor.cc:113).

A Mesh over ICI replaces per-device CUDA streams and NCCL communicators:
collectives are compiled into the step by XLA's SPMD partitioner. Axis
conventions (used across the framework):

  data   — batch/data parallelism (grad allreduce ≅ all_reduce_op_handle)
  model  — tensor parallelism for sharded weights/embeddings
  seq    — sequence/context parallelism (ring attention milestone)
  pipe   — pipeline stages
  expert — MoE expert parallelism
"""

import os

import numpy as np

__all__ = ["make_mesh", "data_parallel_mesh", "local_device_count", "get_shard_map",
           "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def _accel_devices():
    """Device list behind the fluid `use_cuda` flag: ALWAYS the default
    JAX backend (TPU on silicon, CPU on the virtual test mesh). The
    reference's flag picks CUDA vs host-CPU places; this framework has
    no CUDA backend, and `use_cuda=False` (the only spelling the fluid
    API has for "no CUDA") must NOT silently demote a TPU program to
    host-CPU execution — that bug cost 195x on the measured
    ParallelExecutor throughput. Callers that genuinely want a host-CPU
    mesh on an accelerator host pass an explicit mesh (see
    tools/debug_parity.py)."""
    import jax
    return jax.devices()


def local_device_count(use_cuda=True):
    """Device count, honoring CPU_NUM like the reference's parallel_executor.py
    (python wrapper :32 builds places from CUDA_VISIBLE_DEVICES / CPU_NUM)."""
    devs = _accel_devices()
    if not use_cuda and devs and devs[0].platform == "cpu":
        cpu_num = int(os.environ.get("CPU_NUM", len(devs)))
        return min(cpu_num, len(devs)) or 1
    return len(devs)


def make_mesh(axis_sizes, devices=None):
    """axis_sizes: dict axis-name -> size (row-major over the device list).

    RNG caveat (jax 0.4.x, legacy threefry): jax.random bits CHANGE with
    an array's sharding, so a seeded op (dropout) computes a different
    mask on a mesh than replicated on one device. Harnesses that assert
    replicated-vs-sharded trajectory PARITY must flip
    ``jax_threefry_partitionable`` first (see __graft_entry__.py) — not
    done here because the flag redefines every seeded stream
    process-wide, and flipping it lazily at first-mesh-use makes RNG
    order-dependent across a test session."""
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError("mesh needs %d devices, have %d" %
                         (n, len(devices)))
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def data_parallel_mesh(num_devices=None, use_cuda=True):
    devs = _accel_devices()
    if num_devices is None:
        num_devices = local_device_count(use_cuda)
    return make_mesh({DATA_AXIS: num_devices}, devs[:num_devices])


def get_shard_map():
    """Version-compat accessor for jax's shard_map (moved out of
    jax.experimental in jax 0.8)."""
    try:
        from jax import shard_map
    except ImportError:       # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_no_rep_check(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking disabled — required for shard
    bodies that invoke Pallas kernels (jax has no replication rule for
    pallas_call). The kwarg was renamed across jax versions."""
    sm = get_shard_map()
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    except TypeError:          # jax >= 0.8
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
