"""Device-mesh helpers — the TPU analogue of the reference's device lists +
NCCLContextMap (platform/nccl_helper.h:82, parallel_executor.cc:113).

A Mesh over ICI replaces per-device CUDA streams and NCCL communicators:
collectives are compiled into the step by XLA's SPMD partitioner. Axis
conventions (used across the framework):

  data   — batch/data parallelism (grad allreduce ≅ all_reduce_op_handle)
  model  — tensor parallelism for sharded weights/embeddings
  seq    — sequence/context parallelism (ring attention milestone)
  pipe   — pipeline stages
  expert — MoE expert parallelism
"""

import os
import re

import numpy as np

__all__ = ["make_mesh", "data_parallel_mesh", "local_device_count", "get_shard_map",
           "MeshGroup", "MeshMemberLost", "as_mesh_group",
           "set_member_poison", "check_member_poison",
           "tp_param_pspec", "tp_supported",
           "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS"]

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def _accel_devices():
    """Device list behind the fluid `use_cuda` flag: ALWAYS the default
    JAX backend (TPU on silicon, CPU on the virtual test mesh). The
    reference's flag picks CUDA vs host-CPU places; this framework has
    no CUDA backend, and `use_cuda=False` (the only spelling the fluid
    API has for "no CUDA") must NOT silently demote a TPU program to
    host-CPU execution — that bug cost 195x on the measured
    ParallelExecutor throughput. Callers that genuinely want a host-CPU
    mesh on an accelerator host pass an explicit mesh (see
    tools/debug_parity.py)."""
    import jax
    return jax.devices()


def local_device_count(use_cuda=True):
    """Device count, honoring CPU_NUM like the reference's parallel_executor.py
    (python wrapper :32 builds places from CUDA_VISIBLE_DEVICES / CPU_NUM)."""
    devs = _accel_devices()
    if not use_cuda and devs and devs[0].platform == "cpu":
        cpu_num = int(os.environ.get("CPU_NUM", len(devs)))
        return min(cpu_num, len(devs)) or 1
    return len(devs)


def make_mesh(axis_sizes, devices=None):
    """axis_sizes: dict axis-name -> size (row-major over the device list).

    RNG caveat (jax 0.4.x, legacy threefry): jax.random bits CHANGE with
    an array's sharding, so a seeded op (dropout) computes a different
    mask on a mesh than replicated on one device. Harnesses that assert
    replicated-vs-sharded trajectory PARITY must flip
    ``jax_threefry_partitionable`` first (see __graft_entry__.py) — not
    done here because the flag redefines every seeded stream
    process-wide, and flipping it lazily at first-mesh-use makes RNG
    order-dependent across a test session."""
    import jax
    from jax.sharding import Mesh
    if devices is None:
        devices = jax.devices()
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError("mesh needs %d devices, have %d" %
                         (n, len(devices)))
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, names)


def data_parallel_mesh(num_devices=None, use_cuda=True):
    devs = _accel_devices()
    if num_devices is None:
        num_devices = local_device_count(use_cuda)
    return make_mesh({DATA_AXIS: num_devices}, devs[:num_devices])


def get_shard_map():
    """Version-compat accessor for jax's shard_map (moved out of
    jax.experimental in jax 0.8)."""
    try:
        from jax import shard_map
    except ImportError:       # older jax
        from jax.experimental.shard_map import shard_map
    return shard_map


def shard_map_no_rep_check(fn, mesh, in_specs, out_specs):
    """shard_map with replication checking disabled — required for shard
    bodies that invoke Pallas kernels (jax has no replication rule for
    pallas_call). The kwarg was renamed across jax versions."""
    sm = get_shard_map()
    try:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)
    except TypeError:          # jax >= 0.8
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)


# ---------------------------------------------------------------------------
# serving mesh groups: one replica = a device mesh (SERVING.md "Mesh
# replicas")
# ---------------------------------------------------------------------------


class MeshMemberLost(RuntimeError):
    """A device inside a serving mesh group stopped answering: the whole
    group is one logical replica, so losing ONE member kills the lane
    (marked dead, never wedged) — in-flight requests on that lane fail
    with this type while sibling lanes keep serving, and the fleet
    controller rebuilds the lane from its persisted spec (the chaos
    `mesh-member-loss` scenario pins this contract)."""


class MeshGroup:
    """An ordered group of >= 2 local devices acting as ONE logical
    serving device: the placement unit `model_registry.resolve_placement`
    emits for `mesh:RxC` / `a+b` specs and the serving predictors build
    against.

    Ducks the `jax.Device` attribute surface the serving stack touches
    (`platform`, `id`, `device_kind`), so everything that merely labels
    or fingerprints a placement keeps working; code that MOVES data
    branches on `isinstance(dev, MeshGroup)` and uses the sharding
    helpers below.

    Sharding discipline — two compute modes over the same at-rest
    layout family (SERVING.md "Mesh replicas"):

    * shard-at-rest (default, PR 18): parameters and the decode KV slot
      table are SHARDED AT REST over the 1-D `model` axis (per-device
      resident bytes ~ 1/mesh_size — the fit-check unlock); compute
      runs REPLICATED — every traced phase gathers its operands back to
      replicated before any math (see the predictors' `_mesh_wrap`), so
      no float reduction is ever reordered across members and a mesh
      replica's stream is bit-identical to a single-device replica's.
      HBM capacity scales with the mesh; per-step traffic does not.

    * tensor-parallel (`FLAGS.mesh_tp`, SERVING.md "Tensor-parallel
      compute"): the program lowers as one shard_map'd executable over
      this mesh — weights placed by `tp_param_pspec` (Megatron
      column->row pairs, one psum per pair), attention head-parallel
      on the resident KV shard, embedding row-sharded over vocab.
      Params and KV never materialize unsharded, so per-step HBM
      traffic per member drops ~1/mesh_size too (the decode-roofline
      win). Streams are top-1 identical; activations downstream of a
      row-split matmul carry psum reduction-order noise at float
      tolerance (the documented demotion from bit-exact).

    Both are the MLPerf pods paper's weight-update-sharding blueprint
    applied to inference; TP adds the Megatron intra-layer split."""

    __slots__ = ("devices", "shape", "_mesh")

    def __init__(self, devices, shape=None):
        devices = tuple(devices)
        if len(devices) < 2:
            raise ValueError(
                "a mesh group needs >= 2 devices, got %d (a 1-device "
                "mesh is just the device — resolve_placement collapses "
                "it)" % len(devices))
        seen = set()
        for d in devices:
            key = (getattr(d, "platform", None), getattr(d, "id", None))
            if key in seen:
                raise ValueError(
                    "duplicate device %s:%s in mesh group" % key)
            seen.add(key)
        if shape is None:
            shape = (len(devices),)
        shape = tuple(int(s) for s in shape)
        if int(np.prod(shape)) != len(devices):
            raise ValueError(
                "mesh shape %r does not cover %d devices"
                % (shape, len(devices)))
        self.devices = devices
        self.shape = shape
        self._mesh = None

    # -- jax.Device duck surface (labels / fingerprints only) -----------

    @property
    def platform(self):
        return getattr(self.devices[0], "platform", "cpu")

    @property
    def id(self):
        return getattr(self.devices[0], "id", 0)

    @property
    def device_kind(self):
        # namespaced per mesh size so a meshed executable fingerprint
        # can never collide with a single-device one
        return "%s/mesh%d" % (
            getattr(self.devices[0], "device_kind", ""), len(self.devices))

    # -- group surface --------------------------------------------------

    @property
    def mesh_size(self):
        return len(self.devices)

    @property
    def primary(self):
        """The first member — where mesh-incapable callers (serialized
        AOT exports) degrade to."""
        return self.devices[0]

    def label(self):
        """'cpu:0+cpu:1' — the wire/spec spelling; resolve_placement
        parses it back, which is what makes page-out / fault-in / resize
        replay a mesh lane spec verbatim."""
        return "+".join("%s:%d" % (getattr(d, "platform", "cpu"),
                                   getattr(d, "id", 0))
                        for d in self.devices)

    def member_labels(self):
        return [lbl for lbl in self.label().split("+")]

    def __repr__(self):
        return "MeshGroup(%s)" % self.label()

    def __eq__(self, other):
        return isinstance(other, MeshGroup) and \
            self.devices == other.devices

    def __hash__(self):
        return hash(self.devices)

    def mesh(self):
        """The jax.sharding.Mesh (1-D over MODEL_AXIS, lazily built)."""
        if self._mesh is None:
            from jax.sharding import Mesh
            self._mesh = Mesh(np.array(self.devices), (MODEL_AXIS,))
        return self._mesh

    def replicated(self):
        """NamedSharding replicating an array on every member."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh(), P())

    def _axis_sharding(self, ndim, axis):
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = [None] * ndim
        spec[axis] = MODEL_AXIS
        return NamedSharding(self.mesh(), P(*spec))

    def axis_sharding(self, ndim, axis):
        """NamedSharding splitting `axis` of an ndim-rank array over the
        group's `model` axis — the public spelling TP compute uses for
        activations (e.g. head-sharded q/k/v)."""
        return self._axis_sharding(int(ndim), int(axis))

    def tp_param_sharding(self, name, shape):
        """At-rest sharding for one NAMED decode parameter under
        tensor-parallel compute: `tp_param_pspec`'s axis grammar bound
        to this group's mesh. Unlike `param_sharding` (which scans for
        any divisible axis), placement here is dictated by the op's
        role in the partitioned program — a row-parallel weight MUST
        shard its input axis or the local matmul shapes are wrong."""
        from jax.sharding import NamedSharding
        return NamedSharding(self.mesh(), tp_param_pspec(name, shape))

    def param_sharding(self, shape):
        """At-rest sharding for one parameter: the last axis whose size
        divides the mesh (output-column parallel for the common [in,
        out] case), scanning right to left; small / indivisible arrays
        (biases, norms) replicate."""
        n = self.mesh_size
        shape = tuple(int(s) for s in shape)
        for ax in range(len(shape) - 1, -1, -1):
            if shape[ax] >= n and shape[ax] % n == 0:
                return self._axis_sharding(len(shape), ax)
        return self.replicated()

    def kv_sharding(self, shape):
        """At-rest sharding for a [L, n_slots, S, H, Dh] KV slot table:
        heads first (the per-head independence axis the decode kernel
        already respects), then slots, then layers; replicate only when
        nothing divides."""
        n = self.mesh_size
        shape = tuple(int(s) for s in shape)
        if len(shape) != 5:
            return self.param_sharding(shape)
        for ax in (3, 1, 0):
            if shape[ax] >= n and shape[ax] % n == 0:
                return self._axis_sharding(5, ax)
        return self.replicated()


# ---------------------------------------------------------------------------
# tensor-parallel compute grammar (SERVING.md "Tensor-parallel compute")
# ---------------------------------------------------------------------------

# Megatron-style intra-layer split of the decode transformer, by
# parameter family (the layer prefix 'l<N>_' is stripped before lookup):
#
#   column-parallel  [in, out/m]   wq wk wv (head split), w1, lm_head
#   row-parallel     [in/m, out]   wo, w2 — one psum closes each
#                                  column->row pair; b2 adds after it
#   vocab-row        [V/m, D]      embed — local masked gather + psum
#                                  (exact: one member owns each row,
#                                  the rest contribute true zeros —
#                                  parallel/sharded_embedding.py)
#   sharded bias     [4D/m]        b1 — rides its column pair
#   replicated                     pos, layer norms, b2, lnf
_TP_COLUMN = frozenset(("wq", "wk", "wv", "w1", "lm_head"))
_TP_ROW = frozenset(("wo", "w2", "embed"))
_TP_BIAS = frozenset(("b1",))
_LAYER_PREFIX = re.compile(r"^l\d+_")


def tp_param_pspec(name, shape):
    """jax PartitionSpec for one named decode parameter under
    tensor-parallel compute. Names outside the decode state grammar
    (and wrong-rank shapes) replicate — the safe default, since the
    partitioned program only ever consumes local shards of the families
    above."""
    from jax.sharding import PartitionSpec as P
    base = _LAYER_PREFIX.sub("", str(name))
    ndim = len(tuple(shape))
    if base in _TP_COLUMN and ndim == 2:
        return P(None, MODEL_AXIS)
    if base in _TP_ROW and ndim == 2:
        return P(MODEL_AXIS, None)
    if base in _TP_BIAS and ndim == 1:
        return P(MODEL_AXIS)
    return P()


def tp_supported(mesh_size, n_heads, d_model, vocab_size, d_ff=None):
    """True when the decode dims split evenly over `mesh_size` members —
    the gate `GenerativePredictor` checks before placing state TP.
    Every sharded family must divide exactly: heads for attention/KV,
    d_model for the row-parallel contractions, vocab for the embedding
    rows and lm_head columns, d_ff for the MLP pair."""
    m = int(mesh_size)
    if m < 2:
        return False
    dims = [int(n_heads), int(d_model), int(vocab_size)]
    if d_ff:
        dims.append(int(d_ff))
    return all(d >= m and d % m == 0 for d in dims)


def as_mesh_group(device):
    """`device` as (MeshGroup | None): the isinstance probe the
    predictors use without importing jax at module import time."""
    return device if isinstance(device, MeshGroup) else None


# chaos hook (tools/chaos.py mesh-member-loss scenario): poisoning a
# member device label makes every dispatch on a mesh group CONTAINING
# that member raise MeshMemberLost — the in-process stand-in for a chip
# dropping off the ICI mid-stream.  Lanes on meshes that do not include
# the member (and plain single-device lanes) are untouched.
_MEMBER_POISON = {"label": None}


def set_member_poison(device_label=None):
    """Arm (a 'platform:id' member label) or disarm (None) the
    mesh-member-loss chaos injection."""
    _MEMBER_POISON["label"] = (str(device_label)
                               if device_label is not None else None)


def check_member_poison(group):
    """Raise MeshMemberLost if the poisoned member sits in `group`
    (called at every mesh dispatch edge)."""
    lbl = _MEMBER_POISON["label"]
    if lbl is None or not isinstance(group, MeshGroup):
        return
    if lbl in group.member_labels():
        raise MeshMemberLost(
            "mesh member %s lost (chaos poison) — mesh replica %s is "
            "down" % (lbl, group.label()))
