"""Ring attention — context parallelism over the `seq` mesh axis.

No reference analogue (powermano/Paddle predates sequence parallelism —
SURVEY.md §2.10 row 'Pipeline/TP/SP': absent); built TPU-first per the task
charter. Design follows blockwise ring attention: Q stays resident, K/V
blocks circulate the ring via `lax.ppermute` over ICI, each hop overlapped
with the local block's flash-style online-softmax update, so no device ever
materializes the full [S, S] score matrix or the full K/V.

Use inside shard_map over a mesh with a `seq` axis (helper
`ring_attention_sharded` wraps that), sequence sharded as [B, S/n, H, D].

Each hop's local block is computed by the TUNED Pallas flash kernel
(ops/pallas_kernels.py, geometry via ops/attention_tuning.py) when
FLAGS.ring_use_flash is set (default): the kernel returns the block's
normalized output plus its row logsumexp, and hops merge by the
numerically-stable logsumexp combine — so multi-chip sequence
parallelism rides the same kernel single-chip attention does, and no
hop ever materializes its [S_loc, S_loc] score tile. The plain-XLA
online-softmax update remains as the flag-off / non-tileable path.
"""

import functools

import numpy as np

__all__ = ["ring_attention", "ring_attention_sharded", "local_attention"]

_NEG_INF = -1e30   # finite: matches the kernel's mask value, keeps the
                   # fully-masked-hop merge free of inf - inf


def _merge_hops(o, lse, o_t, lse_t):
    """Combine two normalized partial attentions over disjoint K sets:
    (o, lse) <- logsumexp merge. A hop with lse_t = _NEG_INF (fully
    masked) contributes weight exp(_NEG_INF - finite) = 0 exactly."""
    import jax.numpy as jnp
    m = jnp.maximum(lse, lse_t)
    wa = jnp.exp(lse - m)
    wb = jnp.exp(lse_t - m)
    # both-empty rows: lse == lse_t == _NEG_INF -> wa = wb = 1, no 0/0
    o_new = (o * wa[..., None] + o_t.astype(o.dtype) * wb[..., None]) \
        / (wa + wb)[..., None]
    return o_new, m + jnp.log(wa + wb)


def _online_block_update(o, l, m, q, k, v, mask, scale):
    """One flash-attention block accumulation step.

    o [B,Sq,H,D] running (unnormalized) output, l [B,Sq,H] running sum of
    exp, m [B,Sq,H] running max; q [B,Sq,H,D], k/v [B,Sk,H,D];
    mask [Sq, Sk] additive (-inf for masked) or None."""
    import jax.numpy as jnp
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale   # [B,H,Sq,Sk]
    if mask is not None:
        scores = scores + mask[None, None]
    m_blk = jnp.max(scores, axis=-1)                        # [B,H,Sq]
    m_new = jnp.maximum(m, m_blk.transpose(0, 2, 1))        # [B,Sq,H]
    # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> use where
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
    p = jnp.exp(scores - safe_m.transpose(0, 2, 1)[:, :, :, None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1).transpose(0, 2, 1)
    o_new = o * alpha[..., None] + jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o_new, l_new, m_new


def local_attention(q, k, v, causal=False, q_offset=0, k_offset=0,
                    scale=None):
    """Plain (single-block) attention with optional causal mask expressed in
    GLOBAL positions — the building block the ring circulates."""
    import jax.numpy as jnp
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qpos = q_offset + jnp.arange(Sq)
        kpos = k_offset + jnp.arange(Sk)
        mask = (kpos[None, :] > qpos[:, None])
        scores = jnp.where(mask[None, None], -jnp.inf, scores)
    p = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    denom = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-20)
    return jnp.einsum("bhqk,bkhd->bqhd", p / denom, v)


def _ring_flash(q, k, v, axis_name, causal, scale):
    """Flash-kernel ring body: every hop runs the tuned Pallas kernel on
    its local block and merges by logsumexp. For causal, hop 0 is the
    diagonal (causal kernel); later hops are either fully visible
    (origin strictly behind this rank — non-causal kernel) or fully
    masked (origin ahead — contribution zeroed via lse = -inf), so no
    hop needs cross-shard mask coordinates inside the kernel."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas_kernels import flash_attention

    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((B, S_loc, H), _NEG_INF, jnp.float32)
    kb, vb = k, v
    for t in range(n):                 # static: n is a mesh constant
        src = (rank - t) % n           # block origin
        o_t, lse_t = flash_attention(q, kb, vb, causal=causal and t == 0,
                                     scale=scale, return_lse=True)
        if causal and t > 0:
            # whole hop visible iff the K/V block originated behind us
            lse_t = jnp.where(src < rank, lse_t, _NEG_INF)
        o, lse = _merge_hops(o, lse, o_t, lse_t)
        if t + 1 < n:
            kb = jax.lax.ppermute(kb, axis_name, perm)
            vb = jax.lax.ppermute(vb, axis_name, perm)
    return o.astype(q.dtype)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   use_flash=None):
    """Per-shard body: call INSIDE shard_map/pjit with q,k,v local blocks
    [B, S_loc, H, D] sharded over `axis_name`. Returns the local output
    block [B, S_loc, H, D].

    K/V make a full trip around the ring (n hops); hop t processes the
    block that originated on device (rank - t) mod n, with the causal mask
    evaluated in global coordinates. `use_flash=None` defers to
    FLAGS.ring_use_flash (trace-time): the flash path computes each hop
    with the tuned Pallas kernel and merges by logsumexp."""
    import jax
    import jax.numpy as jnp

    scale = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    if use_flash is None:
        from ..flags import FLAGS
        use_flash = bool(FLAGS.ring_use_flash)
    if use_flash:
        return _ring_flash(q, k, v, axis_name, causal, scale)

    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    B, S_loc, H, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    q_pos = rank * S_loc + jnp.arange(S_loc)                 # global q rows

    def hop(t, state):
        o, l, m, kb, vb = state
        src = (rank - t) % n                                  # block origin
        k_pos = src * S_loc + jnp.arange(S_loc)
        if causal:
            mask = jnp.where(k_pos[None, :] > q_pos[:, None],
                             -jnp.inf, 0.0)
        else:
            mask = None
        o, l, m = _online_block_update(o, l, m, q, kb, vb, mask, scale)
        # rotate K/V to the next device (skipped result unused on last hop)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return o, l, m, kb, vb

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((B, S_loc, H), q.dtype)
    m0 = jnp.full((B, S_loc, H), -jnp.inf, q.dtype)
    state = (o0, l0, m0, k, v)
    # static python loop: n is a trace-time constant; each hop's ppermute
    # overlaps with the next hop's compute under XLA's async collectives
    for t in range(n):
        state = hop(t, state)
    o, l, m = state[0], state[1], state[2]
    return o / jnp.maximum(l, 1e-20)[..., None]


def ring_attention_sharded(q, k, v, mesh, axis_name="seq", causal=False,
                           scale=None):
    """Convenience wrapper: q,k,v are GLOBAL [B, S, H, D] arrays; runs
    ring_attention under shard_map with S sharded over `axis_name`."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_no_rep_check

    spec = P(None, axis_name, None, None)
    fn = shard_map_no_rep_check(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
