"""GPipe-style pipeline parallelism over the `pipe` mesh axis.

No reference analogue (SURVEY.md §2.10: pipeline parallelism absent in the
2018 codebase); TPU-first per the task charter. Stage parameters are stacked
on a leading [n_stages, ...] axis and sharded over `pipe`; microbatch
activations flow stage-to-stage via `lax.ppermute` over ICI in a
(M + n - 1)-tick schedule (the classic GPipe fill/drain bubble). Everything
runs inside one shard_map, so XLA overlaps each tick's send with the next
tick's compute.
"""

import functools

import numpy as np

__all__ = ["pipeline_apply", "pipeline_sharded"]


def pipeline_apply(stage_fn, stage_params, microbatches, axis_name):
    """Per-shard body (inside shard_map over `axis_name` of size n).

    stage_fn(params, x) -> y: one pipeline stage; activations keep shape.
    stage_params: this device's stage parameters (leading [1, ...] shard of
      the stacked [n, ...] pytree) — squeezed before use.
    microbatches: [M, mb, ...] all microbatch inputs (replicated).
    Returns [M, mb, ...] outputs (valid on every device after the final
    broadcast from the last stage).
    """
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    params = jax.tree_util.tree_map(lambda p: p[0], stage_params)
    M = microbatches.shape[0]
    ticks = M + n - 1
    fwd_perm = [(i, i + 1) for i in range(n - 1)]

    x_shape = microbatches.shape[1:]

    def tick(carry, t):
        buf, outs = carry
        # stage 0 ingests microbatch t (zeros past the fill phase)
        mb_idx = jnp.minimum(t, M - 1)
        fresh = jax.lax.dynamic_index_in_dim(microbatches, mb_idx, axis=0,
                                             keepdims=False)
        inp = jnp.where(rank == 0, fresh, buf)
        y = stage_fn(params, inp)
        # last stage emits microbatch t - (n - 1) at tick t
        out_idx = t - (n - 1)
        valid = (rank == n - 1) & (out_idx >= 0)
        upd = jax.lax.dynamic_update_index_in_dim(
            outs, y, jnp.maximum(out_idx, 0), axis=0)
        outs = jnp.where(valid, upd, outs)
        # send activations downstream (device i -> i+1)
        buf_next = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (buf_next, outs), None

    buf0 = jnp.zeros(x_shape, microbatches.dtype)
    outs0 = jnp.zeros((M,) + x_shape, microbatches.dtype)
    # carries become device-varying after the first tick (ppermute/rank
    # branches); mark the initial values as varying so scan types match
    if hasattr(jax.lax, "pcast"):          # jax >= 0.8 spelling
        buf0 = jax.lax.pcast(buf0, axis_name, to="varying")
        outs0 = jax.lax.pcast(outs0, axis_name, to="varying")
    elif hasattr(jax.lax, "pvary"):
        buf0 = jax.lax.pvary(buf0, (axis_name,))
        outs0 = jax.lax.pvary(outs0, (axis_name,))
    (_, outs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(ticks))
    # broadcast results from the last stage to every device so the caller
    # sees a replicated output (psum of the masked buffer = broadcast)
    outs = jax.lax.psum(
        jnp.where(rank == n - 1, outs, jnp.zeros_like(outs)), axis_name)
    return outs


def pipeline_sharded(stage_fn, stacked_params, microbatches, mesh,
                     axis_name="pipe"):
    """stacked_params: pytree with leading [n_stages, ...] axis;
    microbatches [M, mb, ...] replicated. Returns [M, mb, ...]."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .mesh import get_shard_map
    shard_map = get_shard_map()

    param_spec = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params)
    fn = shard_map(
        functools.partial(pipeline_apply, stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_spec, P()), out_specs=P())
    return fn(stacked_params, microbatches)
