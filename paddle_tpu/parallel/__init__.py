"""Parallelism strategies (SURVEY.md §2.10) — TPU-native:

data parallel      — ParallelExecutor / pjit batch sharding (fluid layer)
tensor parallel    — NamedSharding on weight matrices (mesh 'model' axis)
sequence/context   — ring_attention (ppermute ring) / ulysses (all-to-all)
pipeline           — GPipe schedule over the 'pipe' axis
expert parallel    — moe_ffn_sharded (top-1 dispatch, all_to_all)
multi-host         — distributed.init_collective (jax.distributed bootstrap)
"""

from .mesh import (make_mesh, data_parallel_mesh, local_device_count,
                   DATA_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS, EXPERT_AXIS)
from .ring_attention import (ring_attention, ring_attention_sharded,
                             local_attention)
from .ulysses import ulysses_attention, ulysses_attention_sharded
from .pipeline import pipeline_apply, pipeline_sharded
from .sharded_embedding import shard_table, sharded_lookup
from .moe import moe_ffn, moe_ffn_sharded, top1_dispatch

__all__ = [
    "shard_table", "sharded_lookup",
    "make_mesh", "data_parallel_mesh", "local_device_count",
    "DATA_AXIS", "MODEL_AXIS", "SEQ_AXIS", "PIPE_AXIS", "EXPERT_AXIS",
    "ring_attention", "ring_attention_sharded", "local_attention",
    "ulysses_attention", "ulysses_attention_sharded",
    "pipeline_apply", "pipeline_sharded",
    "moe_ffn", "moe_ffn_sharded", "top1_dispatch",
]
