"""Mesh-sharded embedding tables (model-parallel lookup).

Reference analogue: the distributed lookup table (SURVEY §2.10 row
"Model/embedding sharding") — rows hashed across pservers with
prefetch_op/split_ids/merge_ids (transpiler distribute_lookup_table
path). The parameter-server realization lives in ops/distributed_ops.py
(prefetch / sparse_table_push); THIS module is the collective (TPU-
native) realization: the table is row-sharded over a mesh axis with
jax.sharding, the lookup runs fully on-device, and XLA inserts the
all-reduce over ICI.

Design: shard rows round-robin-by-block over axis `model`
(NamedSharding P("model", None)); each device gathers its local rows
with out-of-range ids masked to zero contribution, and a psum over the
axis assembles full rows — the same math as the reference's
split_ids -> per-shard lookup -> merge_ids, but compiled into one
collective. Gradients reverse through the same path (scatter-add of the
psum cotangent back onto the owning shard), matching the sparse-grad
semantics of the distributed table.
"""

import numpy as np

__all__ = ["shard_table", "sharded_lookup"]


def shard_table(table, mesh, axis="model"):
    """Place a [V, D] table with rows sharded over `axis` (replicated on
    every other mesh axis). V must divide evenly; pad the vocab up like
    the reference's block-sliced tables otherwise."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    n = mesh.shape[axis]
    if table.shape[0] % n != 0:
        raise ValueError(
            "vocab %d not divisible by %s axis size %d — pad the table"
            % (table.shape[0], axis, n))
    return jax.device_put(table, NamedSharding(mesh, P(axis, None)))


def sharded_lookup(table, ids, mesh, axis="model"):
    """Gather rows of a sharded table: [*, D] rows for integer `ids`.

    Runs under shard_map on `axis`: each shard gathers its local rows
    (non-local ids clamp and zero out), then one psum assembles full
    rows. Differentiable — the vjp scatter-adds back onto the owning
    shard only."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from .mesh import get_shard_map
    shard_map = get_shard_map()

    n = mesh.shape[axis]
    V = table.shape[0]
    rows_per = V // n

    def local(tbl, idv):
        # shard index along `axis` (block-sliced rows: shard s owns
        # [s*rows_per, (s+1)*rows_per))
        s = jax.lax.axis_index(axis)
        lo = s * rows_per
        local_idx = idv - lo
        mine = (local_idx >= 0) & (local_idx < rows_per)
        picked = jnp.take(tbl, jnp.clip(local_idx, 0, rows_per - 1),
                          axis=0)
        picked = picked * mine[..., None].astype(picked.dtype)
        return jax.lax.psum(picked, axis)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), P(*([None] * ids.ndim))),
        out_specs=P(*([None] * ids.ndim), None))(
            table, ids.astype(np.int32))
