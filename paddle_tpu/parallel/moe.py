"""Expert parallelism — Mixture-of-Experts dispatch over a mesh axis.

No reference analogue (SURVEY.md §2.10: expert parallelism absent in the
2018 codebase); TPU-first per the task charter, completing the
parallelism matrix alongside ring attention (cp), Ulysses (sp), pipeline
(pp), and the mesh-sharded ParallelExecutor (dp/tp).

Design (the standard TPU MoE recipe, scaling-book style): experts shard
one-per-group over the `expert` mesh axis. Tokens route top-1 by a
learned gate; dispatch is a capacity-bounded one-hot einsum to
[E, C, D] slots, an all_to_all moves each expert's slots onto its
device, the expert FFN runs as one batched matmul pair, and a second
all_to_all + combine einsum returns outputs to token order, scaled by
the gate probability. Static shapes throughout: overflow beyond
capacity drops (standard top-1 semantics), masked tokens contribute
zero.
"""

import numpy as np

__all__ = ["moe_ffn", "moe_ffn_sharded", "top1_dispatch"]


def top1_dispatch(gate_logits, num_experts, capacity):
    """Top-1 routing tensors from [T, E] gate logits.

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] prob-weighted,
    probs [T, E]). Position within an expert's capacity is the token's
    rank among that expert's tokens; tokens past capacity drop."""
    import jax
    import jax.numpy as jnp

    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                 # [T]
    # rank bookkeeping in int32: a bf16 cumsum of ones saturates past 256
    # and collides capacity slots
    onehot_i = jax.nn.one_hot(expert, num_experts,
                              dtype=jnp.int32)          # [T, E]
    pos = jnp.cumsum(onehot_i, axis=0) * onehot_i - onehot_i
    keep = ((pos < capacity) & (onehot_i > 0))
    pos_oh = jax.nn.one_hot(pos, capacity, dtype=gate_logits.dtype)
    dispatch = keep[..., None].astype(gate_logits.dtype) * pos_oh
    onehot = onehot_i.astype(gate_logits.dtype)
    gate_p = jnp.sum(probs * onehot, axis=-1, keepdims=True)  # [T, 1]
    combine = dispatch * gate_p[..., None]
    return dispatch, combine, probs


def moe_ffn(x, gate_w, w_in, w_out, axis_name, capacity_factor=1.25):
    """Per-shard body (inside shard_map over the `expert` axis).

    x: token-sharded [T_loc, D]; gate_w [D, E] replicated;
    w_in [E_loc, D, F], w_out [E_loc, F, D] expert-sharded (E_loc =
    E / n). Returns [T_loc, D]."""
    import jax
    import jax.numpy as jnp

    n = jax.lax.psum(1, axis_name)
    T_loc, D = x.shape
    E_loc = w_in.shape[0]
    E = E_loc * n
    capacity = int(np.ceil(capacity_factor * T_loc / E)) or 1

    dispatch, combine, _ = top1_dispatch(x @ gate_w, E, capacity)
    # gather slots: [T, E, C] x [T, D] -> [E, C, D]
    slots = jnp.einsum("tec,td->ecd", dispatch, x)
    # all_to_all (tiled=False removes split_axis and inserts the
    # received-from axis at concat_axis): [n, E_loc, C, D] block-major
    # -> device d holds its experts' slots from every source shard as
    # [E_loc, n, C, D]
    slots = slots.reshape(n, E_loc, capacity, D)
    slots = jax.lax.all_to_all(slots, axis_name, split_axis=0,
                               concat_axis=1, tiled=False)
    slots = slots.reshape(E_loc, n * capacity, D)
    # expert FFN: batched matmuls on the MXU
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", slots, w_in))
    y = jnp.einsum("ecf,efd->ecd", h, w_out)
    # return trip: [E_loc, n, C, D] -> send source-shard s its block ->
    # [n, E_loc, C, D] where axis 0 is the expert-block (device) index,
    # i.e. expert-major [E, C, D] after reshape
    y = y.reshape(E_loc, n, capacity, D)
    y = jax.lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                           tiled=False)
    y = y.reshape(E, capacity, D)
    return jnp.einsum("tec,ecd->td", combine, y)


def moe_ffn_sharded(x, gate_w, w_in, w_out, mesh, axis_name="expert",
                    capacity_factor=1.25):
    """Global entry: x [T, D] token-sharded over `axis_name`; w_in/w_out
    [E, D, F]/[E, F, D] expert-sharded; gate replicated. One shard_map
    over the mesh — XLA lowers the two all_to_alls onto ICI."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .mesh import get_shard_map

    shard_map = get_shard_map()
    fn = shard_map(
        lambda xs, gw, wi, wo: moe_ffn(xs, gw, wi, wo, axis_name,
                                       capacity_factor),
        mesh=mesh,
        in_specs=(P(axis_name), P(), P(axis_name), P(axis_name)),
        out_specs=P(axis_name))
    return fn(x, gate_w, w_in, w_out)
