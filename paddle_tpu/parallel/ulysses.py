"""Ulysses-style sequence parallelism — all-to-all head/sequence resharding.

No reference analogue (SURVEY.md §2.10: sequence parallelism absent in the
2018 codebase); TPU-first per the task charter. The DeepSpeed-Ulysses
scheme: activations arrive sequence-sharded [B, S/n, H, D]; an all-to-all
over ICI reshards to head-sharded [B, S, H/n, D] so every device computes
exact full-sequence attention for its head group; a second all-to-all
restores sequence sharding. Two all-to-alls replace ring attention's n
ppermute hops — better when H >= n and ICI bisection bandwidth is plentiful.
"""

import functools

import numpy as np

__all__ = ["ulysses_attention", "ulysses_attention_sharded",
           "seq_to_heads", "heads_to_seq"]


def seq_to_heads(x, axis_name):
    """[B, S/n, H, D] -> all_to_all -> [B, S, H/n, D]: trade the local
    sequence shard for full sequence over a local head group.  Pure
    data movement (exact) — also the reshard the tensor-parallel
    prefill path uses to land sequence-parallel K/V in the
    head-sharded slot cache (inference/decode.py)."""
    import jax
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def heads_to_seq(x, axis_name):
    """Inverse of `seq_to_heads`: [B, S, H/n, D] -> [B, S/n, H, D]."""
    import jax
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None):
    """Per-shard body (inside shard_map): q,k,v local [B, S_loc, H, D] with
    H divisible by the axis size. Returns local [B, S_loc, H, D]."""
    import jax
    import jax.numpy as jnp
    from .ring_attention import local_attention

    n = jax.lax.psum(1, axis_name)
    B, S_loc, H, D = q.shape

    qh = seq_to_heads(q, axis_name)      # [B, S, H/n, D]
    kh = seq_to_heads(k, axis_name)
    vh = seq_to_heads(v, axis_name)
    from ..flags import FLAGS
    if FLAGS.ring_use_flash:
        # after the reshard every device holds FULL sequences for its
        # head group — exactly the tuned flash kernel's shape (it falls
        # back to local_attention itself when S doesn't tile)
        from ..ops.pallas_kernels import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        out = local_attention(qh, kh, vh, causal=causal, q_offset=0,
                              k_offset=0, scale=scale)
    return heads_to_seq(out, axis_name)


def ulysses_attention_sharded(q, k, v, mesh, axis_name="seq", causal=False,
                              scale=None):
    """q,k,v GLOBAL [B, S, H, D]; S sharded over `axis_name` in/out."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .mesh import shard_map_no_rep_check

    spec = P(None, axis_name, None, None)
    fn = shard_map_no_rep_check(
        functools.partial(ulysses_attention, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
