"""Typed runtime flag registry with environment ingestion.

Reference analogue: the gflags config surface — 87 ``DEFINE_*`` across
fluid (e.g. ``fraction_of_gpu_memory_to_use`` platform/gpu_info.cc:22,
``use_mkldnn`` framework/executor.cc:28, allocator strategy
allocation/allocator_strategy.h:21) re-exported to Python through a curated
env-flag allowlist at import (python/paddle/fluid/__init__.py:114-134
``read_env_flags`` -> ``core.init_gflags``).

TPU redesign: one typed registry. A flag is declared with DEFINE_*; at
import, ``PADDLE_TPU_FLAGS_<name>`` (or reference-style ``FLAGS_<name>``)
environment variables override defaults; at runtime ``set_flags`` /
``get_flags`` mirror the modern fluid API. Flags may register an on-change
callback for live wiring (e.g. AMP). Flags whose reference meaning is owned
by XLA on TPU (allocator sizing, per-op GC) are kept as documented
advisory knobs so reference configs keep loading.
"""

import os

__all__ = ["DEFINE_bool", "DEFINE_int", "DEFINE_float", "DEFINE_string",
           "FLAGS", "set_flags", "get_flags", "flag_info"]

_TRUE = frozenset(["1", "true", "yes", "on"])
_FALSE = frozenset(["0", "false", "no", "off", ""])


class _FlagDef:
    __slots__ = ("name", "type", "default", "help", "on_change", "value")

    def __init__(self, name, type_, default, help_, on_change=None):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_
        self.on_change = on_change
        self.value = default


_DEFS = {}


class _Flags:
    """Attribute access mirror of the registry: ``FLAGS.check_nan_inf``."""

    def __getattr__(self, name):
        d = _DEFS.get(name)
        if d is None:
            raise AttributeError("unknown flag %r" % name)
        return d.value

    def __setattr__(self, name, value):
        set_flags({name: value})


FLAGS = _Flags()


def _coerce(d, value):
    if d.type is bool:
        if isinstance(value, str):
            lv = value.strip().lower()
            if lv in _TRUE:
                return True
            if lv in _FALSE:
                return False
            raise ValueError("flag %s: cannot parse %r as bool"
                             % (d.name, value))
        return bool(value)
    return d.type(value)


def _env_override(d):
    for key in ("PADDLE_TPU_FLAGS_" + d.name, "FLAGS_" + d.name):
        if key in os.environ:
            return os.environ[key]
    return None


def _define(name, type_, default, help_, on_change=None):
    d = _FlagDef(name, type_, default, help_, on_change)
    _DEFS[name] = d
    raw = _env_override(d)
    if raw is not None:
        set_flags({name: raw})
    return d


def DEFINE_bool(name, default, help_="", on_change=None):
    return _define(name, bool, default, help_, on_change)


def DEFINE_int(name, default, help_="", on_change=None):
    return _define(name, int, default, help_, on_change)


def DEFINE_float(name, default, help_="", on_change=None):
    return _define(name, float, default, help_, on_change)


def DEFINE_string(name, default, help_="", on_change=None):
    return _define(name, str, default, help_, on_change)


def set_flags(flags_dict):
    """Set one or more flags (modern fluid API: fluid.set_flags)."""
    for name, value in flags_dict.items():
        d = _DEFS.get(name)
        if d is None:
            raise KeyError(
                "unknown flag %r; known flags: %s"
                % (name, ", ".join(sorted(_DEFS))))
        new = _coerce(d, value)
        old, d.value = d.value, new
        if d.on_change is not None and new != old:
            d.on_change(new)


def get_flags(names):
    """Read flags by name (str or list of str) -> dict."""
    if isinstance(names, str):
        names = [names]
    return {n: _DEFS[n].value for n in names}


def flag_info():
    """name -> (type, default, current, help) for documentation/tests."""
    return {n: (d.type.__name__, d.default, d.value, d.help)
            for n, d in sorted(_DEFS.items())}


# ---------------------------------------------------------------------------
# built-in flag definitions (the curated allowlist)
# ---------------------------------------------------------------------------

def _amp_changed(v):
    from .ops import registry
    registry.set_amp(v)


DEFINE_bool(
    "check_nan_inf", False,
    "Re-check every op output for NaN/Inf and NAME the first offending op "
    "(reference FLAGS_check_nan_inf, framework/operator.cc:29). Forces "
    "eager per-op execution — a debugging mode with per-op dispatch cost, "
    "exactly like the reference's per-op re-check + sync.")
DEFINE_bool(
    "benchmark", False,
    "Synchronize after every executor step and make timing honest "
    "(reference FLAGS_benchmark forced per-op device sync, scope.cc:25).")
DEFINE_bool(
    "use_bf16_amp", False,
    "bf16 automatic mixed precision: MXU-native bf16 matmuls/convs with "
    "fp32 master weights (the TPU analogue of the reference's fp16 "
    "data-transform story).", on_change=_amp_changed)
DEFINE_bool(
    "whole_graph_ad", False,
    "Serve a program's backward section with ONE jax.vjp over the whole "
    "forward region instead of per-op stashed vjps, when the program shape "
    "allows it (straight-line forward, generic grads only). Enables real "
    "jax.checkpoint rematerialization via FLAGS.remat_policy.")
DEFINE_string(
    "remat_policy", "",
    "Rematerialization policy for whole_graph_ad: '' (save everything), "
    "'conv_out' (keep conv outputs, recompute BN/activation tails — "
    "ROOFLINE.md's remat lever), 'dots', or 'nothing'.")
DEFINE_int(
    "fuse_bottleneck_max_width", 0,
    "FuseBottleneckPass fuses only bottlenecks whose width F (the 3x3 "
    "conv's channel count) is <= this; 0 (default) disables the pass. "
    "The r05 chip measurements set this default: standalone, the Pallas "
    "kernel beats XLA at F=64 (+12%) and F=128 (tune_bottleneck stages, "
    "BENCH_recovery_r05.json), but IN-GRAPH the custom-call boundary "
    "around each fused block costs more than the kernel saves — "
    "end-to-end ResNet-50 serving measured slower at every gate "
    "(F<=128, 7 blocks: 1354 vs 1599 img/s; F<=64, 3 blocks: 1526 vs "
    "1584; fuse-all was worst). Set a width to opt in for experiments.")
DEFINE_int(
    "flash_block_q", 0,
    "Flash-attention forward q-block edge; 0 (default) resolves per shape "
    "via the tune cache (FLAGS.attention_tune_cache) then the MXU-aligned "
    "heuristic (ops/attention_tuning.py). Nonzero overrides both — the "
    "process-wide expert knob; per-call block args override even this.")
DEFINE_int(
    "flash_block_kv", 0,
    "Flash-attention forward k/v-block edge; 0 = auto (see flash_block_q).")
DEFINE_int(
    "flash_block_q_bwd", 0,
    "Flash-attention backward (dq/dkv kernels) q-block edge; 0 = auto.")
DEFINE_int(
    "flash_block_kv_bwd", 0,
    "Flash-attention backward (dq/dkv kernels) k/v-block edge; 0 = auto.")
DEFINE_string(
    "attention_tune_cache", "",
    "Path of the flash-attention shape->block-config tune cache written "
    "by `tools/bench_attention.py --tune` and consulted at trace time; "
    "empty means <repo>/tools/attention_tune_cache.json.")
DEFINE_bool(
    "ring_use_flash", True,
    "Ring attention (parallel/ring_attention.py) computes each hop's "
    "block with the tuned Pallas flash kernel and merges hops by "
    "logsumexp, instead of the plain-XLA online-softmax update. The "
    "kernel path never materializes the [S_loc, S_loc] score tile; "
    "disable to A/B against the composition the r5 numbers were "
    "recorded on.")
DEFINE_int(
    "roi_align_adaptive_cap", 8,
    "roi_align adaptive-grid cap (sampling_ratio <= 0): the reference's "
    "per-roi ceil(roi_h/ph) x ceil(roi_w/pw) sample grid is emulated "
    "under static shapes by evaluating a [cap, cap] grid and masking; a "
    "roi needing more samples per bin degrades to a cap x cap uniform "
    "subsample (a one-time warning fires when eager inputs actually "
    "clip). Raise for detection heads pooling very large rois; cost is "
    "quadratic in the cap.")
DEFINE_bool(
    "cpu_deterministic", False,
    "Prefer deterministic reduction order (reference FLAGS_cpu_deterministic, "
    "python/paddle/fluid/__init__.py:123). Advisory on TPU: XLA reductions "
    "are deterministic for a fixed compilation.")
DEFINE_string(
    "profiler_path", "/tmp/paddle_tpu_profile",
    "Default trace output directory for fluid.profiler "
    "(reference profiler proto path).")
DEFINE_float(
    "eager_delete_tensor_gb", -1.0,
    "Reference GC threshold (executor.cc eager deletion). Advisory: XLA "
    "owns device memory; buffer lifetime ends with the computation.")
DEFINE_float(
    "fraction_of_gpu_memory_to_use", 0.92,
    "Reference gpu_info.cc:22. Advisory on TPU (XLA preallocates HBM); "
    "honored for CPU client via XLA_PYTHON_CLIENT_MEM_FRACTION when set "
    "before first device use.")
DEFINE_int(
    "paddle_num_threads", 1,
    "Reference inter-op CPU threads. Advisory: XLA owns scheduling.")
DEFINE_float(
    "rpc_deadline", 180.0,
    "Parameter-server RPC timeout in seconds (reference FLAGS_rpc_deadline).")
DEFINE_int(
    "rpc_retry_times", 5,
    "Attempts for the jittered-backoff retry wrappers on the distributed "
    "control plane (MasterClient._call re-dials, wait_server_ready polls, "
    "RPCClient idempotent-command reconnects). 1 disables retries.")
DEFINE_float(
    "rpc_retry_backoff", 0.05,
    "Base delay (seconds) of the retry wrappers' exponential backoff; "
    "each attempt doubles it up to 2s with +/-50% jitter so restarting "
    "peers are not stampeded (utils/retry.py RetryPolicy).")
DEFINE_bool(
    "sentinel_nan_check", False,
    "Anomaly sentinel: screen each Trainer step's fetched losses (and "
    "params with sentinel_check_params) for NaN/Inf at the step boundary "
    "— cheap, jit-preserving, unlike check_nan_inf's eager per-op mode. "
    "A bad step is reverted (immutable-array snapshot restore) and, "
    "after sentinel_max_bad_steps consecutive bad steps, the policy "
    "decides: raise, or roll back to the last-good checkpoint.")
DEFINE_string(
    "sentinel_policy", "skip",
    "What the sentinel does after sentinel_max_bad_steps consecutive "
    "non-finite steps: 'skip' raises SentinelError; 'rollback' reloads "
    "the last-good checkpoint from the Trainer's checkpoint dir and "
    "keeps training (raising only if training re-diverges right after).")
DEFINE_int(
    "sentinel_max_bad_steps", 3,
    "Consecutive non-finite steps the sentinel absorbs by skipping "
    "before escalating to its policy (K in the rollback design).")
DEFINE_bool(
    "sentinel_check_params", False,
    "Sentinel also screens every persistable (params + optimizer "
    "accumulators) each step, not just the fetched losses. Catches "
    "corruption the loss hasn't seen yet; costs a host transfer of the "
    "full state per step.")
DEFINE_float(
    "step_watchdog_secs", 0.0,
    "Wall-clock watchdog on each Executor.run/run_loop dispatch: the "
    "device computation runs on a worker thread and a step exceeding "
    "this many seconds raises StepWatchdogTimeout instead of blocking "
    "forever (generalizes bench.py's subprocess wedge-probe — the r03 "
    "TPU transport outage hung jax inside C, unkillable from Python). "
    "0 disables; enabling forces a block_until_ready per step, so this "
    "is a hang-detection mode, not a fast path.")
DEFINE_int(
    "async_dispatch_depth", 0,
    "Asynchronous step dispatch: the Trainer (and the bench harnesses) "
    "keep up to this many steps' fetches in flight as live device "
    "arrays (Executor.run(as_future=True) -> FetchFuture) and resolve "
    "them at the pipeline tail with one batched jax.device_get each — "
    "loss bookkeeping, sentinel NaN/Inf screening and event callbacks "
    "lag dispatch by <= depth steps (PIPELINE.md). 0 (default) keeps "
    "the fully synchronous per-step behavior. The async trajectory is "
    "bit-exact vs sync on finite runs (same RNG step folds, same "
    "donation discipline); after a non-finite step the sentinel's "
    "recovery re-dispatches the in-flight batches from the reverted "
    "state, so post-anomaly trajectories legitimately differ.")
DEFINE_int(
    "reader_prefetch_depth", 0,
    "Device prefetch queue depth for the Trainer's reader path "
    "(reader.prefetch_to_device): a bounded background thread runs "
    "prepare_feeds + the device_put for the NEXT batch while the "
    "current step computes — the double_buffer/py_reader infeed "
    "overlap (operators/reader/buffered_reader.cc). 0 (default) feeds "
    "on the main thread each step.")
DEFINE_float(
    "serving_batch_deadline_ms", 5.0,
    "Serving micro-batcher coalescing window: after the first request of "
    "a dispatch group arrives, wait at most this many milliseconds for "
    "more compatible requests before dispatching (paddle_tpu/serving/"
    "batcher.py). 0 dispatches immediately — no cross-request batching "
    "beyond what is already queued.")
DEFINE_int(
    "serving_max_queue", 256,
    "Serving admission control: maximum requests waiting in a model's "
    "batcher queue. A submit beyond this depth is shed with an explicit "
    "ServerOverloaded instead of growing an unbounded backlog "
    "(shed-not-hang; see SERVING.md overload semantics).")
DEFINE_int(
    "serving_workers", 1,
    "Dispatch worker threads per replica execution lane: each worker "
    "takes one coalesced micro-batch group off its lane and runs it on "
    "that lane's replica; >1 allows overlapping micro-batches of the "
    "same replica (useful when the runner releases the GIL during XLA "
    "execution).")
DEFINE_string(
    "serving_replicas", "1",
    "Default replica placement spec for served models (SERVING.md "
    "multi-chip serving): an integer N places N device-resident replicas "
    "round-robin over the local devices (1 keeps the single default-"
    "device replica — the pre-multichip behavior); 'auto' places one "
    "replica per local device; an explicit comma list names devices "
    "('0,2' = local device indices, 'cpu:0,tpu:3' = platform:index). "
    "Mesh replicas (SERVING.md 'Mesh replicas'): 'mesh:2' or 'mesh:2x2' "
    "packs the whole host into device meshes of that size, one replica "
    "per mesh (params + KV cache sharded across the members, replies "
    "bit-exact vs a single-device replica); '+' inside a comma list "
    "builds one mesh replica from named members ('tpu:0+tpu:1,"
    "tpu:2+tpu:3'); a member may not repeat across replicas. "
    "Each replica's params live on its device (or mesh) and its batch "
    "buckets compile and warm there; a router assigns each coalesced "
    "micro-batch group to the least-loaded replica.")
DEFINE_int(
    "serving_lane_depth", 1,
    "Per-replica dispatch lane bound: at most this many coalesced "
    "groups wait behind each replica's in-flight dispatches. When every "
    "lane is full the router holds the next group (sticky back-"
    "pressure), the admission queue fills, and submits shed with "
    "ServerOverloaded — overload still sheds at the front instead of "
    "queueing unboundedly behind slow replicas.")
DEFINE_int(
    "serving_device_mem_mb", 0,
    "Per-replica device memory budget (MiB) for the serving admission "
    "fit check (ANALYSIS.md resource analysis): load_model statically "
    "estimates each replica's peak HBM (params + activation peak + "
    "decode KV slot table) and rejects an un-fittable placement with a "
    "ResourceFitError BEFORE any build/warm work — naming the "
    "estimated and available bytes. 0 (default) resolves the budget "
    "from the device itself (memory_stats bytes_limit, else the known "
    "TPU HBM capacity table); on CPU with no configured budget the "
    "check passes trivially.")
DEFINE_int(
    "serving_decode_slots", 8,
    "Slot-table size of each replica's decode lane (SERVING.md "
    "continuous batching): the fixed-shape decode step XLA compiles "
    "once runs over this many KV-cache slots per lane, so it is also "
    "the per-replica cap on concurrently generating requests. A new "
    "request joins the RUNNING decode batch the step after any slot "
    "frees (EOS / max-tokens / deadline / disconnect) — no coalesce "
    "window. Larger tables raise aggregate tokens/sec under load at "
    "the cost of KV-cache HBM (slots x max_seq_len x layers).")
DEFINE_int(
    "serving_max_new_tokens", 128,
    "Default generation budget per streaming request: a decode slot is "
    "reclaimed after this many generated tokens when the request does "
    "not set its own max_new_tokens (which is still clamped to this "
    "server-side ceiling — one runaway prompt must not pin a slot "
    "forever).")
DEFINE_int(
    "serving_stream_chunk_tokens", 1,
    "Streaming reply granularity: the server flushes a token-delta "
    "frame to the client every this many generated tokens (and always "
    "at end of stream). 1 streams every token as it decodes; larger "
    "values trade time-to-token for fewer wire frames.")
DEFINE_int(
    "serving_decode_fuse_steps", 1,
    "Fused multi-step decode window (SERVING.md \"Fused multi-step "
    "decode\"): each decode lane dispatch compiles up to this many "
    "decode steps as ONE device executable (a lax.while_loop with "
    "in-graph early exit), so one host round-trip emits up to N "
    "tokens per slot — the host-dispatch-amortization lever at real "
    "silicon step costs. Slot joins/leaves/deadline evictions move "
    "to window boundaries (a per-lane step-time EWMA clamps trips so "
    "deadlines overshoot by at most ~one dispatch); streams stay "
    "bit-identical to N=1 token-for-token. Spec lanes fuse the whole "
    "draft+verify round into one dispatch instead. 1 (default) keeps "
    "the classic one-dispatch-per-token loop. Per-load override: "
    "load_model(fuse_steps=...).")
DEFINE_string(
    "serving_kv_cache_dtype", "",
    "Default KV-cache numerics for decode artifacts that do not pin "
    "one in decode_meta (QUANTIZE.md \"Quantized KV cache\"): '' or "
    "'fp32'/'float32' keeps the fp32 slot table; 'int8' stores K/V "
    "slots as int8 with per-(layer,head) fp32 scales — ~0.25x cache "
    "bytes per slot, greedy streams bit-stable against themselves. "
    "Per-load override: load_model(kv_cache_dtype=...).")
DEFINE_bool(
    "mesh_tp", False,
    "Tensor-parallel mesh compute (SERVING.md \"Tensor-parallel "
    "compute\"): a mesh replica's decode program lowers as ONE "
    "shard_map'd executable over the replica's MeshGroup — fc/mul "
    "weights in Megatron column->row pairs with one psum per pair, "
    "attention head-parallel with the decode kernel running per member "
    "on its resident KV shard (int8 scales slice along heads too), "
    "embedding row-sharded over vocab — so params and KV never "
    "materialize unsharded and per-step HBM traffic per member drops "
    "~1/mesh_size (the decode-roofline win, ROOFLINE.md). Streams stay "
    "top-1 identical to a single-device replica; activations carry "
    "psum-reduction-order noise at float tolerance where a matmul is "
    "row-split (documented contract, tests/test_mesh_tp.py). False "
    "(default) keeps PR 18's shard-at-rest gather path — bit-exact by "
    "construction. Read at predictor build time: registry fault-in / "
    "hot-swap rebuilds pick up a flip.")
DEFINE_int(
    "mesh_tp_prefill_seq", 128,
    "Minimum prompt bucket for sequence-parallel TP prefill: at or "
    "above this bucket (and when the bucket divides the mesh), prefill "
    "shards the SEQUENCE axis across members ulysses-style (all_to_all "
    "into head-parallel attention, parallel/ulysses.py) with per-layer "
    "weight all_gathers amortized over the long prompt — bit-exact vs "
    "the single-device oracle because every position's math runs with "
    "full weights. Below it, prefill runs head/column-parallel like "
    "decode (top-1 contract). Only read when FLAGS.mesh_tp is on.")
DEFINE_int(
    "serving_spec_k", 4,
    "Speculative-decoding draft depth (SERVING.md): when a decode "
    "model is loaded WITH a draft artifact (load_model(draft=...) or "
    "FLAGS.serving_spec_draft), each round the draft proposes this "
    "many tokens and the fp32 target verifies all k+1 positions in one "
    "fixed-shape batched step; the longest greedily-agreeing prefix "
    "commits, so slots advance 1..k+1 tokens per target step while the "
    "stream stays bit-identical to target-only decode. Only meaningful "
    "with a draft configured; < 1 disables speculation outright.")
DEFINE_string(
    "serving_spec_draft", "",
    "Default draft artifact directory for speculative decoding: a "
    "decode artifact sharing the target's vocab/eos (canonically the "
    "int8 twin of the same model — QUANTIZE.md, the int8 lane's second "
    "job). Every decode load_model without an explicit draft= uses it; "
    "empty (default) serves decode models without speculation. The "
    "draft is fit-checked by the ANALYSIS.md admission gate alongside "
    "the target (both KV slot tables count).")
DEFINE_bool(
    "compile_cache", True,
    "Persistent compile/artifact cache (COMPILE_CACHE.md): Predictor "
    "AOT bucket compiles are keyed by a content fingerprint (program "
    "hash, feed/state shapes+dtypes, device kind, jax+lib versions) and "
    "their serialized jax.export executables committed to the on-disk "
    "store with the checkpoint vault's write-temp->fsync->rename "
    "discipline, so a later server boot or hot-swap flip of the same "
    "(model, bucket, device-kind) deserializes instead of re-tracing "
    "and re-compiling. jax's own persistent XLA-executable cache is "
    "pointed at <store>/xla so the XLA compile is a disk hit too. "
    "Corrupt/truncated entries are silently recompiled; disable to "
    "force fresh compilation everywhere.")
DEFINE_string(
    "compile_cache_dir", "",
    "Root directory of the persistent compile cache + kernel-tuning "
    "registry; empty means $XDG_CACHE_HOME/paddle_tpu "
    "(~/.cache/paddle_tpu). The store is cross-process shared: every "
    "commit is atomic and readers verify CRC32s, so concurrent servers "
    "and a killed writer cannot poison each other.")
DEFINE_int(
    "compile_cache_max_mb", 1024,
    "Size cap (MiB) of the compile cache store; a put past the cap "
    "evicts least-recently-used entries (manifest mtime, touched on "
    "every hit) across both the AOT entries and jax's xla/ files. The "
    "entry just written is never the victim.")
DEFINE_int(
    "quantize_min_weight_elems", 1024,
    "PTQ size floor (inference/quantize.py): a weight with fewer "
    "elements than this stays fp32 — biases, norm scales and small "
    "embeddings are not worth the dequant plumbing (their bytes are "
    "noise on the HBM roofline) and are the numerically riskiest to "
    "quantize. Applies to mul/conv filters and embedding tables alike.")
DEFINE_int(
    "quantize_calib_batches", 4,
    "How many user-supplied calibration batches the PTQ pass consumes "
    "(inference/quantize.py): per-channel int8 scales start at absmax "
    "and a small clip-ratio search refines them against the calibration "
    "activations (fc layers) or the weight-quantization MSE (conv); "
    "extra batches beyond this are ignored so a big feed list cannot "
    "turn quantization into a training run.")
DEFINE_bool(
    "verify_program", False,
    "Pre-run program verification (ANALYSIS.md): before an Executor / "
    "ParallelExecutor compiles a program (or a Predictor loads one), run "
    "the static analysis passes — use-before-def, shape/dtype "
    "propagation, dead-op and fetch-reachability, AOT-exportability — "
    "and raise ProgramVerificationError on error findings instead of "
    "letting the bug surface as a runtime backend trace N steps in. "
    "Memoized per (program version, feeds, fetches): the check runs at "
    "build/load, never per step, so the hot path cost is one dict hit. "
    "The save_inference_model / load_inference_model artifact "
    "boundaries verify unconditionally — this flag adds the in-process "
    "executor surfaces.")
DEFINE_bool(
    "executor_compile_cache", False,
    "Opt-in: Executor.run also consults the persistent compile cache "
    "for INFERENCE-SHAPED programs (single block, no *_grad ops, no "
    "optimizer ops, no host ops) whose fingerprint is derivable from "
    "the Program serialization. Off by default: training steps donate "
    "buffers and change shape rarely, so the win is serving-side; "
    "enable for executor-driven batch inference over a fixed program.")
def _trace_changed(v):
    from .obs import tracing
    tracing.configure(enabled=v)


def _trace_buffer_changed(v):
    from .obs import tracing
    tracing.configure(capacity=v)


def _event_log_changed(v):
    from .obs import events
    events.configure(path=v)


def _event_log_max_changed(v):
    from .obs import events
    events.configure(max_kb=v)


def _flight_changed(v):
    from .obs import flightrec
    flightrec.configure()


# NOTE: companion flags (buffer size / rotation cap) are defined BEFORE
# the flags whose on_change hooks read them, so an env override firing
# mid-import finds them registered.
DEFINE_int(
    "trace_buffer_events", 4096,
    "Capacity of the obs span ring buffer (paddle_tpu/obs/tracing.py): "
    "completed spans land in a fixed-size ring; the oldest fall off "
    "silently under load (the drop count rides the metrics surface). "
    "Sized so the slowest recent requests/steps tools/trace_top.py "
    "prints are always resolvable; memory cost is ~200 bytes/span.",
    on_change=_trace_buffer_changed)
DEFINE_float(
    "trace_slow_ms", 0.0,
    "Slow-request/step log gate: a serving request (root span) or train "
    "step whose duration exceeds this many milliseconds is also emitted "
    "as a 'slow' structured event (event log), carrying its trace_id / "
    "step id so the outlier is findable after the span ring wrapped. "
    "0 disables the slow log.")
DEFINE_bool(
    "trace", True,
    "End-to-end span tracing (OBSERVABILITY.md): serving requests get "
    "per-stage spans (admission, queue wait, coalesce, lane routing, "
    "device compute, reply scatter) under a reply-visible trace_id; "
    "training steps get prefetch_wait/dispatch/drain/ckpt spans. "
    "Overhead is pinned <3% on the bench smoke lanes (BENCH_r09.json); "
    "disable to make the tracer a no-op (spans, not metrics — counters "
    "keep working).", on_change=_trace_changed)
DEFINE_int(
    "event_log_max_kb", 1024,
    "Rotation threshold (KiB) of the structured event log file: past "
    "this size the file is fsynced and atomically renamed to <path>.1 "
    "(vault commit discipline — tools/chaos.py --scenario "
    "trace-overflow kills a writer mid-rotation to prove the old log "
    "survives intact).", on_change=_event_log_max_changed)
DEFINE_string(
    "event_log", "",
    "Path of the append-only JSONL structured event log "
    "(paddle_tpu/obs/events.py): discrete lifecycle events — hot-swap "
    "flips, compile-cache deltas, sentinel skips/rollbacks, sheds with "
    "priority, watchdog fires, checkpoint commits — each stamped with "
    "trace/step ids so logs, metrics and traces cross-reference. "
    "Empty (default) keeps events in the bounded in-memory ring only.",
    on_change=_event_log_changed)
DEFINE_bool(
    "slo_monitor", True,
    "Run the SLO monitor thread on every InferenceServer "
    "(paddle_tpu/obs/slo.py): samples the serving counters every "
    "slo_eval_interval_ms into a bounded time-series ring and "
    "evaluates declared SLOs (serving_slo) with Google-SRE-style "
    "multi-window burn rates into the ok/degraded/breach state "
    "machine the `health` RPC verb renders. Overhead is a counter "
    "read per model per interval (<3% pinned, BENCH_r13.json); "
    "disable only to rule the monitor out while debugging.")
DEFINE_float(
    "slo_eval_interval_ms", 1000.0,
    "SLO monitor sampling/evaluation interval in milliseconds. Each "
    "tick appends one sample per served model lane to the timeline "
    "ring (also the flight-recorder bundle's metrics timeline) and "
    "re-evaluates the burn-rate windows; detection latency for a "
    "hard breach is ~2 fast-window ticks.")
DEFINE_string(
    "serving_slo", "",
    "Declared SLOs (OBSERVABILITY.md \"SLOs & burn rates\"): "
    "semicolon-separated '[model:]key=val,key=val' declarations; no "
    "model prefix (or '*') sets the default for every model. Keys: "
    "p95_ms, ttft_p95_ms, error_rate, shed_rate, spec_accept "
    "(objectives) plus budget, fast_window, slow_window, fast_burn, "
    "slow_burn, breach_evals, recover_evals (tuning). Example: "
    "'p95_ms=250,error_rate=0.01;llm:ttft_p95_ms=400'. Empty = "
    "sample-only (timeline for the flight recorder, no evaluation).")
DEFINE_string(
    "flight_dir", "",
    "Flight-recorder bundle root (paddle_tpu/obs/flightrec.py): on "
    "trigger (watchdog_fire, sentinel giveup/rollback, slo_breach, "
    "serving thread death, manual `flight` RPC) a post-mortem bundle "
    "— spans, events, metrics, SLO timeline, all-thread stacks, "
    "resolved flags, server snapshots — is committed atomically "
    "(write-temp -> fsync -> rename, vault discipline) under this "
    "directory. Empty (default) disables the recorder.",
    on_change=_flight_changed)
DEFINE_int(
    "flight_keep", 8,
    "Keep-N rotation for flight-recorder bundles: after each commit "
    "the oldest bundles beyond this count are deleted.",
    on_change=_flight_changed)
DEFINE_float(
    "flight_cooldown_s", 30.0,
    "Per-trigger-reason cooldown (seconds) on the flight recorder: a "
    "breach storm writes ONE bundle per reason per window, not "
    "hundreds. The manual `flight` RPC bypasses it (force).",
    on_change=_flight_changed)
DEFINE_bool(
    "fleet_controller", False,
    "Run the fleet controller on every InferenceServer "
    "(paddle_tpu/serving/fleet.py, SERVING.md \"Fleet controller\"): a "
    "background loop that closes the loop from the SLO burn/queue/"
    "occupancy/shed sensors to the registry's actuators — scaling a "
    "model's replica set within its declared [min,max] policy (every "
    "resize rides the build-warm-flip hot swap, so scaling is zero-"
    "drop by construction, and the resource fit check gates every "
    "grow), paging idle-past-TTL models out to their artifact paths "
    "(they fault back in on the next request — a reload, not a "
    "recompile, under the warm compile cache), and degrading under "
    "sustained burn by shifting ab_weight toward the int8 lane BEFORE "
    "admission sheds. Off (default) keeps replica counts, residency "
    "and lane weights fully operator-driven.")
DEFINE_float(
    "fleet_eval_interval_ms", 1000.0,
    "Fleet-controller evaluation interval in milliseconds: each tick "
    "reads the per-model sensors (SLO state/burn, queue depth, slot "
    "occupancy, shed/request deltas, idle age) and decides at most a "
    "few cooldown-bounded actions. Detection-to-actuation latency for "
    "a hard breach is roughly one SLO fast window plus one tick.")
DEFINE_string(
    "fleet_policy", "",
    "Declared fleet policies (SERVING.md \"Fleet controller\"): "
    "semicolon-separated '[model:]key=val,key=val' declarations; no "
    "model prefix (or '*') sets the default for every model. Keys: "
    "min_replicas, max_replicas (the scale range; max_replicas=1 "
    "disables scaling), page_ttl_s (idle seconds before a model pages "
    "out to its artifact path; 0 never pages), scale_up_queue (queued "
    "requests per live replica that trigger a grow), "
    "scale_down_idle_s, degrade_weight (the int8 lane's ab share "
    "under sustained burn), restore_evals (clean ticks before the "
    "weight restores — hysteresis), scale_cooldown_s, page_cooldown_s, "
    "degrade_cooldown_s. Example: 'max_replicas=4;llm:page_ttl_s=600,"
    "scale_up_queue=8'. Empty = observe-only (no policy, no actions).")
DEFINE_bool(
    "fleet_dry_run", False,
    "Fleet-controller dry-run: every tick still senses and decides, "
    "and every decision is logged as a fleet_decision event with its "
    "triggering signal, but NO action touches the registry — replica "
    "counts, residency and ab weights stay untouched. The rehearsal "
    "mode for a new policy spec against live traffic.")
DEFINE_string(
    "federation_frontend", "",
    "Federation frontend endpoint HOST:PORT (SERVING.md \"Federated "
    "serving\"): when set, every InferenceServer registers a "
    "membership lease with that front-door router at start, "
    "heartbeats its resident-model/queue payload, and deregisters on "
    "shutdown — the server becomes a BACKEND the frontend places "
    "traffic onto. Empty (default) keeps the server standalone. An "
    "InferenceServer(federation=...) argument overrides per server.")
DEFINE_float(
    "federation_ttl_s", 3.0,
    "Membership lease TTL in seconds (paddle_tpu/federation/"
    "membership.py): a backend whose heartbeat goes missing this long "
    "expires from the placement set and a backend_lost event fires. "
    "The frontend re-places subsequent traffic within one TTL of a "
    "backend death — this is the detection bound the chaos "
    "backend-kill scenario pins. Must exceed federation_heartbeat_ms "
    "with slack (3x is a sane floor: one lost beat must not flap the "
    "lease).")
DEFINE_float(
    "federation_heartbeat_ms", 1000.0,
    "Backend heartbeat interval toward the federation frontend in "
    "milliseconds. Each beat renews the lease and refreshes the "
    "serving payload the frontend places by (resident models with "
    "est_peak_mb, paged set, queue depth, accepting flag), so "
    "placement staleness is bounded by one beat.")
DEFINE_float(
    "federation_capacity_mb", 0.0,
    "Device-memory capacity this backend advertises on its lease in "
    "MB — the denominator of the global controller's placement-by-"
    "capacity signal (free = capacity - sum of resident est_peak_mb). "
    "0 (default) means unknown: the backend still serves, but "
    "capacity-aware placement treats it as last resort. An "
    "InferenceServer(capacity_mb=...) argument overrides per server.")
DEFINE_bool(
    "global_fleet", False,
    "Run the fleet-of-fleets controller on the federation frontend "
    "(paddle_tpu/federation/global_fleet.py): per-model GLOBAL "
    "replica budgets within declared [min,max] policies, placed "
    "across backends by the free-capacity signal (lease capacity_mb "
    "minus resident est_peak_mb); cold models page out cluster-wide "
    "past their idle TTL and fault back in wherever capacity lives, "
    "via the persisted lane specs the frontend records from "
    "load_model passthrough. Per-backend fleet controllers delegate "
    "their scale/page decisions to this tier while a federation link "
    "is up (degrade-before-shed stays local). Off (default) keeps "
    "cross-host placement operator-driven.")
DEFINE_string(
    "global_fleet_policy", "",
    "Global fleet policies, same grammar as fleet_policy "
    "('[model:]key=val,...;...', '*' or no prefix = default) but with "
    "min_replicas/max_replicas read as CLUSTER-WIDE totals across "
    "backends. Example: 'llm:min_replicas=2,max_replicas=8,"
    "page_ttl_s=600,scale_up_queue=8'. Empty = observe-only.")
DEFINE_float(
    "global_fleet_eval_interval_ms", 1000.0,
    "Global fleet-of-fleets evaluation interval in milliseconds: "
    "each tick senses the whole membership table (heartbeat-fed, no "
    "RPC fan-out) and decides at most a few cooldown-bounded "
    "cross-host actions.")
DEFINE_int(
    "dist_threadpool_size", 0,
    "Reference distributed thread pool size. Advisory.")
DEFINE_bool(
    "enable_rpc_profiler", False,
    "Record every parameter-server RPC as a profiler event "
    "(reference profiler.cc:33 FLAGS_enable_rpc_profiler).")
DEFINE_int(
    "while_grad_max_iters", 256,
    "Trip-count bucket for differentiating an UNBOUNDED While loop "
    "in-graph: the jit-native while gradient records per-iteration "
    "carries into a static buffer of this size. A loop still running at "
    "the cap poisons its float carries with NaN (loud failure, never a "
    "silently-truncated forward). Raise it for longer data-dependent "
    "loops; memory cost is cap x carry size.")
DEFINE_bool(
    "dynamic_while_host_grad", False,
    "Differentiate unbounded While loops via the host-path replay op "
    "(while_grad_dynamic) instead of the jit-native recorded gradient. "
    "The replay supports truly unbounded trip counts but forces the "
    "whole program onto the segmented eager path (reference "
    "while_op.cc:119 semantics).")
