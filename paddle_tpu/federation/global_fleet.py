"""Fleet-of-fleets: the PR 15 controller promoted to the global tier.

The per-server FleetController (serving/fleet.py) senses one server's
queues and actuates one server's registry.  This controller senses the
WHOLE federation — every backend's heartbeat already carries its
resident models, per-model replica counts, queue depths, request
counters and est_peak_mb (membership.py), so sensing is free: no RPC
fan-out, the lease table IS the sensor bus — and actuates ACROSS
hosts:

* **global replica budgets** — one `[min_replicas, max_replicas]`
  envelope per model counts replicas cluster-wide; scale-up places the
  next replica on the host where capacity lives (`place_by_capacity`,
  the PR 11 est_peak_mb fit/cost signal summed per lease), preferring
  a host NOT yet holding the model (spread one model's budget across
  hosts — the MLPerf TPU-pods idiom, arXiv 1909.09756); scale-down
  removes from the host holding the most.
* **cluster-wide paging** — a model idle past `page_ttl_s` everywhere
  is paged out on EVERY resident backend; demand (or this controller,
  on rising queues) faults it back in wherever capacity lives via the
  lane specs the frontend persisted from `load_model` passthrough.

The decision core (`decide_global`) is pure — seeded GlobalSensors in,
FleetAction list out — mirroring serving/fleet.py's `decide` so tests
drive it without sockets.  Policies reuse the exact `parse_fleet_spec`
grammar (`[model:]key=val,...;...`, `*` default); the per-server
controllers DELEGATE replica/paging actions to this tier when a
frontend owns them (fleet.py `delegated_to`) so the two tiers never
fight over the same knob.
"""

import threading
import time

from ..flags import FLAGS
from ..obs import events as obs_events
from ..serving.fleet import (FleetAction, _cool, parse_fleet_spec)

__all__ = ["GlobalSensors", "GlobalFleetController", "decide_global",
           "place_by_capacity"]


def place_by_capacity(leases, prefer_absent=None):
    """Pick the backend id where capacity lives: most free HBM
    (declared capacity minus the Σ est_peak_mb x replicas resident
    estimate) first; backends that declared NO capacity rank after
    every declared one, least-resident first (unknown is not
    infinite).  ``prefer_absent`` names a model — hosts not already
    holding it win ties (spread the budget across hosts).  Ties break
    on backend id: deterministic.  ``leases`` is the
    MembershipRegistry.backends() snapshot {bid: lease_dict}."""
    best_bid, best_key = None, None
    for bid in sorted(leases):
        lease = leases[bid]
        cap = float(lease.get("capacity_mb") or 0.0)
        resident = float(lease.get("resident_mb") or 0.0)
        holds = (prefer_absent is not None
                 and str(prefer_absent) in (lease.get("models") or {}))
        if cap > 0.0:
            key = (0, int(holds), -(cap - resident), bid)
        else:
            key = (1, int(holds), resident, bid)
        if best_key is None or key < best_key:
            best_bid, best_key = bid, key
    return best_bid


class GlobalSensors(object):
    """One model's CLUSTER-WIDE sensor snapshot for one tick — plain
    data so seeded instances drive ``decide_global`` in tests."""

    __slots__ = ("model", "total_replicas", "resident", "paged_on",
                 "queue_depth", "requests_delta", "idle_s",
                 "est_peak_mb")

    def __init__(self, model, total_replicas=0, resident=None,
                 paged_on=(), queue_depth=0, requests_delta=0,
                 idle_s=0.0, est_peak_mb=0.0):
        self.model = str(model)
        self.total_replicas = int(total_replicas)
        self.resident = dict(resident or {})   # bid -> replicas
        self.paged_on = sorted(paged_on or ())
        self.queue_depth = int(queue_depth)
        self.requests_delta = int(requests_delta)
        self.idle_s = float(idle_s)
        self.est_peak_mb = float(est_peak_mb)

    def to_dict(self):
        return {"model": self.model,
                "total_replicas": self.total_replicas,
                "resident": dict(self.resident),
                "paged_on": list(self.paged_on),
                "queue_depth": self.queue_depth,
                "requests_delta": self.requests_delta,
                "idle_s": round(self.idle_s, 3),
                "est_peak_mb": round(self.est_peak_mb, 3)}


def decide_global(sensors, policy, state, now):
    """Pure global decision core: cluster sensors + policy envelope +
    cooldown state -> ordered FleetAction list.  Kinds: ``fault_in``
    (paged everywhere, demand arriving), ``scale_up``/``scale_down``
    (global replica total vs the budget), ``page_out`` (idle past TTL
    everywhere).  ``state`` is read-only here; the controller stamps
    cooldowns only after an action actually executes."""
    acts = []
    if sensors is None or policy is None:
        return acts
    s = sensors
    if s.total_replicas == 0:
        # cold everywhere: demand faults it in where capacity lives
        if s.paged_on and (s.requests_delta > 0 or s.queue_depth > 0):
            acts.append(FleetAction(
                "fault_in", s.model,
                signal=dict(s.to_dict(), trigger="demand",
                            tier="global")))
        return acts
    if (s.queue_depth >= policy.scale_up_queue
            and s.total_replicas < policy.max_replicas
            and _cool(state, "last_scale_t", now,
                      policy.scale_cooldown_s)):
        acts.append(FleetAction(
            "scale_up", s.model,
            params={"to": s.total_replicas + 1},
            signal=dict(s.to_dict(), trigger="queue_depth",
                        tier="global")))
    elif (s.idle_s >= policy.scale_down_idle_s
            and s.total_replicas > policy.min_replicas
            and s.requests_delta == 0
            and _cool(state, "last_scale_t", now,
                      policy.scale_cooldown_s)):
        acts.append(FleetAction(
            "scale_down", s.model,
            params={"to": s.total_replicas - 1},
            signal=dict(s.to_dict(), trigger="idle",
                        tier="global")))
    if (policy.page_ttl_s > 0.0 and s.idle_s >= policy.page_ttl_s
            and s.requests_delta == 0 and s.queue_depth == 0
            and _cool(state, "last_page_t", now,
                      policy.page_cooldown_s)):
        acts.append(FleetAction(
            "page_out", s.model,
            signal=dict(s.to_dict(), trigger="page_ttl",
                        tier="global")))
    return acts


class GlobalFleetController(object):
    """Sense from the membership lease table, decide with the pure
    core, actuate over the wire through the frontend's per-backend
    clients.  Owned and started by FrontendServer when
    ``FLAGS.global_fleet`` is set (the `fleet` verb against the
    frontend reads/configures it)."""

    HISTORY_KEPT = 64

    def __init__(self, frontend, policies=None, eval_interval_s=None,
                 dry_run=None):
        self.frontend = frontend
        if policies is None:
            policies = parse_fleet_spec(FLAGS.global_fleet_policy)
        self.policies = dict(policies or {})
        self.eval_interval_s = (
            max(float(FLAGS.global_fleet_eval_interval_ms), 10.0)
            / 1000.0
            if eval_interval_s is None else float(eval_interval_s))
        self.dry_run = (bool(FLAGS.fleet_dry_run) if dry_run is None
                        else bool(dry_run))
        self._lock = threading.Lock()
        self._state = {}          # model -> {"last_scale_t", ...}
        self._last_requests = {}  # model -> cluster request total
        self._last_active = {}    # model -> monotonic t of last delta
        self._last_sense = {}     # model -> GlobalSensors.to_dict()
        self._acted = {}          # kind -> count
        self._ticks = 0
        self._history = []
        self._stop = threading.Event()
        self._thread = None

    # -- policy --------------------------------------------------------

    def policy_for(self, model):
        return self.policies.get(str(model)) or self.policies.get("*")

    def set_policy(self, model, spec):
        """`fleet set_policy` against the frontend: one model's (or
        ``*``'s) envelope, serving_slo grammar, replaces wholesale."""
        parsed = parse_fleet_spec(spec)
        with self._lock:
            if list(parsed) == ["*"] and str(model) != "*":
                self.policies[str(model)] = parsed["*"]
            else:
                self.policies.update(parsed)

    # -- sense ---------------------------------------------------------

    def sense(self, now=None):
        """{model: GlobalSensors} straight from the lease table — the
        heartbeats already carried every number this needs."""
        now = time.monotonic() if now is None else now
        leases = self.frontend.membership.backends()
        per = {}
        for bid, lease in leases.items():
            for name, m in (lease.get("models") or {}).items():
                s = per.setdefault(name, GlobalSensors(name))
                reps = max(int(m.get("replicas") or 1), 1)
                s.total_replicas += reps
                s.resident[bid] = reps
                s.queue_depth += int(m.get("queue_depth") or 0)
                s.est_peak_mb = max(s.est_peak_mb,
                                    float(m.get("est_peak_mb") or 0.0))
            for name in (lease.get("paged") or ()):
                s = per.setdefault(name, GlobalSensors(name))
                if bid not in s.paged_on:
                    s.paged_on = sorted(set(s.paged_on) | {bid})
        # request deltas + idle clocks from the cluster-wide totals
        totals = {}
        for lease in leases.values():
            for name, m in (lease.get("models") or {}).items():
                totals[name] = (totals.get(name, 0)
                                + int(m.get("requests") or 0))
        for name, s in per.items():
            total = totals.get(name, 0)
            prev = self._last_requests.get(name)
            delta = 0 if prev is None else max(total - prev, 0)
            self._last_requests[name] = total
            s.requests_delta = delta
            if delta > 0 or prev is None:
                self._last_active[name] = now
            s.idle_s = now - self._last_active.get(name, now)
            self._last_sense[name] = s.to_dict()
        return per

    # -- actuate -------------------------------------------------------

    def _backend_call(self, bid, msg):
        lease = self.frontend.membership.get(bid)
        if lease is None:
            raise KeyError("backend %s lost before actuation" % bid)
        cli = self.frontend._client(bid, lease["endpoint"])
        return cli.call(msg)

    def _execute(self, action, sensors):
        """One decided action, over the wire.  Placement happens HERE
        (not in decide): the lease table may have changed since the
        decision, so the capacity ranking reads a fresh snapshot."""
        kind, model = action.kind, action.model
        accepting = self.frontend.membership.backends(
            accepting_only=True)
        if kind == "fault_in":
            placed = self.frontend._fault_in(model, trigger="fleet")
            if not placed:
                raise KeyError("no host with capacity for %r" % model)
            return {"backend": placed[0]}
        if kind == "scale_up":
            bid = place_by_capacity(accepting, prefer_absent=model)
            if bid is None:
                raise KeyError("no accepting backend to scale %r onto"
                               % model)
            cur = int(sensors.resident.get(bid, 0))
            if cur > 0:
                self._backend_call(bid, {"cmd": "resize_model",
                                         "name": model,
                                         "replicas": cur + 1})
            elif model in (accepting[bid].get("paged") or ()):
                self._backend_call(bid, {"cmd": "fault_model",
                                         "name": model,
                                         "trigger": "global_scale_up"})
            else:
                with self.frontend._lock:
                    spec = dict(self.frontend._model_specs.get(model)
                                or {})
                if not spec:
                    raise KeyError(
                        "no persisted lane spec to place %r on %s"
                        % (model, bid))
                spec.update(cmd="load_model", name=model, replicas=1)
                self._backend_call(bid, spec)
            return {"backend": bid, "from": cur}
        if kind == "scale_down":
            if not sensors.resident:
                raise KeyError("%r resident nowhere" % model)
            # shrink where the most replicas live (ties: backend id)
            bid = max(sorted(sensors.resident),
                      key=lambda b: sensors.resident[b])
            cur = int(sensors.resident[bid])
            if cur > 1:
                self._backend_call(bid, {"cmd": "resize_model",
                                         "name": model,
                                         "replicas": cur - 1})
            else:
                # last replica on this host: page (keeps the spec warm
                # for a rejoin) rather than unload
                self._backend_call(bid, {"cmd": "page_model",
                                         "name": model})
            return {"backend": bid, "from": cur}
        if kind == "page_out":
            paged = []
            for bid in sorted(sensors.resident):
                try:
                    self._backend_call(bid, {"cmd": "page_model",
                                             "name": model})
                    paged.append(bid)
                except Exception:
                    continue
            if not paged:
                raise KeyError("paged %r nowhere" % model)
            return {"backends": paged}
        raise ValueError("unknown global action %r" % kind)

    # -- tick ----------------------------------------------------------

    def tick(self, now=None):
        """One sense -> decide -> act pass; returns the processed
        [(action, outcome)] list.  Every decision is evented
        (``global_fleet_decision``) whether executed, dry-run, or
        failed — the acceptance idiom the per-server tier set."""
        now = time.monotonic() if now is None else now
        sensed = self.sense(now)
        plan = []
        with self._lock:
            self._ticks += 1
            for model, s in sorted(sensed.items()):
                policy = self.policy_for(model)
                state = self._state.setdefault(model, {})
                for act in decide_global(s, policy, state, now):
                    plan.append((act, s))
        processed = []
        for act, s in plan:
            if self.dry_run:
                outcome = "dry_run"
            else:
                try:
                    detail = self._execute(act, s)
                    outcome = "ok"
                    act.params.update(detail or {})
                    with self._lock:
                        st = self._state.setdefault(act.model, {})
                        if act.kind in ("scale_up", "scale_down"):
                            st["last_scale_t"] = now
                        elif act.kind in ("page_out", "fault_in"):
                            st["last_page_t"] = now
                except Exception as e:
                    outcome = "error:%s" % type(e).__name__
            with self._lock:
                self._acted[act.kind] = self._acted.get(act.kind, 0) \
                    + (0 if self.dry_run else 1)
                self._history.append(
                    dict(act.to_dict(), outcome=outcome))
                del self._history[:-self.HISTORY_KEPT]
            obs_events.emit("global_fleet_decision", tier="global",
                            action=act.kind, model=act.model,
                            outcome=outcome, params=dict(act.params),
                            signal=dict(act.signal))
            processed.append((act, outcome))
        return processed

    # -- lifecycle -----------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name="paddle-tpu-global-fleet")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.eval_interval_s):
            try:
                self.tick()
            except Exception:
                pass  # the controller loop must never die

    def stop(self, timeout=2.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=timeout)

    # -- exposition ----------------------------------------------------

    def status(self):
        with self._lock:
            return {
                "enabled": True, "global": True,
                "dry_run": bool(self.dry_run),
                "interval_ms": round(self.eval_interval_s * 1e3, 3),
                "ticks": self._ticks,
                "actions": dict(self._acted),
                "policies": {m: p.to_dict()
                             for m, p in sorted(self.policies.items())},
                "models": {m: dict(d) for m, d in
                           sorted(self._last_sense.items())},
                "history": [dict(h) for h in self._history[-8:]]}

    def export(self):
        """Prometheus rows riding the frontend's attach_federation."""
        with self._lock:
            rows = [("global_fleet_ticks_total", {}, self._ticks,
                     "counter")]
            for kind in sorted(self._acted):
                rows.append(("global_fleet_actions_total",
                             {"kind": kind}, self._acted[kind],
                             "counter"))
            for model, d in sorted(self._last_sense.items()):
                rows.append(("global_fleet_replicas",
                             {"model": model}, d["total_replicas"],
                             "gauge"))
                rows.append(("global_fleet_paged", {"model": model},
                             int(d["total_replicas"] == 0
                                 and bool(d["paged_on"])), "gauge"))
        return rows
