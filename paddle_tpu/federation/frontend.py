"""Front-door router/LB for a federation of InferenceServers.

One endpoint, N backend servers, the SAME wire protocol on both sides:
clients speak `infer` / `infer_stream` / `stats` / `health` / `flight`
to the frontend exactly as they would to a single InferenceServer
(ServingClient works unchanged), and the frontend forwards over the
same length-prefixed typed framing (distributed/rpc.py) to the backends
its membership table (membership.py) says are alive and accepting.

Placement policy (SERVING.md "Federated serving"):

* **least-loaded** — candidates are live, accepting leases with the
  model resident; score = 2 x frontend-tracked in-flight + the
  heartbeat-fed backend queue depth; ties break on backend id
  (deterministic).
* **session affinity** — a decode stream pins to the backend holding
  its KV slots: the trace_id -> backend pin is taken at placement and
  honored first on later streams with the same trace_id; a pin onto a
  lost/draining backend re-pins onto the survivor set (counted —
  ``repins``).
* **spillover before shed** — a ``ServerOverloaded`` reply retries on
  the next-least-loaded candidate carrying the SAME trace_id; only
  when every candidate sheds does the client see "overloaded"
  (``spillover`` vs ``shed`` counters).
* **drain** — `drain backend=<id>`: the lease leaves the placement set
  immediately (membership.mark_draining + the backend's own `drain`
  verb), in-flight streams run to completion (the frontend tracks its
  per-backend in-flight count), then the lease is de-leased
  (``backend_drained`` event).  Draining is visibly distinct from
  dead: the lease stays, `health` says accepting=False.
* **global fault-in** — a request for a model resident on NO live
  backend faults it in wherever capacity lives (prefer a backend
  holding it paged — warm) by replaying the lane spec the frontend
  persisted from `load_model` passthrough (global_fleet.py owns the
  background version of this decision).

A backend death mid-stream surfaces to the client as ONE terminal
frame ``{"error", "code": "stream_broken", "done": True}`` carrying
the chunk count already relayed — ServingClient raises the typed
StreamBroken; tokens already delivered are real and are never
replayed.  Subsequent traffic re-places within one heartbeat TTL
(suspect-on-connect-failure makes it usually immediate).
"""

import collections
import socket
import socketserver
import threading
import time

from ..distributed.rpc import _recv_msg, _send_msg
from ..flags import FLAGS
from ..native.wire import WireError
from ..obs import tracing as obs_tracing
from ..serving.batcher import DeadlineExceeded, ServerOverloaded
from ..serving.server import (ServingClient, ServingError, StreamBroken,
                              _error_reply)
from .membership import MembershipRegistry

__all__ = ["FrontendServer"]

_CLOSE = object()

# counters summed across backends when merging stats snapshots; the
# histogram quantiles take the elementwise MAX (conservative — a
# cross-server percentile cannot be recovered from per-server ones)
_MERGE_SUM = ("requests", "responses", "errors", "shed",
              "deadline_expired", "dispatches", "streams", "prefills",
              "decode_tokens", "decode_steps", "decode_dispatches",
              "spec_rounds", "draft_tokens", "accepted_tokens",
              "spec_degraded", "queue_depth", "qps_recent",
              "qps_lifetime", "tokens_per_sec", "kv_cache_bytes")
_MERGE_MAX_HIST = ("latency_ms", "queue_wait_ms", "ttft_ms",
                   "tokens_per_dispatch")


def _ferror_reply(exc):
    """Frontend error mapping: the serving table plus the federation
    codes (a backend's typed reply re-raised by the forwarding client
    keeps its code end to end)."""
    if isinstance(exc, StreamBroken):
        return {"error": str(exc), "code": "stream_broken"}
    if isinstance(exc, ServingError) and getattr(exc, "code", None):
        return {"error": str(exc), "code": exc.code}
    return _error_reply(exc)


class FrontendServer:
    """The front door: membership + routing + the global fleet tier.

    Speaks the backend-facing verbs (`register`/`heartbeat`/
    `deregister` from _FederationLink) and the client-facing
    passthrough verbs on ONE endpoint — a backend is just another wire
    peer."""

    AFFINITY_KEPT = 4096

    def __init__(self, endpoint="127.0.0.1:0", ttl_s=None,
                 global_fleet=None, global_policy=None,
                 name="frontend"):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self.name = str(name)
        self.membership = MembershipRegistry(ttl_s=ttl_s, name=name)
        self._lock = threading.Lock()
        self._clients = {}     # backend_id -> ServingClient
        self._inflight = {}    # backend_id -> frontend in-flight count
        self._placed = {}      # backend_id -> requests placed (counter)
        self._counters = {"spillover": 0, "shed": 0,
                          "streams_broken": 0, "repins": 0,
                          "faulted": 0}
        self._affinity = collections.OrderedDict()  # trace_id -> bid
        self._draining = {}    # backend_id -> drain start (monotonic)
        self._model_specs = {}  # model -> persisted load_model kwargs
        self._want_global = (bool(FLAGS.global_fleet)
                             if global_fleet is None
                             else bool(global_fleet))
        self._global_policy = global_policy
        self.global_fleet = None
        self._started_t = time.monotonic()
        self._stopped = False
        self._server = None
        self._thread = None
        self._sweeper = None
        from ..obs import registry as obs_registry
        self._obs_registry = obs_registry.default()

    # -- lifecycle -----------------------------------------------------

    def start(self, background=True):
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_msg(self.request)
                        if msg.get("cmd") == "infer_stream":
                            outer._handle_infer_stream(msg, self.request)
                            continue
                        try:
                            reply = outer._dispatch(
                                msg, peer=self.client_address)
                        except BaseException as e:
                            reply = _ferror_reply(e)
                        if reply is _CLOSE:
                            _send_msg(self.request, {"ok": True})
                            break
                        try:
                            _send_msg(self.request, reply)
                        except WireError as e:
                            _send_msg(self.request, {"error": str(e),
                                                     "code": "internal"})
                except WireError:
                    pass  # desynced stream: drop the connection
                except (ConnectionError, EOFError, OSError):
                    pass

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            request_queue_size = 128

        self._server = Server(self._addr, Handler)
        self._addr = self._server.server_address
        self._obs_registry.attach_federation(self)
        if self._want_global:
            from .global_fleet import GlobalFleetController
            self.global_fleet = GlobalFleetController(
                self, policies=self._global_policy)
            self.global_fleet.start()
        self._sweeper = threading.Thread(
            target=self._sweep_loop, daemon=True,
            name="paddle-tpu-fed-sweeper")
        self._sweeper.start()
        if background:
            self._thread = threading.Thread(target=self._serve,
                                            daemon=True)
            self._thread.start()
        else:
            self._serve()
        return self

    @property
    def endpoint(self):
        return "%s:%d" % (self._addr[0], self._addr[1])

    def _serve(self):
        self._server.timeout = 0.2
        with self._server:
            while not self._stopped:
                self._server.handle_request()

    def shutdown(self, timeout=10.0):
        """Stop the front door (backends keep running — they notice
        the missing frontend only as failed heartbeats and keep
        serving direct traffic)."""
        self._stopped = True
        if self.global_fleet is not None:
            self.global_fleet.stop()
            self.global_fleet = None
        self._obs_registry.detach_federation(self)
        with self._lock:
            clients, self._clients = dict(self._clients), {}
        for cli in clients.values():
            cli.close()
        try:
            s = socket.create_connection(self._addr, timeout=1)
            s.close()
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        if self._sweeper is not None:
            self._sweeper.join(timeout=2.0)

    def _sweep_loop(self):
        interval = min(max(self.membership.ttl_s / 4.0, 0.05), 1.0)
        while not self._stopped:
            time.sleep(interval)
            try:
                self.membership.sweep()
                self._drain_progress()
            except Exception:
                pass  # the sweeper must never die

    # -- bookkeeping ---------------------------------------------------

    def _client(self, bid, endpoint=None):
        with self._lock:
            cli = self._clients.get(bid)
            if cli is None and endpoint:
                cli = self._clients[bid] = ServingClient(endpoint)
            return cli

    def _drop_client(self, bid):
        with self._lock:
            cli = self._clients.pop(bid, None)
        if cli is not None:
            cli.close()

    def _bump_inflight(self, bid, delta):
        with self._lock:
            self._inflight[bid] = max(
                self._inflight.get(bid, 0) + delta, 0)

    def _note_placed(self, bid):
        with self._lock:
            self._placed[bid] = self._placed.get(bid, 0) + 1

    def _count(self, key, n=1):
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def _pin(self, trace_id, bid):
        with self._lock:
            self._affinity[trace_id] = bid
            self._affinity.move_to_end(trace_id)
            while len(self._affinity) > self.AFFINITY_KEPT:
                self._affinity.popitem(last=False)

    def _pinned(self, trace_id):
        with self._lock:
            return self._affinity.get(trace_id)

    def _unpin(self, trace_id):
        with self._lock:
            self._affinity.pop(trace_id, None)

    # -- placement -----------------------------------------------------

    def _candidates(self, model=None):
        """Live, accepting backends ordered least-loaded-first; with a
        model, only backends where it is RESIDENT (a model nowhere
        resident goes through the global fault-in path instead)."""
        backs = self.membership.backends(accepting_only=True)
        scored = []
        with self._lock:
            inflight = dict(self._inflight)
        for bid, lease in backs.items():
            if model is not None and str(model) not in lease["models"]:
                continue
            score = (2 * inflight.get(bid, 0)
                     + int((lease.get("load") or {})
                           .get("queue_depth") or 0))
            scored.append((score, bid))
        scored.sort()
        return [bid for _, bid in scored]

    def _fault_in(self, model, trigger="demand"):
        """The model is resident on NO live backend: place it where
        capacity lives (warm paged holder first), replaying the
        persisted lane spec.  Returns the chosen backend as a 1-entry
        candidate list, [] when nothing can host it."""
        from ..obs import events as obs_events
        from .global_fleet import place_by_capacity
        model = str(model)
        backs = self.membership.backends(accepting_only=True)
        if not backs:
            return []
        paged = {bid: l for bid, l in backs.items()
                 if model in (l.get("paged") or [])}
        with self._lock:
            spec = dict(self._model_specs.get(model) or {})
        pool = paged or (backs if spec else {})
        if not pool:
            return []
        bid = place_by_capacity(pool)
        lease = backs[bid]
        cli = self._client(bid, lease["endpoint"])
        try:
            if bid in paged:
                cli.call({"cmd": "fault_model", "name": model,
                          "trigger": "federation_%s" % trigger})
            else:
                cli.call(dict(spec, cmd="load_model", name=model))
        except Exception:
            return []
        self._count("faulted")
        obs_events.emit("global_fault_in", model=model, backend=bid,
                        trigger=str(trigger), warm=bid in paged)
        return [bid]

    # -- routing: one-shot ---------------------------------------------

    def _route_infer(self, msg):
        model = msg.get("model")
        trace_id = str(msg.get("trace_id")
                       or obs_tracing.new_trace_id())
        msg = dict(msg, trace_id=trace_id)
        cands = self._candidates(model)
        if not cands:
            cands = self._fault_in(model)
        if not cands:
            raise KeyError("model %r is resident on no live backend"
                           % (model,))
        overloaded = None
        for i, bid in enumerate(cands):
            lease = self.membership.get(bid)
            if lease is None:
                continue
            cli = self._client(bid, lease["endpoint"])
            self._bump_inflight(bid, +1)
            try:
                reply = cli.call(msg)
            except ServerOverloaded as e:
                overloaded = e
                if i + 1 < len(cands):
                    # spillover before shed: the SAME trace_id retries
                    # on the next-least-loaded backend
                    self._count("spillover")
                continue
            except DeadlineExceeded:
                raise
            except (ConnectionError, EOFError, OSError,
                    WireError) as e:
                # hard transport evidence beats waiting out the TTL
                self.membership.suspect(
                    bid, "conn:%s" % type(e).__name__)
                self._drop_client(bid)
                continue
            finally:
                self._bump_inflight(bid, -1)
            self._note_placed(bid)
            reply["backend"] = bid
            return reply
        if overloaded is not None:
            self._count("shed")
            raise overloaded
        raise ServingError(
            "no live backend answered for model %r" % (model,))

    # -- routing: streams ----------------------------------------------

    def _handle_infer_stream(self, msg, sock):
        """Relay one decode stream: place (affinity first), forward the
        request on a dedicated backend connection, pump frames to the
        client annotated with the serving backend id.  Backend death
        mid-stream -> ONE terminal stream_broken frame (chunks already
        relayed are committed — never replayed); overloaded before the
        first chunk -> spillover to the next candidate, same
        trace_id."""
        trace_id = str(msg.get("trace_id")
                       or obs_tracing.new_trace_id())
        msg = dict(msg, trace_id=trace_id)
        model = msg.get("model")

        def terminal(exc):
            reply = _ferror_reply(exc)
            reply["done"] = True
            reply["trace_id"] = trace_id
            try:
                _send_msg(sock, reply)
            except (ConnectionError, EOFError, OSError, WireError):
                pass

        cands = self._candidates(model)
        pin = self._pinned(trace_id)
        if pin is not None:
            if pin in cands:
                # session affinity: the backend holding this session's
                # KV slots serves it again
                cands = [pin] + [b for b in cands if b != pin]
            else:
                # pinned backend lost/draining: re-pin onto survivors
                self._count("repins")
        if not cands:
            cands = self._fault_in(model)
        if not cands:
            terminal(KeyError("model %r is resident on no live backend"
                              % (model,)))
            return
        overloaded = None
        for i, bid in enumerate(cands):
            lease = self.membership.get(bid)
            if lease is None:
                continue
            try:
                bs = socket.create_connection(
                    (lease["host"], lease["port"]),
                    timeout=FLAGS.rpc_deadline)
            except OSError:
                self.membership.suspect(bid, "conn_refused")
                continue
            self._bump_inflight(bid, +1)
            relayed = 0
            try:
                try:
                    _send_msg(bs, msg)
                except (ConnectionError, EOFError, OSError, WireError):
                    self.membership.suspect(bid, "conn_reset")
                    continue
                self._pin(trace_id, bid)
                while True:
                    try:
                        frame = _recv_msg(bs)
                    except (ConnectionError, EOFError, OSError,
                            WireError):
                        # backend died MID-STREAM: its KV slots (and
                        # this stream) are gone.  One typed terminal
                        # frame; the relayed chunks stand.
                        self.membership.suspect(bid, "stream")
                        self._drop_client(bid)
                        self._count("streams_broken")
                        self._unpin(trace_id)
                        _send_msg(sock, {
                            "error": "backend %s lost mid-stream "
                                     "after %d chunk(s)"
                                     % (bid, relayed),
                            "code": "stream_broken", "done": True,
                            "trace_id": trace_id, "backend": bid,
                            "chunks": relayed})
                        return
                    if frame.get("chunk"):
                        frame["backend"] = bid
                        # a send failure here = CLIENT died: propagate,
                        # the finally closes the backend socket, which
                        # is the backend's eviction signal
                        _send_msg(sock, frame)
                        relayed += 1
                        continue
                    # terminal frame
                    if ("error" in frame
                            and frame.get("code") == "overloaded"
                            and relayed == 0
                            and i + 1 < len(cands)):
                        # nothing streamed yet: spillover, same trace
                        self._count("spillover")
                        self._unpin(trace_id)
                        overloaded = frame
                        break
                    frame["backend"] = bid
                    if "error" in frame:
                        self._unpin(trace_id)
                    else:
                        self._note_placed(bid)
                    _send_msg(sock, frame)
                    return
            finally:
                self._bump_inflight(bid, -1)
                try:
                    bs.close()
                except OSError:
                    pass
        if overloaded is not None:
            self._count("shed")
            overloaded = dict(overloaded, trace_id=trace_id, done=True)
            try:
                _send_msg(sock, overloaded)
            except (ConnectionError, EOFError, OSError, WireError):
                pass
            return
        terminal(ServingError(
            "no live backend accepted stream for model %r" % (model,)))

    # -- drain ---------------------------------------------------------

    def _drain_progress(self):
        """Sweeper hook: a draining backend whose frontend in-flight
        count reached zero has finished its streams — de-lease it."""
        from ..obs import events as obs_events
        with self._lock:
            draining = dict(self._draining)
            inflight = dict(self._inflight)
        for bid, t0 in draining.items():
            if self.membership.get(bid) is None:
                with self._lock:
                    self._draining.pop(bid, None)
                continue
            if inflight.get(bid, 0) > 0:
                continue
            self.membership.deregister(bid, reason="drained")
            self._drop_client(bid)
            with self._lock:
                self._draining.pop(bid, None)
            obs_events.emit("backend_drained", backend=bid,
                            drain_s=round(time.monotonic() - t0, 3))

    # -- merged readouts -----------------------------------------------

    def _merge_stats(self):
        """One ServingMetrics-shaped snapshot across the federation:
        counters sum, queue depths and QPS sum, percentiles take the
        elementwise max (conservative — exact cross-server quantiles
        are not recoverable from per-server summaries)."""
        merged, desc, per_backend = {}, {}, {}
        for bid, lease in self.membership.backends().items():
            cli = self._client(bid, lease["endpoint"])
            if cli is None:
                continue
            try:
                r = cli.call({"cmd": "stats"})
            except Exception:
                continue
            per_backend[bid] = {"endpoint": lease["endpoint"],
                                "models": sorted(
                                    (r.get("stats") or {})
                                    .get("models") or ())}
            for key, m in ((r.get("stats") or {})
                           .get("models") or {}).items():
                if key not in merged:
                    merged[key] = dict(m)
                    continue
                out = merged[key]
                for f in _MERGE_SUM:
                    if m.get(f) is not None:
                        out[f] = (out.get(f) or 0) + m[f]
                for f in _MERGE_MAX_HIST:
                    h = m.get(f)
                    if not isinstance(h, dict):
                        continue
                    oh = out.setdefault(f, {})
                    for q, v in h.items():
                        if v is None:
                            continue
                        if q == "count":
                            oh[q] = (oh.get(q) or 0) + v
                        elif oh.get(q) is None or v > oh[q]:
                            oh[q] = v
            for name, d in (r.get("models") or {}).items():
                if name not in desc:
                    desc[name] = dict(d)
                else:
                    od = desc[name]
                    od["replicas"] = ((od.get("replicas") or 0)
                                      + (d.get("replicas") or 0))
                    od["paged"] = bool(od.get("paged")) \
                        and bool(d.get("paged"))
                desc[name].setdefault("federated_on", []).append(bid)
        return merged, desc, per_backend

    def federation_status(self):
        """The federation readout: membership table + routing counters
        + per-backend placement/in-flight + the global tier's status —
        rides the `stats` reply's "federation" key (serving_top) and
        the `health` payload."""
        st = self.membership.status()
        with self._lock:
            st["inflight"] = dict(self._inflight)
            st["placed"] = dict(self._placed)
            st["counters"] = dict(self._counters)
            st["draining"] = sorted(self._draining)
            st["models"] = sorted(self._model_specs)
        st["endpoint"] = self.endpoint
        if self.global_fleet is not None:
            st["global_fleet"] = self.global_fleet.status()
        return st

    # -- verbs ---------------------------------------------------------

    def _dispatch(self, msg, peer=None):
        cmd = msg.get("cmd")
        if cmd == "infer":
            return self._route_infer(msg)
        if cmd == "register":
            host = msg.get("host") or (peer[0] if peer else "127.0.0.1")
            grant = self.membership.register(
                host, msg["port"], backend_id=msg.get("backend_id"),
                models=msg.get("models"), paged=msg.get("paged"),
                capacity_mb=msg.get("capacity_mb") or 0.0)
            if msg.get("load") is not None:
                self.membership.heartbeat(
                    grant["backend_id"], grant["lease_id"],
                    load=msg["load"])
            self._client(grant["backend_id"],
                         "%s:%d" % (host, int(msg["port"])))
            return dict(grant, ok=True,
                        heartbeat_ms=float(FLAGS.federation_heartbeat_ms))
        if cmd == "heartbeat":
            ok = self.membership.heartbeat(
                msg["backend_id"], msg["lease_id"],
                models=msg.get("models"), paged=msg.get("paged"),
                accepting=msg.get("accepting"), load=msg.get("load"))
            if not ok:
                return {"error": "unknown or expired lease — "
                                 "re-register", "code": "no_lease"}
            return {"ok": True, "revision": self.membership.revision}
        if cmd == "deregister":
            self.membership.deregister(msg["backend_id"])
            self._drop_client(msg["backend_id"])
            return {"ok": True}
        if cmd == "drain":
            bid = str(msg["backend"])
            lease = self.membership.get(bid)
            if lease is None:
                raise KeyError("no live backend %r" % bid)
            self.membership.mark_draining(bid, not msg.get("resume"))
            cli = self._client(bid, lease["endpoint"])
            try:
                cli.call({"cmd": "drain",
                          "resume": bool(msg.get("resume"))})
            except Exception:
                pass  # lease state governs placement either way
            with self._lock:
                if msg.get("resume"):
                    self._draining.pop(bid, None)
                else:
                    self._draining[bid] = time.monotonic()
            return {"ok": True, "backend": bid,
                    "draining": not msg.get("resume")}
        if cmd == "stats":
            merged, desc, per_backend = self._merge_stats()
            fed = self.federation_status()
            fed["per_backend"] = per_backend
            return {"ok": True,
                    "stats": {"uptime_sec": round(
                        time.monotonic() - self._started_t, 3),
                        "models": merged},
                    "models": desc,
                    "federation": fed}
        if cmd == "health":
            backends = {}
            for bid, lease in self.membership.backends().items():
                cli = self._client(bid, lease["endpoint"])
                try:
                    backends[bid] = cli.call({"cmd": "health"})["health"]
                except Exception as e:
                    backends[bid] = {"error": "%s: %s"
                                     % (type(e).__name__, e)}
            return {"ok": True, "health": {
                "accepting": not self._stopped, "draining": False,
                "frontend": True,
                "federation": self.federation_status(),
                "backends": backends}}
        if cmd == "flight":
            bundles, enabled = {}, False
            for bid, lease in self.membership.backends().items():
                cli = self._client(bid, lease["endpoint"])
                try:
                    r = cli.call({"cmd": "flight",
                                  "reason": str(msg.get("reason")
                                                or "federation_rpc"),
                                  "force": bool(msg.get("force",
                                                        True))})
                    bundles[bid] = r.get("bundle")
                    enabled = enabled or bool(r.get("enabled"))
                except Exception:
                    bundles[bid] = None
            return {"ok": True, "bundles": bundles, "enabled": enabled,
                    # a single-server caller reads "bundle": give it
                    # the first committed path
                    "bundle": next((p for p in bundles.values() if p),
                                   None)}
        if cmd == "fleet":
            if msg.get("set_policy") or msg.get("dry_run") is not None:
                if self.global_fleet is None:
                    raise ValueError(
                        "global fleet controller disabled — start the "
                        "frontend with FLAGS.global_fleet=true")
                for model, spec in dict(
                        msg.get("set_policy") or {}).items():
                    self.global_fleet.set_policy(str(model), str(spec))
                if msg.get("dry_run") is not None:
                    self.global_fleet.dry_run = bool(msg["dry_run"])
            return {"ok": True,
                    "fleet": (self.global_fleet.status()
                              if self.global_fleet is not None
                              else {"enabled": False, "global": True})}
        if cmd == "metrics":
            return {"ok": True,
                    "text": self._obs_registry.prometheus_text()}
        if cmd == "load_model":
            return self._load_model(msg)
        if cmd == "unload_model":
            replies = {}
            for bid, lease in self.membership.backends().items():
                cli = self._client(bid, lease["endpoint"])
                try:
                    cli.call({"cmd": "unload_model",
                              "name": msg["name"]})
                    replies[bid] = {"ok": True}
                except Exception as e:
                    replies[bid] = {"error": str(e)}
            with self._lock:
                self._model_specs.pop(str(msg["name"]), None)
            return {"ok": True, "backends": replies}
        if cmd == "shutdown":
            threading.Thread(target=self.shutdown,
                             daemon=True).start()
            return {"ok": True, "draining": True}
        if cmd == "exit":
            self._stopped = True
            return _CLOSE
        return {"error": "unknown cmd %r" % cmd, "code": "bad_request"}

    def _load_model(self, msg):
        """Fan the load to every live accepting backend (or the one
        named by "backend") and PERSIST the lane spec — the global
        fault-in path replays it wherever capacity lives later."""
        name = str(msg["name"])
        spec = {k: v for k, v in msg.items()
                if k not in ("cmd", "backend")}
        target = msg.get("backend")
        backs = self.membership.backends(accepting_only=True)
        if target is not None:
            if str(target) not in backs:
                raise KeyError("no live backend %r" % (target,))
            backs = {str(target): backs[str(target)]}
        if not backs:
            raise ServingError("no live backend to load %r onto" % name)
        replies, ok = {}, 0
        for bid, lease in sorted(backs.items()):
            cli = self._client(bid, lease["endpoint"])
            try:
                r = cli.call(dict(spec, cmd="load_model"))
                replies[bid] = {k: v for k, v in r.items()}
                ok += 1
            except Exception as e:
                replies[bid] = {"error": "%s: %s"
                                % (type(e).__name__, e)}
        if not ok:
            raise ServingError(
                "load_model(%s) failed on every backend: %r"
                % (name, {b: r.get("error")
                          for b, r in replies.items()}))
        with self._lock:
            self._model_specs[name] = spec
        return {"ok": True, "name": name, "loaded": ok,
                "backends": replies}

    # -- exposition ----------------------------------------------------

    def export(self):
        """[(metric, labels, value, type)] rows for the obs registry's
        attach_federation render: membership by state, placement /
        spillover / shed / broken-stream counters, revision — plus the
        global tier's rows."""
        st = self.membership.status()
        live = sum(1 for l in st["backends"].values()
                   if not l["draining"])
        draining = sum(1 for l in st["backends"].values()
                       if l["draining"])
        rows = [
            ("federation_backends", {"state": "live"}, live, "gauge"),
            ("federation_backends", {"state": "draining"}, draining,
             "gauge"),
            ("federation_backends", {"state": "lost"},
             len(st["lost"]), "gauge"),
            ("federation_revision", {}, st["revision"], "gauge"),
        ]
        with self._lock:
            for bid, n in sorted(self._placed.items()):
                rows.append(("federation_placed_total",
                             {"backend": bid}, n, "counter"))
            for key in sorted(self._counters):
                rows.append(("federation_%s_total" % key, {},
                             self._counters[key], "counter"))
        if self.global_fleet is not None:
            rows.extend(self.global_fleet.export())
        return rows


def main(argv=None):
    """Run a front-door router as a process:
    ``python -m paddle_tpu.federation.frontend --endpoint 0.0.0.0:9500``
    — backends point FLAGS.federation_frontend at it."""
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint", default="127.0.0.1:9500")
    ap.add_argument("--ttl_s", type=float, default=None)
    ap.add_argument("--global_fleet", action="store_true")
    ap.add_argument("--global_policy", default=None)
    args = ap.parse_args(argv)
    from ..serving.fleet import parse_fleet_spec
    fe = FrontendServer(
        endpoint=args.endpoint, ttl_s=args.ttl_s,
        global_fleet=args.global_fleet or None,
        global_policy=(parse_fleet_spec(args.global_policy)
                       if args.global_policy else None))
    print("federation frontend on %s" % args.endpoint)
    fe.start(background=False)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
