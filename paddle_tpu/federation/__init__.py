"""Federated serving: membership leases, the front-door router/LB,
and the fleet-of-fleets controller (SERVING.md "Federated serving").

One `FrontendServer` endpoint fronts N `InferenceServer` backends over
the existing wire protocol — backends register heartbeat-TTL leases
(`MembershipRegistry`), clients keep using `ServingClient` unchanged,
and the `GlobalFleetController` places per-model replica budgets and
cluster-wide paging across hosts by the est_peak_mb capacity signal.
"""

from .membership import Lease, MembershipRegistry
from .frontend import FrontendServer
from .global_fleet import (GlobalFleetController, GlobalSensors,
                           decide_global, place_by_capacity)

__all__ = [
    "Lease",
    "MembershipRegistry",
    "FrontendServer",
    "GlobalFleetController",
    "GlobalSensors",
    "decide_global",
    "place_by_capacity",
]
