"""Membership registry: heartbeat-TTL leases for the federation tier.

The etcd-backed go/master + go/pserver membership layer of the
reference EDL design, rebuilt in-process: a backend server registers a
**lease** — ``{host, port, models, capacity}`` plus a TTL — and renews
it by heartbeating.  A lease whose heartbeat goes missing past its TTL
expires: the backend drops out of the placement set and a
``backend_lost`` obs event fires (``backend_joined`` on register, with
``rejoin=True`` when the same backend id returns after a loss — the
elastic-membership cycle the TensorFlow system paper's dynamic
discovery design sketches, arXiv 1605.08695).

The registry is deliberately serving-agnostic bones: members are
``(id, endpoint, ttl, payload)`` with a monotonic **revision** counter
bumped on every membership change (the etcd idiom — a watcher compares
revisions instead of diffing tables), so the elastic-training roadmap
item can lease trainers/pservers through the same class.  The serving
payload (resident models, paged models, capacity_mb, queue depth) is
carried opaquely in ``models``/``paged``/``capacity_mb``/``load`` and
interpreted only by the frontend's placement logic (frontend.py).

Drain is a first-class lease state, distinct from loss: a draining
backend keeps heartbeating (it is alive, finishing streams) but is
excluded from placement; de-leasing it after its in-flight work ends
is the frontend's job (``backend_drained`` event).  `health` carrying
``accepting: False`` and a live lease means "draining", a missing
lease means "dead" — serving_top renders the two differently.
"""

import threading
import time

__all__ = ["Lease", "MembershipRegistry"]


class Lease(object):
    """One member's registration: identity, endpoint, TTL bookkeeping,
    and the opaque serving payload the frontend places by."""

    __slots__ = ("backend_id", "lease_id", "host", "port", "models",
                 "paged", "capacity_mb", "ttl_s", "registered_t",
                 "renewed_t", "accepting", "draining", "load", "meta")

    def __init__(self, backend_id, lease_id, host, port, models=(),
                 paged=(), capacity_mb=0.0, ttl_s=3.0, meta=None,
                 now=None):
        now = time.monotonic() if now is None else now
        self.backend_id = str(backend_id)
        self.lease_id = str(lease_id)
        self.host = str(host)
        self.port = int(port)
        self.models = dict(models or {})   # name -> {"replicas", ...}
        self.paged = list(paged or ())
        self.capacity_mb = float(capacity_mb or 0.0)
        self.ttl_s = float(ttl_s)
        self.registered_t = now
        self.renewed_t = now
        self.accepting = True
        self.draining = False
        self.load = {}                     # heartbeat-fed load snapshot
        self.meta = dict(meta or {})

    @property
    def endpoint(self):
        return "%s:%d" % (self.host, self.port)

    def age_s(self, now=None):
        now = time.monotonic() if now is None else now
        return max(now - self.renewed_t, 0.0)

    def expired(self, now=None):
        return self.age_s(now) > self.ttl_s

    def resident_mb(self):
        """Estimated HBM resident across this backend's models — the
        PR 11 est_peak_mb cost signal summed over replicas, fed by the
        heartbeat; the placement-by-capacity input."""
        total = 0.0
        for m in self.models.values():
            per = float(m.get("est_peak_mb") or 0.0)
            total += per * max(int(m.get("replicas") or 1), 1)
        return total

    def free_mb(self):
        """Declared capacity minus resident estimate (None when the
        backend declared no capacity — unknown, not zero)."""
        if self.capacity_mb <= 0.0:
            return None
        return self.capacity_mb - self.resident_mb()

    def to_dict(self, now=None):
        return {"backend_id": self.backend_id,
                "lease_id": self.lease_id,
                "host": self.host, "port": self.port,
                "endpoint": self.endpoint,
                "models": {k: dict(v) for k, v in self.models.items()},
                "paged": list(self.paged),
                "capacity_mb": self.capacity_mb,
                "resident_mb": round(self.resident_mb(), 3),
                "ttl_s": self.ttl_s,
                "age_s": round(self.age_s(now), 3),
                "accepting": bool(self.accepting),
                "draining": bool(self.draining),
                "load": dict(self.load),
                "meta": dict(self.meta)}


class MembershipRegistry(object):
    """TTL-lease member table with a monotonic revision counter.

    Every mutation (join, leave, loss, drain flip) bumps ``revision``;
    reads sweep expired leases first, so a caller never places onto a
    lease that stopped heartbeating more than one sweep ago.  Lost
    members are kept (bounded) in a shadow table so operators can tell
    "died 4s ago" from "never existed"."""

    LOST_KEPT = 32

    def __init__(self, ttl_s=None, name="frontend"):
        from ..flags import FLAGS
        self.ttl_s = (float(FLAGS.federation_ttl_s) if ttl_s is None
                      else float(ttl_s))
        self.ttl_s = max(self.ttl_s, 0.05)
        self.name = str(name)
        self._lock = threading.Lock()
        self._leases = {}      # backend_id -> Lease
        self._lost = {}        # backend_id -> {"reason", "t", ...}
        self._revision = 0
        self._seq = 0

    # -- lifecycle -----------------------------------------------------

    def register(self, host, port, backend_id=None, models=None,
                 paged=None, capacity_mb=0.0, ttl_s=None, meta=None):
        """Grant (or re-grant) a lease.  Returns the wire-encodable
        grant: {"backend_id", "lease_id", "ttl_s", "revision"}.
        Re-registering an id that is currently LOST is the rejoin path
        — same id, fresh lease, ``backend_joined`` with rejoin=True."""
        from ..obs import events as obs_events
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            bid = str(backend_id or "%s:%s" % (host, port))
            self._seq += 1
            lease = Lease(bid, "ls-%d" % self._seq, host, port,
                          models=models, paged=paged,
                          capacity_mb=capacity_mb,
                          ttl_s=self.ttl_s if ttl_s is None else ttl_s,
                          meta=meta, now=now)
            rejoin = bid in self._lost or bid in self._leases
            self._lost.pop(bid, None)
            self._leases[bid] = lease
            self._revision += 1
            rev = self._revision
        obs_events.emit("backend_joined", backend=bid,
                        endpoint=lease.endpoint, rejoin=bool(rejoin),
                        capacity_mb=lease.capacity_mb, revision=rev)
        return {"backend_id": bid, "lease_id": lease.lease_id,
                "ttl_s": lease.ttl_s, "revision": rev}

    def heartbeat(self, backend_id, lease_id, models=None, paged=None,
                  accepting=None, load=None):
        """Renew one lease; the serving payload rides along (resident
        models + est_peak_mb, paged set, queue depth) so placement and
        the global controller sense without extra RPC fan-out.
        Returns False for an unknown/stale lease — the backend must
        re-register (the rejoin path), never silently keep serving on
        a lease the frontend already declared lost."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            lease = self._leases.get(str(backend_id))
            if lease is None or lease.lease_id != str(lease_id):
                return False
            lease.renewed_t = now
            if models is not None:
                lease.models = {str(k): dict(v)
                                for k, v in dict(models).items()}
            if paged is not None:
                lease.paged = [str(p) for p in paged]
            if accepting is not None:
                lease.accepting = bool(accepting)
            if load is not None:
                lease.load = dict(load)
            return True

    def deregister(self, backend_id, reason="deregister"):
        """Clean leave (drain completed / operator removal): the lease
        goes away without entering the lost table."""
        from ..obs import events as obs_events
        with self._lock:
            lease = self._leases.pop(str(backend_id), None)
            if lease is None:
                return False
            self._revision += 1
            rev = self._revision
        obs_events.emit("backend_left", backend=str(backend_id),
                        endpoint=lease.endpoint, reason=str(reason),
                        revision=rev)
        return True

    def suspect(self, backend_id, reason="conn"):
        """Immediate expiry on hard evidence (connection refused/reset
        beats waiting out the TTL): the placement path calls this the
        moment a forward fails at the socket level."""
        with self._lock:
            lease = self._leases.get(str(backend_id))
            if lease is None:
                return False
            self._expire_locked(lease, reason, time.monotonic())
            return True

    def mark_draining(self, backend_id, draining=True):
        """Flip one lease's drain state: a draining backend stays
        leased (alive, finishing streams) but leaves the placement
        set."""
        from ..obs import events as obs_events
        with self._lock:
            lease = self._leases.get(str(backend_id))
            if lease is None:
                return False
            lease.draining = bool(draining)
            lease.accepting = not lease.draining
            self._revision += 1
            rev = self._revision
        obs_events.emit("backend_draining", backend=str(backend_id),
                        endpoint=lease.endpoint, draining=bool(draining),
                        revision=rev)
        return True

    # -- expiry --------------------------------------------------------

    def _expire_locked(self, lease, reason, now):
        from ..obs import events as obs_events
        self._leases.pop(lease.backend_id, None)
        self._lost[lease.backend_id] = {
            "endpoint": lease.endpoint, "reason": str(reason),
            "t_mono": now, "models": sorted(lease.models)}
        while len(self._lost) > self.LOST_KEPT:
            self._lost.pop(next(iter(self._lost)))
        self._revision += 1
        obs_events.emit("backend_lost", backend=lease.backend_id,
                        endpoint=lease.endpoint, reason=str(reason),
                        age_s=round(lease.age_s(now), 3),
                        revision=self._revision)

    def _sweep_locked(self, now):
        for lease in [l for l in self._leases.values()
                      if l.expired(now)]:
            self._expire_locked(lease, "ttl", now)

    def sweep(self):
        """Expire every lease past its TTL (the frontend's background
        sweeper; reads also sweep lazily)."""
        with self._lock:
            self._sweep_locked(time.monotonic())

    # -- readouts ------------------------------------------------------

    def backends(self, accepting_only=False, model=None):
        """Live member snapshot {backend_id: lease dict}, swept first.
        ``accepting_only`` drops draining/not-accepting leases (the
        placement view); ``model`` keeps only backends with that model
        RESIDENT."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            out = {}
            for bid, lease in self._leases.items():
                if accepting_only and (lease.draining
                                       or not lease.accepting):
                    continue
                if model is not None and str(model) not in lease.models:
                    continue
                out[bid] = lease.to_dict(now)
            return out

    def get(self, backend_id):
        with self._lock:
            self._sweep_locked(time.monotonic())
            lease = self._leases.get(str(backend_id))
            return None if lease is None else lease.to_dict()

    def lost(self):
        """{backend_id: {"endpoint","reason","age_s",...}} — recent
        losses (bounded), for the dead-vs-draining readout."""
        now = time.monotonic()
        with self._lock:
            return {bid: dict(rec, age_s=round(
                max(now - rec["t_mono"], 0.0), 3))
                for bid, rec in self._lost.items()}

    @property
    def revision(self):
        with self._lock:
            return self._revision

    def status(self):
        """Wire-encodable membership table (the frontend's `health`
        payload carries it; serving_top renders it)."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            return {
                "revision": self._revision,
                "ttl_s": self.ttl_s,
                "backends": {bid: lease.to_dict(now)
                             for bid, lease in self._leases.items()},
                "lost": {bid: {k: v for k, v in rec.items()
                               if k != "t_mono"}
                         for bid, rec in self._lost.items()}}
