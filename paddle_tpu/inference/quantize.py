"""Post-training quantization over saved inference artifacts
(QUANTIZE.md).

Reference analogue: contrib/quantize_transpiler.py simulates int8 with
fake-quant ops during training; TensorRT's calibration pass is the
closer shape — take a FROZEN fp32 artifact, sweep a few calibration
batches, and emit a quantized engine.  Here the "engine" is a sibling
``save_inference_model`` directory: the Program rewritten so matmul-
class ops become their ``dequant_*`` twins (ops/quant_ops.py), the
weight vars re-typed int8 with one ``<w>@scale`` fp32 per-channel scale
var each, and everything non-quantizable (biases, norm params, weights
below ``FLAGS.quantize_min_weight_elems``) left fp32 untouched.

Why this wins: bench.py's MFU note pins the serving flagship at 97% of
HBM peak — memory-roofline-bound — so halving weight bytes IS the
speedup; int8 weights are 4x smaller than fp32 and the fused
dequant-matmul kernel (ops/pallas_kernels.py) never materializes a
float copy in HBM.

Scale selection: per-output-channel symmetric int8 (q = round(W/s)
clipped to [-127, 127], s = absmax * r / 127).  The clip ratio r comes
from a small calibration search: with user-supplied feed batches, fc
weights minimize the OUTPUT error ||X @ W - X @ dq(W)||^2 on the
captured activations; without activations (and for conv/embedding
weights) the weight-space MSE decides.  Absmax (r = 1.0) is always a
candidate, so calibration can only improve on it.

Commit discipline is the checkpoint vault's (CHECKPOINT.md): every
file of the quantized artifact is written into a ``<dst>.tmp.*`` dir,
fsynced, then the dir renames into place — a SIGKILL mid-write leaves
the fp32 source artifact AND any previously committed quantized
artifact intact (chaos scenario ``quantize-commit``).  Chaos points, in
commit order: ``quant_arrays_written`` (files durable, rename pending)
and ``quant_committed``.

Tamper rejection at load: the Program half rides the PR 9 verifier
(fluid/io.load_inference_model's unconditional ``check_serialized_cached``
— a rewritten graph with a bad op/shape is rejected with named
diagnostics); the payload half is the ``quant_meta.bin`` CRC table over
every int8 payload and scale file, checked by ``check_quantized_dir``
before any weight loads (and by ``tools/verify_quantized.py`` offline).
"""

import binascii
import hashlib
import json
import os
import shutil
import threading

import numpy as np

__all__ = [
    "QUANT_META", "QuantizedArtifactError", "quantize_inference_model",
    "read_quant_meta", "is_quantized_dir", "verify_quantized_dir",
    "check_quantized_dir", "artifact_precision", "CHAOS_POINTS",
]

QUANT_META = "quant_meta.bin"
SCHEMA_VERSION = 1
CHAOS_POINTS = ("quant_arrays_written", "quant_committed")
_TINY_SCALE = 1e-12
_QMAX = 127.0


class QuantizedArtifactError(RuntimeError):
    """A quantized artifact failed its payload verification; the
    message names the corrupt file."""


def _chaos(point):
    from ..fluid import checkpoint
    checkpoint._chaos(point)


# ---------------------------------------------------------------------------
# scale selection
# ---------------------------------------------------------------------------

def _channel_absmax(w, reduce_axes):
    return np.maximum(np.abs(w).max(axis=reduce_axes), _TINY_SCALE)


def _quantize_array(w, scale, ch_axis):
    """Symmetric per-channel int8: broadcast `scale` along `ch_axis`."""
    shape = [1] * w.ndim
    shape[ch_axis] = -1
    s = scale.reshape(shape)
    q = np.clip(np.rint(w / s), -_QMAX, _QMAX).astype(np.int8)
    return q


def _dequant(q, scale, ch_axis):
    shape = [1] * q.ndim
    shape[ch_axis] = -1
    return q.astype(np.float32) * scale.reshape(shape)


def _pick_scale(w, reduce_axes, ch_axis, clip_ratios, acts=None):
    """Search the clip ratio minimizing reconstruction error.  `acts`
    (fc only): captured calibration activations [rows, K] — the error
    is then measured where it matters, on the layer OUTPUT."""
    absmax = _channel_absmax(w, reduce_axes)
    best = None
    for r in clip_ratios:
        scale = (absmax * float(r) / _QMAX).astype(np.float32)
        q = _quantize_array(w, scale, ch_axis)
        dq = _dequant(q, scale, ch_axis)
        if acts is not None and w.ndim == 2 and ch_axis == 1:
            err = float(np.mean(
                (acts @ w.astype(np.float32) - acts @ dq) ** 2))
        else:
            err = float(np.mean((w.astype(np.float32) - dq) ** 2))
        if best is None or err < best[0]:
            best = (err, float(r), scale, q)
    return best  # (err, clip_ratio, scale, q)


# candidate quantized ops: op type -> (weight slot, scale reduce axes,
# channel axis).  mul weights are [K, N] (channel = output column),
# conv filters OIHW (channel = O), embeddings [V, D] (channel = row —
# the gathered axis).
_CANDIDATES = {
    "mul": ("Y", (0,), 1),
    "conv2d": ("Filter", (1, 2, 3), 0),
    "lookup_table": ("W", (1,), 0),
}


def _supported(op, block, scope, min_elems):
    """(weight_name, spec) when this op quantizes, else None."""
    spec = _CANDIDATES.get(op.type)
    if spec is None:
        return None
    slot, reduce_axes, ch_axis = spec
    names = op.inputs.get(slot) or []
    if len(names) != 1:
        return None
    v = block._find_var_recursive(names[0])
    if v is None or not v.persistable or v.shape is None:
        return None
    if op.type == "conv2d" and int(op.attrs.get("groups", 1) or 1) != 1:
        return None  # grouped/depthwise: per-O scale story differs
    val = scope.get(names[0])
    if val is None:
        return None
    arr = np.asarray(val)
    if arr.dtype != np.float32 or arr.size < int(min_elems):
        return None
    if op.type == "mul" and arr.ndim != 2:
        return None
    return names[0], spec


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def _capture_activations(exe, scope, program, calib_feeds, wanted,
                         max_batches):
    """Run the fp32 program over the calibration batches fetching the
    mul ops' input vars; returns {var_name: [rows, K] fp32}.  Best
    effort — any failure degrades to weight-only calibration."""
    import paddle_tpu.fluid as fluid
    if not calib_feeds or not wanted:
        return {}
    acc = {n: [] for n, _ in wanted}
    try:
        with fluid.scope_guard(scope):
            for feed in list(calib_feeds)[:max_batches]:
                outs = exe.run(program, feed=dict(feed),
                               fetch_list=[n for n, _ in wanted])
                for (name, xd), val in zip(wanted, outs):
                    a = np.asarray(val, dtype=np.float32)
                    lead = int(np.prod(a.shape[:xd])) if xd > 0 else 1
                    acc[name].append(a.reshape(lead, -1))
    except Exception:
        return {}
    return {n: np.concatenate(v, axis=0) for n, v in acc.items() if v}


def _fetch_outputs(exe, scope, program, calib_feeds, fetch_names,
                   max_batches):
    outs = []
    import paddle_tpu.fluid as fluid
    with fluid.scope_guard(scope):
        for feed in list(calib_feeds)[:max_batches]:
            outs.append([np.asarray(o) for o in exe.run(
                program, feed=dict(feed), fetch_list=list(fetch_names))])
    return outs


def _accuracy_delta(fp32_outs, q_outs):
    """Pinned per-fetch delta between the fp32 and quantized artifacts
    on the calibration batches: max |delta|, mean |delta|, and (for
    class-prob-shaped fetches) top-1 agreement."""
    deltas = {"max_abs": 0.0, "mean_abs": 0.0}
    n, mean_sum = 0, 0.0
    agree, total = 0, 0
    for ref_batch, q_batch in zip(fp32_outs, q_outs):
        for ref, q in zip(ref_batch, q_batch):
            ref = np.asarray(ref, np.float32)
            q = np.asarray(q, np.float32)
            if ref.shape != q.shape:
                return {"error": "fetch shape changed: %s vs %s"
                        % (ref.shape, q.shape)}
            d = np.abs(ref - q)
            deltas["max_abs"] = max(deltas["max_abs"],
                                    float(d.max()) if d.size else 0.0)
            mean_sum += float(d.mean()) if d.size else 0.0
            n += 1
            if ref.ndim == 2 and ref.shape[1] > 1:
                agree += int((ref.argmax(1) == q.argmax(1)).sum())
                total += ref.shape[0]
    deltas["mean_abs"] = mean_sum / max(n, 1)
    if total:
        deltas["top1_agreement"] = agree / total
    return deltas


# ---------------------------------------------------------------------------
# the PTQ pass
# ---------------------------------------------------------------------------

def quantize_inference_model(src_dir, dst_dir=None, calib_feeds=None,
                             min_weight_elems=None, clip_ratios=None,
                             model_filename=None, params_filename=None):
    """Quantize a ``save_inference_model`` artifact dir into a sibling
    int8 artifact; returns a summary dict (dst, per-layer table, byte
    counts, calibration deltas).

    `calib_feeds`: iterable of feed dicts (name -> batch array) — at
    most ``FLAGS.quantize_calib_batches`` are consumed for the scale
    search and the accuracy-delta measurement.  Without them the scales
    are weight-space absmax/MSE and no delta is recorded."""
    import paddle_tpu.fluid as fluid
    from ..flags import FLAGS
    from ..fluid.framework import Program
    from ..fluid import core as fcore
    from ..native import wire

    if params_filename is not None:
        raise ValueError(
            "combined params_filename artifacts are not supported by "
            "the PTQ pass; re-save with one file per var")
    min_elems = FLAGS.quantize_min_weight_elems \
        if min_weight_elems is None else int(min_weight_elems)
    max_batches = max(int(FLAGS.quantize_calib_batches), 1)
    clip_ratios = tuple(clip_ratios or (1.0, 0.95, 0.9, 0.8))
    src_dir = os.path.abspath(src_dir)
    dst_dir = os.path.abspath(dst_dir) if dst_dir \
        else src_dir.rstrip("/\\") + "_int8"

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        program, feed_names, fetch_vars = fluid.load_inference_model(
            src_dir, exe, model_filename=model_filename)
    fetch_names = [v.name for v in fetch_vars]
    gb = program.global_block()

    # -- pick candidates (a weight consumed by ANY unsupported op must
    #    stay fp32: its var dtype cannot be two things at once) --------
    consumers = {}
    for op in gb.ops:
        for name in op.input_arg_names:
            consumers.setdefault(name, []).append(op)
    candidates = []        # (op_index, op, weight_name, spec)
    weights = {}           # weight_name -> spec (dedup for shared weights)
    for idx, op in enumerate(gb.ops):
        hit = _supported(op, gb, scope, min_elems)
        if hit is None:
            continue
        wname, spec = hit
        if any(_CANDIDATES.get(c.type) is None
               for c in consumers.get(wname, ())):
            continue
        prev = weights.get(wname)
        if prev is not None and prev != spec:
            continue  # same weight feeding mul AND conv: leave fp32
        weights[wname] = spec
        candidates.append((idx, op, wname, spec))

    # -- calibration activations for the fc (mul) layers ---------------
    wanted = []
    for idx, op, wname, spec in candidates:
        if op.type == "mul":
            xd = int(op.attrs.get("x_num_col_dims", 1))
            wanted.append((op.inputs["X"][0], xd))
    acts = _capture_activations(exe, scope, program, calib_feeds,
                                sorted(set(wanted)), max_batches)

    # -- quantize every candidate weight --------------------------------
    layers = []
    q_arrays = {}          # weight_name -> int8 array
    s_arrays = {}          # scale var name -> fp32 scale array
    fp32_bytes = 0
    quant_bytes = 0
    act_by_weight = {}
    for idx, op, wname, spec in candidates:
        if wname in q_arrays:
            layers.append({"op_index": idx, "op_type": op.type,
                           "weight": wname, "shared": True})
            continue
        slot, reduce_axes, ch_axis = spec
        w = np.asarray(scope.get(wname), dtype=np.float32)
        layer_acts = None
        if op.type == "mul":
            layer_acts = acts.get(op.inputs["X"][0])
        err, ratio, scale, q = _pick_scale(w, reduce_axes, ch_axis,
                                           clip_ratios, acts=layer_acts)
        sname = wname + "@scale"
        q_arrays[wname] = q
        s_arrays[sname] = scale.astype(np.float32)
        fp32_bytes += w.nbytes
        quant_bytes += q.nbytes + scale.nbytes
        layers.append({
            "op_index": idx, "op_type": op.type, "weight": wname,
            "scale": sname, "shape": list(w.shape),
            "clip_ratio": ratio, "mse": err,
            "calibrated": layer_acts is not None,
        })

    if not q_arrays:
        raise ValueError(
            "nothing to quantize in %r: no supported weight at or above "
            "the %d-element floor (FLAGS.quantize_min_weight_elems)"
            % (src_dir, min_elems))

    # -- rewrite the program --------------------------------------------
    from ..ops.quant_ops import quantized_op_for
    serialized_src = program.serialize_to_string()
    q_program = Program.parse_from_string(serialized_src)
    qgb = q_program.global_block()
    int8_dtype = fcore.convert_np_dtype_to_dtype_(np.int8)
    for idx, op, wname, spec in candidates:
        qop = qgb.ops[idx]
        qop.type = quantized_op_for(op.type)
        qop.inputs["Scale"] = [wname + "@scale"]
        qop.attrs["act_dtype"] = "bfloat16"
        qop.attrs["quant_axis"] = int(spec[2])
    for wname in q_arrays:
        qgb.vars[wname].dtype = int8_dtype
    for sname, scale in s_arrays.items():
        qgb.create_var(name=sname, shape=list(scale.shape),
                       dtype="float32", persistable=True)
    serialized = q_program.serialize_to_string()
    # build-time verification (ANALYSIS.md): a broken rewrite fails HERE
    # with named diagnostics, not in whatever server loads the artifact
    from ..analysis import check_serialized_cached
    check_serialized_cached(q_program, serialized, feeds=feed_names,
                            fetches=fetch_names,
                            what="quantize_inference_model(%r)" % dst_dir)

    # -- quantized persistable value set --------------------------------
    values = {}
    for v in qgb.vars.values():
        if not v.persistable:
            continue
        if v.name in q_arrays:
            values[v.name] = q_arrays[v.name]
        elif v.name in s_arrays:
            values[v.name] = s_arrays[v.name]
        else:
            val = scope.get(v.name)
            if val is not None:
                values[v.name] = np.asarray(val)

    # -- pinned accuracy delta on the calibration batches ---------------
    calibration = {"batches": 0}
    if calib_feeds:
        fp32_outs = _fetch_outputs(exe, scope, program, calib_feeds,
                                   fetch_names, max_batches)
        q_scope = fluid.Scope()
        import jax.numpy as jnp
        for name, arr in values.items():
            q_scope.set(name, jnp.asarray(arr))
        q_outs = _fetch_outputs(exe, q_scope, q_program, calib_feeds,
                                fetch_names, max_batches)
        calibration = _accuracy_delta(fp32_outs, q_outs)
        calibration["batches"] = min(len(list(calib_feeds)), max_batches)

    # -- commit the artifact (vault discipline) -------------------------
    from ..fluid import checkpoint as ckpt
    parent = os.path.dirname(dst_dir) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = "%s.tmp.%d.%x" % (dst_dir, os.getpid(),
                            threading.get_ident())
    # sweep stale in-flight dirs of THIS dst (a quantizer killed
    # mid-write leaves one; the next commit is the crash repair)
    base = os.path.basename(dst_dir) + ".tmp."
    for name in os.listdir(parent):
        if name.startswith(base):
            shutil.rmtree(os.path.join(parent, name),
                          ignore_errors=True)
    os.makedirs(tmp)

    def _write(fname, data, mode="wb"):
        path = os.path.join(tmp, fname)
        with open(path, mode) as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return path

    crcs = {}
    for name, arr in values.items():
        fname = name.replace("/", "__") + ".npy"
        data = ckpt._npy_bytes(np.ascontiguousarray(arr))
        _write(fname, data)
        if name in q_arrays or name in s_arrays:
            crcs[fname] = binascii.crc32(data) & 0xFFFFFFFF
    meta = {
        "schema": SCHEMA_VERSION,
        "precision": "int8",
        "act_dtype": "bfloat16",
        "layers": layers,
        "crc32": crcs,
        "bytes": {
            "fp32_weight_bytes": int(fp32_bytes),
            "quant_weight_bytes": int(quant_bytes),
            "ratio": round(quant_bytes / max(fp32_bytes, 1), 4),
        },
        "source": {
            "dir": src_dir,
            "program_sha256": hashlib.sha256(
                serialized_src.encode()).hexdigest(),
        },
        "calibration": calibration,
        "min_weight_elems": int(min_elems),
        "clip_ratios": list(clip_ratios),
    }
    _write(QUANT_META, wire.encode(meta))
    _write(model_filename or "__model__", json.dumps({
        "program": serialized,
        "feed_names": list(feed_names),
        "fetch_names": fetch_names,
    }).encode())
    ckpt._fsync_dir(tmp)
    _chaos("quant_arrays_written")
    if os.path.isdir(dst_dir):
        # re-quantize over a prior artifact: move it aside only now —
        # every byte of the replacement is already durable in tmp
        trash = dst_dir + ".old.%d" % os.getpid()
        os.rename(dst_dir, trash)
        shutil.rmtree(trash, ignore_errors=True)
    os.rename(tmp, dst_dir)
    _chaos("quant_committed")
    ckpt._fsync_dir(parent)

    return {
        "dst": dst_dir,
        "layers": layers,
        "bytes": dict(meta["bytes"]),
        "calibration": dict(calibration),
        "n_quantized": len(q_arrays),
    }


# ---------------------------------------------------------------------------
# artifact inspection / verification
# ---------------------------------------------------------------------------

def is_quantized_dir(dirname):
    return os.path.exists(os.path.join(dirname, QUANT_META))


def artifact_precision(dirname):
    """'int8' for a quantized artifact dir, 'fp32' otherwise — the
    precision axis the serving registry files a load under."""
    if is_quantized_dir(dirname):
        meta = read_quant_meta(dirname)
        return str(meta.get("precision", "int8"))
    return "fp32"


def read_quant_meta(dirname):
    from ..native import wire
    path = os.path.join(dirname, QUANT_META)
    with open(path, "rb") as f:
        return wire.decode(f.read())


def verify_quantized_dir(dirname):
    """CRC-walk the quantized payloads (int8 weights + scale tables)
    against the quant_meta.bin table; returns [(file, error-or-None)]
    — the list tools/verify_quantized.py renders."""
    try:
        meta = read_quant_meta(dirname)
    except Exception as e:
        return [(QUANT_META, "does not decode: %s: %s"
                 % (type(e).__name__, e))]
    if meta.get("schema") != SCHEMA_VERSION:
        return [(QUANT_META, "schema %r (this build reads %d)"
                 % (meta.get("schema"), SCHEMA_VERSION))]
    out = []
    for fname, want in sorted((meta.get("crc32") or {}).items()):
        path = os.path.join(dirname, fname)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            out.append((fname, "missing payload file (%s)" % e))
            continue
        got = binascii.crc32(data) & 0xFFFFFFFF
        if got != int(want):
            out.append((fname, "failed CRC32 (manifest %08x != file "
                        "%08x)" % (int(want), got)))
        else:
            out.append((fname, None))
    if not out:
        out.append((QUANT_META, "empty CRC table — no quantized "
                    "payloads recorded"))
    return out


def check_quantized_dir(dirname):
    """Load-boundary gate: raise QuantizedArtifactError naming the
    first corrupt int8 payload / scale table.  fluid.io.
    load_inference_model calls this for every quant_meta.bin dir, so a
    tampered quantized artifact is rejected before any weight loads."""
    for fname, err in verify_quantized_dir(dirname):
        if err is not None:
            raise QuantizedArtifactError(
                "quantized artifact %s: %s: %s" % (dirname, fname, err))
