"""Inference predictor — the serving layer.

Reference analogue: paddle/fluid/inference/api/ — `PaddlePredictor` /
`CreatePaddlePredictor` (paddle_api.h:134,:204), `NativePaddlePredictor`
(api_impl.cc:95 creates an Executor over the loaded program; Run at :135),
and `AnalysisPredictor` (analysis_predictor.cc) which runs the analysis pass
pipeline + TensorRT subgraph slicing before the same run loop.

TPU redesign: XLA *is* the analysis layer. NativeConfig -> load + jit the
pruned inference program; AnalysisConfig additionally runs the
InferenceTranspiler rewrites (BN fold, dropout removal — the ir/ fusion
passes whose effect XLA cannot replicate because they rewrite *weights*)
then AOT-compiles with jax.jit(...).lower(...).compile(), the TensorRT
engine analogue. Batch-size bucketing bounds recompiles the way TRT
profiles bounded engine shapes.
"""

import threading
import warnings

import numpy as np

__all__ = ["NativeConfig", "AnalysisConfig", "PaddleTensor", "Predictor",
           "create_paddle_predictor", "AotPredictor",
           "load_aot_predictor"]


# sentinel in the shared export map: this program cannot ride the
# export/serialize path (host callbacks, exotic lowering) — every
# replica falls back to direct compilation without retrying the export
_UNEXPORTABLE = object()

# mesh placements an AotPredictor has already warned about degrading
# (once per mesh label per process, not once per replica build)
_AOT_MESH_WARNED = set()


def _aot_degrade_mesh(device):
    """Serialized AOT exports carry a single-device calling convention —
    they cannot run sharded.  A mesh placement degrades LOUDLY (warn
    once per mesh) to the group's primary member so the artifact still
    serves; use Predictor/GenerativePredictor artifacts for real mesh
    replicas (SERVING.md "Mesh replicas")."""
    group = _mesh_of(device)
    if group is None:
        return device
    lbl = group.label()
    if lbl not in _AOT_MESH_WARNED:
        _AOT_MESH_WARNED.add(lbl)
        warnings.warn(
            "AOT artifacts cannot shard across a mesh — replica "
            "placement %s degrades to its primary member %s (serialized "
            "exports have a single-device calling convention; serve a "
            "Program or decode artifact to use the mesh)"
            % (lbl, _device_label(group.primary)),
            RuntimeWarning, stacklevel=3)
    return group.primary


def _amp_enabled():
    from paddle_tpu.ops.registry import amp_enabled
    return bool(amp_enabled())


def _var_is_batch_major(gb, name):
    """True when the program var's recorded shape leads with -1 — the
    marker save_aot already persists for AOT artifacts; the live
    Predictor reads the same ground truth instead of guessing from
    runtime shapes."""
    v = gb._find_var_recursive(name)
    return bool(v is not None and v.shape is not None
                and len(v.shape) >= 1 and int(v.shape[0]) == -1)


class PaddleTensor:
    """Loose analogue of paddle_api.h PaddleTensor (name + data)."""

    def __init__(self, data, name=None, lod=None):
        self.data = np.asarray(data)
        self.name = name
        self.lod = lod or []

    @property
    def shape(self):
        return self.data.shape


class NativeConfig:
    """reference paddle_api.h NativeConfig."""

    def __init__(self, model_dir=None, prog_file=None, param_file=None,
                 use_gpu=False, device=0):
        self.model_dir = model_dir
        self.prog_file = prog_file
        self.param_file = param_file
        self.use_gpu = use_gpu  # accepted for parity; backend is jax's
        self.device = device


class AnalysisConfig(NativeConfig):
    """reference analysis_predictor: adds graph rewrites + AOT compile."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.ir_optim = True
        self.aot_compile = True
        self.batch_size_buckets = (1, 2, 4, 8, 16, 32, 64, 128)


def _device_label(device):
    """Stable wire-encodable device id ('cpu:0', 'tpu:3' — or the
    '+'-joined member list 'tpu:0+tpu:1' for a mesh group) for metrics
    and the per-replica stats the serving layer surfaces; 'default' when
    the predictor floats on jax's default device.  Mesh labels parse
    back through `model_registry.resolve_placement`, which is what lets
    a persisted lane spec replay a mesh placement verbatim."""
    if device is None:
        return "default"
    group = _mesh_of(device)
    if group is not None:
        return group.label()
    return "%s:%d" % (getattr(device, "platform", "dev"),
                      getattr(device, "id", 0))


def _mesh_of(device):
    """The device as a MeshGroup, or None for a plain device."""
    from paddle_tpu.parallel.mesh import as_mesh_group
    return as_mesh_group(device)


def _put_state(state, device):
    """Commit a param dict to its placement: plain device -> device_put;
    mesh group -> every param SHARDED AT REST over the mesh
    (`MeshGroup.param_sharding` — per-device resident bytes ~
    1/mesh_size, the whole point of a mesh replica)."""
    import jax
    group = _mesh_of(device)
    if group is not None:
        return {n: jax.device_put(np.asarray(v),
                                  group.param_sharding(np.shape(v)))
                for n, v in state.items()}
    return {n: jax.device_put(np.asarray(v), device)
            for n, v in state.items()}


def _put_state_tp(state, group):
    """Tensor-parallel at-rest placement (SERVING.md "Tensor-parallel
    compute"): every NAMED decode parameter lands on the mesh axis its
    role in the partitioned program dictates (`MeshGroup.
    tp_param_sharding` — column weights split output columns, row
    weights split input rows, the embedding splits vocab rows) instead
    of `param_sharding`'s any-divisible-axis scan.  Resident bytes stay
    ~1/mesh_size like shard-at-rest; the difference is the compute
    consumes these shards IN PLACE — no gather per dispatch."""
    import jax
    return {n: jax.device_put(np.asarray(v),
                              group.tp_param_sharding(n, np.shape(v)))
            for n, v in state.items()}


def _put_feed(arr, device):
    """Commit one feed/arg to its placement (replicated on every mesh
    member — feeds are small; the sharded thing is the resident
    state)."""
    import jax
    group = _mesh_of(device)
    if group is not None:
        return jax.device_put(arr, group.replicated())
    return jax.device_put(arr, device)


def _mesh_wrap(math_fn, group, kv_outputs=False):
    """The mesh-replica compute contract (SERVING.md "Mesh replicas"):
    gather every operand back to REPLICATED before any math runs, so the
    traced computation is identical on every member and no float
    reduction ever reorders across devices — a mesh replica's output is
    bit-exact vs a single-device replica by construction (the
    weight-update-sharding blueprint: HBM shards, math does not).

    `kv_outputs=True` re-shards 5-D outputs (the decode KV slot tables)
    back to their at-rest `kv_sharding` before returning, so the
    session-resident cache stays ~1/mesh_size per device between
    dispatches; everything else returns replicated."""
    import jax

    def _rep(x):
        return jax.lax.with_sharding_constraint(x, group.replicated())

    def _out(x):
        if kv_outputs and getattr(x, "ndim", 0) == 5:
            return jax.lax.with_sharding_constraint(
                x, group.kv_sharding(x.shape))
        return _rep(x)

    def wrapped(state, *args):
        state = jax.tree_util.tree_map(_rep, state)
        args = jax.tree_util.tree_map(_rep, args)
        return jax.tree_util.tree_map(_out, math_fn(state, *args))

    return wrapped


def _mesh_wrap_tp(math_fn, group):
    """Partitioned-compute contract for PROGRAM predictors under
    `FLAGS.mesh_tp` (SERVING.md "Tensor-parallel compute"): instead of
    gathering operands to replicated, PIN the resident at-rest
    shardings on the state and let XLA's SPMD partitioner run the math
    over the shards — a contraction against a sharded weight computes
    on local columns/rows with the partitioner inserting the reduce,
    so weights never materialize unsharded and per-dispatch HBM
    traffic per member drops ~1/mesh_size.  Feeds and outputs stay
    replicated (the serving wire is host-side either way).  Outputs
    agree with a single-device replica at float tolerance, not
    bit-exactly (partitioned reductions reorder), which is exactly why
    the flag gates it; the decode path (inference/decode.py) carries
    the explicit shard_map'd program and the top-1 pins."""
    import jax

    def _rep(x):
        return jax.lax.with_sharding_constraint(x, group.replicated())

    def wrapped(state, *args):
        state = {n: jax.lax.with_sharding_constraint(
            x, group.param_sharding(np.shape(x)))
            for n, x in state.items()}
        args = jax.tree_util.tree_map(_rep, args)
        return jax.tree_util.tree_map(_rep, math_fn(state, *args))

    return wrapped


class Predictor:
    """`device`: optional jax.Device this predictor is pinned to — its
    params are `jax.device_put` there, feeds are committed there per
    run, and every bucket executable AOT-compiles for it.  The serving
    registry places one replica Predictor per device this way (SERVING.md
    multi-chip serving); None keeps jax's default-device behavior."""

    def __init__(self, config, device=None):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import functionalizer

        self._config = config
        self._scope = fluid.Scope()
        self._exe = fluid.Executor(
            fluid.TPUPlace(config.device) if _tpu_available()
            else fluid.CPUPlace())
        with fluid.scope_guard(self._scope):
            program, feed_names, fetch_vars = fluid.load_inference_model(
                config.model_dir, self._exe,
                model_filename=config.prog_file,
                params_filename=config.param_file)
            if isinstance(config, AnalysisConfig) and config.ir_optim:
                fluid.InferenceTranspiler().transpile(program,
                                                      scope=self._scope)
        from paddle_tpu.flags import FLAGS
        if FLAGS.verify_program:
            # load_inference_model already verified the artifact; this
            # re-checks AFTER the transpiler rewrites (BN fold, fusion)
            # — a buggy rewrite is exactly what the shape pass catches
            from paddle_tpu.analysis import check_program
            check_program(program, feeds=feed_names,
                          fetches=[v.name for v in fetch_vars],
                          what="predictor program (post-transpile)")
        self._program = program
        # the numerics lane this artifact serves (QUANTIZE.md): 'int8'
        # when the PTQ pass rewrote its contractions to dequant_* ops,
        # else 'fp32'.  Read from the program (not the dir) so clones
        # and registry replicas agree by construction.
        self._precision = "int8" if any(
            op.type.startswith("dequant_")
            for op in program.global_block().ops) else "fp32"
        self._feed_names = list(feed_names)
        self._fetch_names = [v.name for v in fetch_vars]
        self._fetch_vars = fetch_vars
        self._state_names = tuple(
            functionalizer.persistable_names(program))
        self._state = {n: self._scope.get(n) for n in self._state_names
                       if self._scope.get(n) is not None}
        self._device = device
        if device is not None:
            self._state = _put_state(self._state, device)
        self._compiled = {}  # feed shape signature -> compiled fn
        # serializes compile-and-cache and the overflow warn-once set:
        # concurrent dispatch lanes must neither double-compile one
        # bucket signature nor double-warn one overflow size
        self._lock = threading.Lock()
        # (device_kind, sig) -> jitted exported call, SHARED BY REFERENCE
        # across clone()/clone_to() replicas: N replicas of the same
        # device kind deserialize/export one executable, not N
        # (COMPILE_CACHE.md). _UNEXPORTABLE marks programs the export
        # path cannot serve (fall back to lower().compile() once, not
        # once per replica).
        self._shared_exports = {}
        self._shared_lock = threading.Lock()
        self._program_fp = None  # lazy sha256 of the transpiled program
        # batch-major markers from the program vars (-1 leading dim),
        # the same ground truth save_aot records in aot_meta.bin: only
        # these feeds get bucket-padded and only these fetches un-padded
        gb = program.global_block()
        self._batched_feed = {n: _var_is_batch_major(gb, n)
                              for n in self._feed_names}
        self._fetch_batched = [_var_is_batch_major(gb, n)
                               for n in self._fetch_names]
        self._overflow_warned = set()

    # ------------------------------------------------------------------
    def _device_kind(self):
        """Executable-compatibility label of this replica's target: two
        replicas with the same kind can share one AOT executable."""
        import jax
        d = self._device
        if d is None:
            devs = jax.devices()
            d = devs[0] if devs else None
        return "%s/%s" % (getattr(d, "platform", "cpu"),
                          getattr(d, "device_kind", ""))

    def _build_fwd(self, feed_names):
        from paddle_tpu.fluid import functionalizer
        step_fn = functionalizer.build_step_fn(
            self._program, tuple(feed_names),
            tuple(self._fetch_names), ())

        def fwd(state, feed_dict):
            fetches, _ = step_fn(state, feed_dict, np.uint32(0))
            return fetches

        group = _mesh_of(self._device)
        if group is not None:
            from paddle_tpu.flags import FLAGS
            if FLAGS.mesh_tp:
                return _mesh_wrap_tp(fwd, group)
            return _mesh_wrap(fwd, group)
        return fwd

    def _aot_fingerprint(self, feeds):
        from paddle_tpu import compile_cache as cc
        if self._program_fp is None:
            self._program_fp = cc.program_fingerprint(self._program)
        return {
            "kind": "predictor_aot",
            "program": self._program_fp,
            "feeds": cc._spec_sig(feeds),
            "fetches": list(self._fetch_names),
            "state": cc._spec_sig(self._state),
            "amp": _amp_enabled(),
            # the numerics lane is an explicit fingerprint field: an
            # int8 and an fp32 build of the same model must NEVER share
            # an executable, whatever else collides (COMPILE_CACHE.md)
            "precision": self._precision,
            "env": cc.environment_fingerprint(self._device),
        }

    def _get_aot_fn(self, sig, feeds):
        """Cached-executable resolution for the AnalysisConfig AOT path
        (called under self._lock).  Order: in-process shared map (one
        deserialize per device kind across all replica clones) -> the
        persistent store (hit: deserialize, no trace/lower) -> fresh
        export (miss: trace+lower once, serialize, commit).  Any failure
        returns None and the caller falls back to the legacy
        lower().compile() — the cache can only ever cost a recompile."""
        import time as _time
        import jax
        from paddle_tpu import compile_cache as cc
        if not cc.cache_enabled():
            return None
        if _mesh_of(self._device) is not None:
            # meshed replicas compile directly (lower().compile() against
            # the sharded state): a serialized export has no sharding in
            # its calling convention, so a cached single-device blob
            # would silently gather the whole model onto one member.
            # _device_kind carries a '/meshN' suffix, so nothing meshed
            # ever namespace-collides with a single-device executable.
            return None
        if self._device is not None and \
                self._device.platform != jax.default_backend():
            # cross-platform pinning (e.g. a cpu replica on a tpu host):
            # trace-time kernel dispatch follows the default backend, so
            # an export here could embed the wrong lowering — keep the
            # legacy per-device compile for this exotic case
            return None
        skey = (self._device_kind(), sig)
        with self._shared_lock:
            ent = self._shared_exports.get(skey)
        if ent is _UNEXPORTABLE:
            return None
        if ent is not None:
            return ent
        from jax import export as jax_export
        cache = cc.default_cache()
        fn = None
        try:
            fp = self._aot_fingerprint(feeds)
            blob = cache.get(fp) if cache is not None else None
            if blob is not None:
                try:
                    t0 = _time.monotonic()
                    exp = jax_export.deserialize(blob)
                    fn = jax.jit(exp.call)
                    cc.note_deserialize_ms(
                        (_time.monotonic() - t0) * 1000.0)
                except Exception:
                    blob = None  # truncated/alien entry: recompile
            if fn is None:
                t0 = _time.monotonic()
                fwd = self._build_fwd(sorted(feeds))
                state_spec = {
                    n: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                    for n, v in self._state.items()}
                feeds_spec = {
                    n: jax.ShapeDtypeStruct(np.shape(v), v.dtype)
                    for n, v in feeds.items()}
                exp = jax_export.export(jax.jit(fwd))(state_spec,
                                                      feeds_spec)
                cc.note_compile_ms((_time.monotonic() - t0) * 1000.0)
                if cache is not None:
                    cache.put(fp, exp.serialize())
                fn = jax.jit(exp.call)
        except Exception as e:
            with self._shared_lock:
                already = self._shared_exports.get(skey)
                self._shared_exports[skey] = _UNEXPORTABLE
            if already is not _UNEXPORTABLE:
                warnings.warn(
                    "compile cache disabled for this program (export "
                    "failed: %s: %s) — falling back to direct "
                    "compilation" % (type(e).__name__, e),
                    RuntimeWarning, stacklevel=3)
            return None
        with self._shared_lock:
            self._shared_exports[skey] = fn
        return fn

    def _get_compiled(self, feeds):
        import jax
        sig = tuple((n, feeds[n].shape, str(feeds[n].dtype))
                    for n in sorted(feeds))
        fn = self._compiled.get(sig)
        if fn is not None:
            return fn
        with self._lock:
            # re-check under the lock: another dispatch lane may have
            # compiled this signature while we waited — without the
            # recheck both lanes would pay the compile and the loser's
            # executable would be silently thrown away
            fn = self._compiled.get(sig)
            if fn is not None:
                return fn
            aot = isinstance(self._config, AnalysisConfig) and \
                self._config.aot_compile
            jitted = self._get_aot_fn(sig, feeds) if aot else None
            if jitted is None:
                jitted = jax.jit(self._build_fwd(sorted(feeds)))
                if aot:
                    # AOT: lower+compile now so first Run has no compile
                    # stall (the TRT build-engine-at-init analogue); with
                    # `self._state` committed to this replica's device,
                    # the executable compiles for that device
                    jitted = jitted.lower(self._state, feeds).compile()
            self._compiled[sig] = jitted
            return jitted

    def _bucket_cap(self, b):
        """Smallest configured batch bucket >= b, or None when bucketing
        is off (NativeConfig) or `b` overflows every bucket.  The
        overflow fall-through compiles a one-off computation per exact
        size — fine for a notebook, a recompile storm in serving — so it
        warns ONCE per overflow size, naming it."""
        if not isinstance(self._config, AnalysisConfig):
            return None
        buckets = self._config.batch_size_buckets
        for cap in buckets:
            if b <= cap:
                return cap
        if b not in self._overflow_warned:
            with self._lock:
                # re-check under the lock: concurrent dispatch lanes
                # racing the same overflow size must produce exactly one
                # warning, not one per lane
                if b in self._overflow_warned:
                    return None
                self._overflow_warned.add(b)
            warnings.warn(
                "batch %d exceeds every configured bucket %s on replica "
                "device [%s] — falling through to an unbucketed per-size "
                "compile; raise batch_size_buckets (or split the "
                "request) to avoid a recompile per distinct oversize "
                "batch in serving"
                % (b, tuple(buckets), _device_label(self._device)),
                RuntimeWarning, stacklevel=3)
        return None

    def _is_batched_feed(self, name):
        cached = self._batched_feed.get(name)
        if cached is None:
            cached = self._batched_feed[name] = _var_is_batch_major(
                self._program.global_block(), name)
        return cached

    def run(self, inputs):
        """inputs: dict name->array, list of PaddleTensor, or list of arrays
        (positional, matching the saved feed order). Returns list of numpy
        arrays in fetch order."""
        import jax.numpy as jnp
        from paddle_tpu.parallel.mesh import check_member_poison
        # a mesh replica dies whole: a lost member fails the dispatch
        # typed (MeshMemberLost) so the serving lane can mark itself
        # dead instead of wedging (chaos mesh-member-loss)
        check_member_poison(self._device)
        if isinstance(inputs, dict):
            named = {k: np.asarray(v) for k, v in inputs.items()}
        else:
            named = {}
            for i, t in enumerate(inputs):
                if isinstance(t, PaddleTensor):
                    named[t.name or self._feed_names[i]] = t.data
                else:
                    named[self._feed_names[i]] = np.asarray(t)

        # the batch is read from (and padding applied to) BATCH-MAJOR
        # feeds only — a fixed-shape side feed goes through untouched,
        # the same contract AotPredictor.run already enforces
        real_batch = next(
            (arr.shape[0] for name, arr in named.items()
             if arr.ndim >= 1 and self._is_batched_feed(name)), None)
        cap = self._bucket_cap(real_batch) if real_batch is not None \
            else None
        feeds = {}
        gb = self._program.global_block()
        for name, arr in named.items():
            v = gb._find_var_recursive(name)
            if v is not None and v.dtype is not None:
                want = v.np_dtype
                if arr.dtype != want:
                    arr = arr.astype(want)
            if cap is not None and cap > real_batch and \
                    self._is_batched_feed(name):
                pad = np.zeros((cap - real_batch,) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad], axis=0)
            if self._device is not None:
                # commit the feed to this replica's device (replicated
                # across a mesh group) so the computation runs there,
                # not on jax's default device
                feeds[name] = _put_feed(arr, self._device)
            else:
                feeds[name] = jnp.asarray(arr)

        fn = self._get_compiled(feeds)
        fetches = fn(self._state, feeds)
        out = []
        for i, f in enumerate(fetches):
            a = np.asarray(f)
            # un-pad only batch-major fetches (program-var -1 leading
            # dim), never a global output whose leading dim happens to
            # equal the padded bucket
            batched = (i < len(self._fetch_batched)
                       and self._fetch_batched[i])
            if cap is not None and cap > real_batch and batched and \
                    a.ndim >= 1 and a.shape[0] == cap:
                a = a[:real_batch]
            out.append(a)
        return out

    # C++-API-shaped alias
    Run = run

    def clone(self):
        """reference PaddlePredictor::Clone — share weights, new exec state."""
        p = object.__new__(Predictor)
        p._config = self._config
        p._scope = self._scope
        p._exe = self._exe
        p._program = self._program
        p._precision = self._precision
        p._feed_names = list(self._feed_names)
        p._fetch_names = list(self._fetch_names)
        p._fetch_vars = self._fetch_vars
        p._state_names = self._state_names
        p._state = self._state
        p._device = self._device
        p._compiled = {}
        p._lock = threading.Lock()
        # shared BY REFERENCE: replicas of the same device kind reuse
        # one exported executable instead of re-tracing per clone
        p._shared_exports = self._shared_exports
        p._shared_lock = self._shared_lock
        p._program_fp = self._program_fp
        p._batched_feed = dict(self._batched_feed)
        p._fetch_batched = list(self._fetch_batched)
        p._overflow_warned = set()
        return p

    def clone_to(self, device):
        """Replica placement: a clone whose param copy lives on `device`
        and whose bucket executables compile for it.  The Program parse
        + InferenceTranspiler work is shared (done once at load); only
        the device commit and the per-device compile cache are new —
        this is how the serving registry builds N device-resident
        replicas from one artifact load."""
        p = self.clone()
        p._device = device
        if device is not None:
            p._state = _put_state(self._state, device)
        return p

    @property
    def device(self):
        """The jax.Device this predictor is pinned to, or None."""
        return self._device

    @property
    def precision(self):
        """The numerics lane this predictor serves: 'fp32' or 'int8'
        (the serving registry's precision axis, QUANTIZE.md)."""
        return self._precision

    def resource_report(self, batch=None):
        """Static ResourceReport of the program THIS predictor actually
        serves — post-transpile, so BN folds / fusions / the PTQ
        dequant rewrite are priced as they will run (sharper than
        analysis.analyze_artifact, which reads the artifact as saved).
        `batch` defaults to the largest configured bucket."""
        from paddle_tpu.analysis import analyze_program
        if batch is None:
            buckets = self.batch_buckets()
            batch = buckets[-1] if buckets else 1
        return analyze_program(self._program, feeds=self._feed_names,
                               fetches=self._fetch_names, batch=batch,
                               device=self._device,
                               what="predictor(%s)"
                                    % (self._config.model_dir,))

    # ------------------------------------------------------------------
    # serving introspection (paddle_tpu/serving): the batcher needs the
    # same three facts from a live Predictor and an AotPredictor — batch
    # buckets, feed specs, batch-major markers — in one shape.
    # ------------------------------------------------------------------

    def batch_buckets(self):
        """Sorted batch-size buckets this predictor pads requests into;
        () when bucketing is off (NativeConfig)."""
        if isinstance(self._config, AnalysisConfig):
            return tuple(sorted(self._config.batch_size_buckets))
        return ()

    def feed_specs(self):
        """name -> (shape list with -1 dynamic dims, dtype str)."""
        gb = self._program.global_block()
        out = {}
        for name in self._feed_names:
            v = gb._find_var_recursive(name)
            out[name] = ([int(d) for d in v.shape],
                         str(np.dtype(v.np_dtype)))
        return out

    def batched_feed_names(self):
        return frozenset(n for n in self._feed_names
                         if self._is_batched_feed(n))

    def fetch_batched_flags(self):
        return list(self._fetch_batched)


    # ------------------------------------------------------------------
    # AOT export (VERDICT r3 #8 — native-callable inference).
    #
    # Decision note: the reference exposes a C++ `PaddlePredictor`
    # (paddle_api.h:134) because its runtime IS C++. Here the compiled
    # artifact is an XLA executable; a C ABI would have to embed either a
    # Python interpreter or the PJRT C API + StableHLO deserializer —
    # disproportionate plumbing that re-wraps what jax.export already
    # standardizes. So the native-serving contract is: `save_aot` writes
    # the serialized StableHLO modules (jax.export, versioned+stable) +
    # weights + metadata in the no-pickle wire format; `load_aot_predictor`
    # in a FRESH process deserializes and serves with NO Program rebuild
    # and NO jax trace (XLA compiles the stored module directly). Any
    # PJRT-capable host — including a C++ one via the PJRT C API — can
    # consume the same artifact.
    # ------------------------------------------------------------------

    def save_aot(self, dirname, batch_sizes=(1,), platforms=None):
        """Export the inference computation for the given batch sizes so
        a new process can serve without rebuilding or retracing.

        `platforms` selects the artifact's target(s): ("tpu",) CROSS-
        COMPILES from a CPU build host with the real Mosaic kernels
        embedded; ("cpu", "tpu") embeds both lowerings in one artifact
        but only for Pallas-free programs (jax lowers every
        platform_dependent branch on every platform when the platform
        index is dynamic, and Pallas has no non-interpret CPU
        lowering). Default: the current platform only."""
        import os
        import jax
        import jax.numpy as jnp
        from jax import export as jax_export
        from paddle_tpu.fluid import functionalizer
        from paddle_tpu.native import wire

        os.makedirs(dirname, exist_ok=True)
        if isinstance(platforms, str):
            # list("tpu") would become ['t','p','u'] and fail far away
            platforms = (platforms,)
        gb = self._program.global_block()
        feed_specs = {}
        for name in self._feed_names:
            v = gb._find_var_recursive(name)
            shape = [int(d) for d in v.shape]
            feed_specs[name] = (shape, str(np.dtype(v.np_dtype)))

        step_fn = functionalizer.build_step_fn(
            self._program, tuple(sorted(self._feed_names)),
            tuple(self._fetch_names), ())

        def fwd(state, feed_dict):
            fetches, _ = step_fn(state, feed_dict, np.uint32(0))
            return fetches

        state_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                              np.asarray(v).dtype)
                      for n, v in self._state.items()}
        for name, (shape, dt) in feed_specs.items():
            if any(d == -1 for d in shape[1:]):
                # same guard as train_export.save_aot_trainer: a
                # non-leading dynamic dim silently frozen to the batch
                # size would produce an artifact that rejects every
                # differently-shaped request at serve time
                raise ValueError(
                    "feed %r has non-batch dynamic dims %s — AOT export "
                    "needs static non-batch shapes" % (name, shape))
        exports = {}
        for bs in batch_sizes:
            feeds_spec = {}
            for name, (shape, dt) in feed_specs.items():
                s = [bs if d == -1 else d for d in shape]
                feeds_spec[name] = jax.ShapeDtypeStruct(
                    tuple(s), np.dtype(dt))
            from paddle_tpu.ops.pallas_kernels import mosaic_lowering
            # a pure-TPU target embeds the real Mosaic kernels even from
            # a CPU build host; any cpu target keeps interpret emulation
            with mosaic_lowering(bool(platforms)
                                 and "tpu" in platforms
                                 and "cpu" not in platforms):
                exp = jax_export.export(
                    jax.jit(fwd),
                    platforms=list(platforms) if platforms else None)(
                    state_spec, feeds_spec)
            fname = "aot_b%d.bin" % bs
            with open(os.path.join(dirname, fname), "wb") as f:
                f.write(exp.serialize())
            exports[str(bs)] = fname

        with open(os.path.join(dirname, "aot_state.bin"), "wb") as f:
            f.write(wire.encode({n: np.asarray(v)
                                 for n, v in self._state.items()}))
        # which fetches are batch-major (program var has a -1 leading
        # dim): only those get un-padded at serve time — a global output
        # whose leading dim merely EQUALS the padded bucket must come
        # back whole
        fetch_batched = []
        for name in self._fetch_names:
            v = gb._find_var_recursive(name)
            fetch_batched.append(
                bool(v is not None and v.shape is not None
                     and len(v.shape) >= 1 and int(v.shape[0]) == -1))
        meta = {
            "feed_names": list(self._feed_names),
            "fetch_names": list(self._fetch_names),
            "feed_specs": {n: {"shape": list(s), "dtype": d}
                           for n, (s, d) in feed_specs.items()},
            "fetch_batched": fetch_batched,
            "exports": exports,
            "platform": jax.default_backend(),
        }
        with open(os.path.join(dirname, "aot_meta.bin"), "wb") as f:
            f.write(wire.encode(meta))
        return dirname


class AotPredictor:
    """Serve a `save_aot` artifact: no Program, no trace — the stored
    StableHLO modules are deserialized and compiled directly by XLA.

    `device`: optional jax.Device to pin this instance to (state +
    per-run feeds committed there) — the replica-per-device serving
    placement; `clone_to` shares the deserialized modules across
    replicas so only the first replica pays the artifact read."""

    def __init__(self, dirname, device=None):
        import os
        from jax import export as jax_export
        from paddle_tpu.native import wire
        from paddle_tpu import compile_cache as cc

        if cc.cache_enabled():
            # the artifact IS a pre-serialized AOT cache; flipping the
            # store on points jax's persistent XLA cache at it, so even
            # the first .call per bucket skips the XLA compile on a
            # warm boot (counted as artifact_loads, not hits — the
            # hit/miss ratio stays about the fingerprint store)
            cc.default_cache()

        with open(os.path.join(dirname, "aot_meta.bin"), "rb") as f:
            meta = wire.decode(f.read())
        with open(os.path.join(dirname, "aot_state.bin"), "rb") as f:
            self._state = wire.decode(f.read())
        self._feed_names = list(meta["feed_names"])
        self._fetch_names = list(meta["fetch_names"])
        self._feed_specs = meta["feed_specs"]
        self._fetch_batched = meta.get("fetch_batched")
        self._fns = {}
        for bs, fname in sorted(meta["exports"].items(),
                                key=lambda kv: int(kv[0])):
            with open(os.path.join(dirname, fname), "rb") as f:
                self._fns[int(bs)] = jax_export.deserialize(
                    f.read()).call
        cc.note_artifact_load(len(self._fns))
        device = _aot_degrade_mesh(device)
        self._device = device
        if device is not None:
            import jax
            self._state = {n: jax.device_put(np.asarray(v), device)
                           for n, v in self._state.items()}

    def run(self, inputs):
        import jax.numpy as jnp
        if isinstance(inputs, dict):
            named = {k: np.asarray(v) for k, v in inputs.items()}
        else:
            named = {}
            for i, t in enumerate(inputs):
                if isinstance(t, PaddleTensor):
                    named[t.name or self._feed_names[i]] = t.data
                else:
                    named[self._feed_names[i]] = np.asarray(t)
        # the batch is read from (and padding applied to) BATCH-MAJOR
        # feeds only — those whose recorded var shape leads with -1; a
        # fixed-shape side feed must go through untouched
        batched_feed = {n: bool(spec["shape"]
                                and int(spec["shape"][0]) == -1)
                        for n, spec in self._feed_specs.items()}
        b = next((arr.shape[0] for name, arr in named.items()
                  if batched_feed.get(name)), None)
        if b is None:
            b = next(iter(named.values())).shape[0]
        cap = next((c for c in self._fns if c >= b), None)
        if cap is None:
            raise ValueError(
                "batch %d exceeds every exported batch size %s"
                % (b, sorted(self._fns)))
        feeds = {}
        for name, arr in named.items():
            want = np.dtype(self._feed_specs[name]["dtype"])
            if arr.dtype != want:
                arr = arr.astype(want)
            if cap > b and batched_feed.get(name):
                arr = np.concatenate(
                    [arr, np.zeros((cap - b,) + arr.shape[1:],
                                   arr.dtype)], axis=0)
            if self._device is not None:
                import jax
                feeds[name] = jax.device_put(arr, self._device)
            else:
                feeds[name] = jnp.asarray(arr)
        fetches = self._run_export(cap, feeds)
        out = []
        for i, f in enumerate(fetches):
            a = np.asarray(f)
            # un-pad only fetches the artifact marked batch-major — a
            # reduced/global output whose leading dim coincidentally
            # equals the padded bucket must come back whole. Artifacts
            # predating the marker fall back to the shape heuristic.
            if self._fetch_batched is not None:
                batched = (i < len(self._fetch_batched)
                           and self._fetch_batched[i])
            else:
                batched = a.ndim >= 1 and a.shape[0] == cap
            if cap > b and batched and a.ndim >= 1 and a.shape[0] == cap:
                a = a[:b]
            out.append(a)
        return out

    Run = run

    def _run_export(self, cap, feeds):
        """One seam around the stored executable call (tests inject
        slow/faulty models here without touching the jax.export path)."""
        return self._fns[cap](self._state, feeds)

    def clone_to(self, device):
        """Replica placement: share the deserialized StableHLO modules,
        re-commit the state copy to `device`."""
        import jax
        device = _aot_degrade_mesh(device)
        p = object.__new__(AotPredictor)
        p._feed_names = list(self._feed_names)
        p._fetch_names = list(self._fetch_names)
        p._feed_specs = self._feed_specs
        p._fetch_batched = self._fetch_batched
        p._fns = self._fns
        p._device = device
        if device is not None:
            p._state = {n: jax.device_put(np.asarray(v), device)
                        for n, v in self._state.items()}
        else:
            p._state = self._state
        return p

    @property
    def device(self):
        return self._device

    @property
    def precision(self):
        """AOT artifacts are exported from the fp32 path today; the
        attribute exists so the serving registry's precision axis reads
        one surface across predictor types."""
        return "fp32"

    # ---- serving introspection (mirrors Predictor's) ----

    def batch_buckets(self):
        return tuple(sorted(self._fns))

    def feed_specs(self):
        return {n: (list(spec["shape"]), str(spec["dtype"]))
                for n, spec in self._feed_specs.items()}

    def batched_feed_names(self):
        return frozenset(
            n for n, spec in self._feed_specs.items()
            if spec["shape"] and int(spec["shape"][0]) == -1)

    def fetch_batched_flags(self):
        if self._fetch_batched is None:
            return None  # pre-marker artifact: scatter falls back to shape
        return list(self._fetch_batched)


def load_aot_predictor(dirname):
    """Open a `Predictor.save_aot` artifact (fresh-process serving)."""
    return AotPredictor(dirname)


def _tpu_available():
    import jax
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except RuntimeError:
        return False


def create_paddle_predictor(config):
    """reference CreatePaddlePredictor (api_impl.cc:304)."""
    return Predictor(config)
