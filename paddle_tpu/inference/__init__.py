from .predictor import (
    NativeConfig, AnalysisConfig, PaddleTensor, Predictor,
    create_paddle_predictor, AotPredictor, load_aot_predictor,
)
from .decode import (
    GenerativePredictor, DecodeSession, SpeculativeDecodeSession,
    save_decode_model, build_tiny_decode_model, load_decode_predictor,
    greedy_decode, set_draft_poison, normalize_kv_dtype,
)
from .quantize import (
    quantize_inference_model, read_quant_meta, is_quantized_dir,
    verify_quantized_dir, check_quantized_dir, artifact_precision,
    QuantizedArtifactError,
)

__all__ = [
    "NativeConfig", "AnalysisConfig", "PaddleTensor", "Predictor",
    "create_paddle_predictor", "AotPredictor", "load_aot_predictor",
    "GenerativePredictor", "DecodeSession", "SpeculativeDecodeSession",
    "save_decode_model", "set_draft_poison", "normalize_kv_dtype",
    "build_tiny_decode_model", "load_decode_predictor", "greedy_decode",
    "quantize_inference_model", "read_quant_meta", "is_quantized_dir",
    "verify_quantized_dir", "check_quantized_dir", "artifact_precision",
    "QuantizedArtifactError",
]
