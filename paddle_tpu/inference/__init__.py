from .predictor import (
    NativeConfig, AnalysisConfig, PaddleTensor, Predictor,
    create_paddle_predictor, AotPredictor, load_aot_predictor,
)

__all__ = [
    "NativeConfig", "AnalysisConfig", "PaddleTensor", "Predictor",
    "create_paddle_predictor", "AotPredictor", "load_aot_predictor",
]
