"""Autoregressive generation: decode artifacts, prefill/decode phase
split, and the slot-table KV cache the serving layer batches over.

The one-shot Predictor serves classifier-shaped programs: fixed-shape
in, fixed-shape out, stateless between calls.  Generation breaks that
contract — each request carries growing state (the KV cache) across
many tiny steps, and the chip idles unless many requests decode
TOGETHER.  This module is the inference-side half of the answer
(SERVING.md "Continuous batching & streaming" is the serving half):

* a **decode artifact** (`save_decode_model` / `build_tiny_decode_model`)
  — a directory holding a causal-transformer LM's weights plus a meta
  record (vocab, layers, heads, max_seq_len, eos id, prefill buckets)
  in the typed wire format, detected by `decode_meta.bin` the way the
  AOT predictor is detected by `aot_meta.bin`;
* a **prefill / decode phase split** (`GenerativePredictor`): prefill
  runs the whole prompt through the causal forward once per padded
  *prompt bucket* (each bucket's executable rides the persistent
  compile cache, COMPILE_CACHE.md, so a warm boot deserializes instead
  of retracing), emitting the prompt's K/V and the first generated
  token; decode is ONE fixed-shape step function over the WHOLE slot
  table — XLA compiles it exactly once per (n_slots) geometry, and
  every later step, whatever mix of requests occupies the slots, reuses
  that executable;
* a **slot-indexed KV cache** (`DecodeSession`): [layers, n_slots,
  max_seq_len, heads, head_dim] arrays resident on the session's
  device.  A request owns one slot from prefill to finish; freeing a
  slot ZEROES its cache lines before reuse (no cross-request KV
  leakage — pinned by tests/test_decode_serving.py), and the decode
  step's cache writes are gated by the active mask so a dead slot
  stays zero.  Per-slot math is independent by construction, which is
  what makes batched decode bit-exact vs a single-request session:
  requests joining or leaving the running batch cannot move another
  request's tokens by one bit.

**Quantized KV cache** (QUANTIZE.md "Quantized KV cache"): decode is
HBM-bound and the slot table is its dominant byte stream — every step
re-reads the whole cache.  `kv_cache_dtype="int8"` (a `load_model` /
`decode_meta` knob, default FLAGS.serving_kv_cache_dtype) stores K/V
slots as int8 with per-(layer, head) symmetric fp32 scales calibrated
once per artifact from a deterministic probe prefill: cache WRITES
quantize in-graph (prefill, step, and verify all land
`clip(round(x / scale))` rows), and the decode/verify kernels stream
int8 tiles dequantized in-register (`ops/pallas_kernels.
decode_attention` — float KV never materializes in HBM), cutting cache
bytes 4x at equal slots.  The scales are baked constants of the traced
phases, and `kv_cache_dtype` is a compile-cache fingerprint field, so
fp32/int8 executables never collide.  Greedy int8 streams are
bit-stable against themselves (every row quantizes identically in
every path — the slot-reuse / rollback / spec-verify contracts all
survive unchanged); vs the fp32 cache they agree to quantization
error, not bit-exactly.

Decode attention gathers K/V from the slot cache through the Pallas
decode kernel (`ops/pallas_kernels.decode_attention` — block geometry
from the shared kernel-tuning registry); sampling is greedy argmax
(deterministic — the parity contract above is exact equality, not
"close").

**Speculative decoding** (`SpeculativeDecodeSession`, SERVING.md
"Speculative decoding"): a cheap *draft* GenerativePredictor (the int8
twin of the same artifact, or any vocab-compatible decode artifact)
autoregressively proposes k tokens per round, and the fp32 *target*
scores all k+1 positions in ONE fixed-shape batched verify step (its
executable is one new compile-cache fingerprint per (n_slots, k)).
The longest greedily-agreeing prefix commits to the target's KV slot
cache; rejected suffixes roll the slot's length pointer back with the
stale KV rows zeroed in-graph.  Greedy acceptance keeps the committed
stream BIT-IDENTICAL to the fp32-only plain-step stream: every emitted
token is a target argmax, and the verify step attends through the SAME
`decode_attention` kernel the plain step runs (each chunk position is
a pseudo-slot with its own length mask), so verify logits round
exactly like sequential step logits.  A draft failure mid-round
degrades the session to target-only plain decode within that same
step (`degraded`), never wedging or corrupting a stream.

**Fused multi-step decode** (SERVING.md "Fused multi-step decode"):
every plain decode step is one host->device dispatch, so at real
silicon step costs the HOST becomes the tokens/sec ceiling long
before the HBM roofline does.  `fused_step_fn(n_slots, n_steps)`
compiles up to N steps as ONE executable — a `lax.while_loop`
carrying {cache, lengths, last_tokens, running masks} through
step+argmax+KV-write per trip with in-graph early exit — and
`DecodeSession.decode_fused` drives it, returning a [n_slots,
n_steps] token block per dispatch.  The speculative path rides the
same discipline: `fused_spec_fn` runs k draft steps + batched verify
+ in-graph accept/rollback/catch-up as one dispatch
(`SpeculativeDecodeSession.step(fused=True)`).  Because the per-trip
body IS the plain step math and per-slot math is independent, fused
streams are bit-identical to N=1 streams token-for-token — the
serving layer (FLAGS.serving_decode_fuse_steps) moves slot
joins/leaves to window boundaries without moving a single token.
"""

import hashlib
import json
import os
import threading
import time
import warnings

import numpy as np

__all__ = ["GenerativePredictor", "DecodeSession",
           "SpeculativeDecodeSession", "save_decode_model",
           "build_tiny_decode_model", "load_decode_predictor",
           "greedy_decode", "set_draft_poison", "normalize_kv_dtype",
           "DECODE_META"]

DECODE_META = "decode_meta.bin"
_DECODE_STATE = "decode_state.bin"

# shared-map sentinel, same contract as predictor._UNEXPORTABLE: this
# function cannot ride the export/serialize path — every clone falls
# back to direct jit without retrying the export
_UNEXPORTABLE = object()

# chaos hook (tools/chaos.py spec-fallback scenario): once armed, the
# draft side of every SpeculativeDecodeSession raises after the given
# number of further draft steps — the in-process stand-in for a dead /
# poisoned draft predictor.  The session must degrade to target-only
# decode within the same round, bit-exact and un-wedged.
_DRAFT_POISON = {"after": None, "steps": 0}


def set_draft_poison(after_steps=0):
    """Arm (int: poison fires once `after_steps` more draft steps have
    run) or disarm (None) the draft-failure chaos injection."""
    _DRAFT_POISON["after"] = None if after_steps is None \
        else int(after_steps)
    _DRAFT_POISON["steps"] = 0


def _check_draft_poison():
    after = _DRAFT_POISON["after"]
    if after is None:
        return
    _DRAFT_POISON["steps"] += 1
    if _DRAFT_POISON["steps"] > after:
        raise RuntimeError("chaos: draft predictor poisoned "
                           "(set_draft_poison)")


def normalize_kv_dtype(value):
    """Canonical KV-cache dtype: ''/None/'fp32'/'f32'/'float32' ->
    'float32', 'int8' -> 'int8'; anything else is a typed error (the
    serving wire validates through this too)."""
    v = str(value or "").strip().lower()
    if v in ("", "fp32", "f32", "float32"):
        return "float32"
    if v == "int8":
        return "int8"
    raise ValueError(
        "unsupported kv_cache_dtype %r (expected float32|int8)"
        % (value,))


def _default_prefill_buckets(max_seq_len):
    """Powers of two up to max_seq_len (min 8): the prompt-length
    buckets prefill compiles for.  Deterministic by prompt length, so
    two decodes of the same prompt always ride the same executable —
    the bit-exactness contract leans on this."""
    buckets, b = [], 8
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_seq_len))
    return buckets


def save_decode_model(dirname, state, meta):
    """Write a decode artifact: `meta` (vocab_size, d_model, n_heads,
    n_layers, max_seq_len, eos_id, dtype, prefill_buckets) +  `state`
    (the weight dict) in the typed wire format — no pickle, same
    discipline as save_aot."""
    from paddle_tpu.native import wire
    os.makedirs(dirname, exist_ok=True)
    meta = dict(meta)
    meta.setdefault("arch", "causal_lm")
    meta.setdefault("version", 1)
    meta.setdefault("dtype", "float32")
    # the per-artifact KV-cache dtype pin (QUANTIZE.md "Quantized KV
    # cache"); load_model's kv_cache_dtype knob overrides per load,
    # and an artifact with NO pin defers to FLAGS.serving_kv_cache_dtype
    # at open time — so only normalize a pin the caller actually set
    if meta.get("kv_cache_dtype"):
        meta["kv_cache_dtype"] = normalize_kv_dtype(
            meta["kv_cache_dtype"])
    meta.setdefault("prefill_buckets",
                    _default_prefill_buckets(meta["max_seq_len"]))
    with open(os.path.join(dirname, _DECODE_STATE), "wb") as f:
        f.write(wire.encode({n: np.asarray(v) for n, v in state.items()}))
    with open(os.path.join(dirname, DECODE_META), "wb") as f:
        f.write(wire.encode(meta))
    return dirname


def build_tiny_decode_model(dirname, vocab_size=32, d_model=16,
                            n_heads=2, n_layers=2, max_seq_len=64,
                            eos_id=0, seed=7):
    """Deterministic random-weight tiny causal LM — the CPU-smoke /
    test fixture (the decode analogue of bench_serving's `fc` model).
    Same seed -> bit-identical artifact."""
    if d_model % n_heads:
        raise ValueError("d_model %d not divisible by n_heads %d"
                         % (d_model, n_heads))
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(d_model)

    def w(*shape):
        return (rng.randn(*shape) * scale).astype(np.float32)

    state = {"embed": w(vocab_size, d_model),
             "pos": w(max_seq_len, d_model),
             "lnf_g": np.ones(d_model, np.float32),
             "lnf_b": np.zeros(d_model, np.float32),
             "lm_head": w(d_model, vocab_size)}
    for i in range(n_layers):
        p = "l%d_" % i
        state[p + "ln1_g"] = np.ones(d_model, np.float32)
        state[p + "ln1_b"] = np.zeros(d_model, np.float32)
        state[p + "wq"] = w(d_model, d_model)
        state[p + "wk"] = w(d_model, d_model)
        state[p + "wv"] = w(d_model, d_model)
        state[p + "wo"] = w(d_model, d_model)
        state[p + "ln2_g"] = np.ones(d_model, np.float32)
        state[p + "ln2_b"] = np.zeros(d_model, np.float32)
        state[p + "w1"] = w(d_model, 4 * d_model)
        state[p + "b1"] = np.zeros(4 * d_model, np.float32)
        state[p + "w2"] = w(4 * d_model, d_model)
        state[p + "b2"] = np.zeros(d_model, np.float32)
    meta = {"vocab_size": int(vocab_size), "d_model": int(d_model),
            "n_heads": int(n_heads), "n_layers": int(n_layers),
            "max_seq_len": int(max_seq_len), "eos_id": int(eos_id)}
    return save_decode_model(dirname, state, meta)


def _ln(x, g, b):
    import jax.numpy as jnp
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * g + b


def _causal_attention(q, k, v, scale):
    """Prefill attention oracle: [B, T, H, D] causal, same finite-mask
    convention as the kernels."""
    import jax.numpy as jnp
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.arange(T)[None, :] < jnp.arange(T)[:, None] + 1
    s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)) \
        / jnp.maximum(jnp.sum(p, axis=-1), 1e-20).transpose(0, 2, 1)[
            ..., None]
    return o


class _TPContext:
    """Trace-time handle threaded through the phase math when the
    program lowers TENSOR-PARALLEL over a mesh replica (`FLAGS.mesh_tp`,
    SERVING.md "Tensor-parallel compute").  Inside the shard_map'd body
    every weight/KV operand is this member's LOCAL shard; the context
    carries the axis grammar plus the handful of collectives the
    Megatron split needs — one psum per column->row pair, one logits
    all_gather, the exact masked-gather+psum embedding lookup.
    `tp=None` (the default everywhere) keeps each math fn's trace
    byte-identical to the single-device program."""

    __slots__ = ("axis", "size")

    def __init__(self, size, axis=None):
        from paddle_tpu.parallel.mesh import MODEL_AXIS
        self.size = int(size)
        self.axis = axis or MODEL_AXIS

    def index(self):
        import jax
        return jax.lax.axis_index(self.axis)

    def psum(self, x):
        """Close one column->row-parallel pair: sum the members' partial
        products.  THE tolerance point of the TP contract — reduction
        order moves across members, so downstream activations agree
        with the single-device oracle at float tolerance, not
        bit-exactly (tests/test_mesh_tp.py pins top-1 agreement)."""
        import jax
        return jax.lax.psum(x, self.axis)

    def all_gather(self, x, axis):
        """Tiled all_gather (exact — pure data movement): reassembles
        the vocab-sharded logits for the replicated argmax."""
        import jax
        return jax.lax.all_gather(x, self.axis, axis=axis, tiled=True)

    def head_scales(self, scales, n_local):
        """This member's head block of a BAKED full-table kv-scale
        constant [..., H, 1] (sliced on axis -2 at a traced offset):
        the int8 quantize/dequant stays local to the resident heads."""
        import jax
        import jax.numpy as jnp
        full = jnp.asarray(scales, jnp.float32)
        off = self.index() * jnp.int32(n_local)
        return jax.lax.dynamic_slice_in_dim(full, off, int(n_local),
                                            axis=full.ndim - 2)

    def embed_lookup(self, embed_local, ids):
        """EXACT embedding gather over the vocab-row-sharded table
        (parallel/sharded_embedding.py's convention): each member
        gathers the ids it owns, contributes true zeros for the rest,
        and the psum adds exactly one nonzero term per row — 0 + v is
        exact in float, so no tolerance demotion here."""
        import jax.numpy as jnp
        vl = int(embed_local.shape[0])
        off = self.index() * jnp.int32(vl)
        local = ids - off
        ok = (local >= 0) & (local < vl)
        rows = embed_local[jnp.clip(local, 0, vl - 1)]
        return self.psum(jnp.where(ok[..., None], rows, 0.0))


class GenerativePredictor:
    """A decode artifact opened for serving: weights + meta + the two
    compiled phases (per-bucket prefill, one fixed-shape decode step
    per slot-table size).  `device` pins state and compute to one
    jax.Device — the serving registry's replica placement; `clone_to`
    shares the artifact read and the in-process export map so N
    same-device-kind replicas deserialize ONE executable each
    (COMPILE_CACHE.md).

    `kv_cache_dtype` picks the slot-table cache numerics per OPEN
    (explicit arg > the artifact's decode_meta pin >
    FLAGS.serving_kv_cache_dtype > float32); 'int8' calibrates
    per-(layer, head) scales once and every session this predictor
    vends quantizes its cache writes in-graph."""

    def __init__(self, dirname, device=None, kv_cache_dtype=None,
                 _clone_of=None):
        from paddle_tpu.native import wire
        if _clone_of is not None:
            src = _clone_of
            self.meta = src.meta
            self._state_host = src._state_host
            self._shared_exports = src._shared_exports
            self._shared_lock = src._shared_lock
            self._model_fp = src._model_fp
            self._kv_dtype = src._kv_dtype
            self._kv_scales = src._kv_scales
        else:
            with open(os.path.join(dirname, DECODE_META), "rb") as f:
                self.meta = wire.decode(f.read())
            with open(os.path.join(dirname, _DECODE_STATE), "rb") as f:
                raw_state = f.read()
            self._state_host = wire.decode(raw_state)
            # (device_kind, phase-key) -> jitted call, shared BY
            # REFERENCE across clone_to replicas
            self._shared_exports = {}
            self._shared_lock = threading.Lock()
            # the fingerprint must cover the WEIGHTS, not just the
            # meta: the int8 phases bake the weight-derived kv scales
            # as trace constants, so two same-shape artifacts with
            # different weights must never resolve each other's
            # persisted executables (a meta-only fingerprint let a
            # stale ("step", n) int8 blob quantize with another
            # model's scales)
            self._model_fp = hashlib.sha256(json.dumps(
                {k: self.meta[k] for k in sorted(self.meta)},
                sort_keys=True, default=str).encode()
                + hashlib.sha256(raw_state).digest()).hexdigest()
            if kv_cache_dtype is not None:
                self._kv_dtype = normalize_kv_dtype(kv_cache_dtype)
            elif self.meta.get("kv_cache_dtype"):
                self._kv_dtype = normalize_kv_dtype(
                    self.meta["kv_cache_dtype"])
            else:
                from paddle_tpu.flags import FLAGS
                self._kv_dtype = normalize_kv_dtype(
                    FLAGS.serving_kv_cache_dtype)
            # per-(layer, head) symmetric fp32 scales [2, L, H, 1]
            # (K row 0, V row 1), a deterministic function of the
            # weights — baked into the traced phases as constants
            # (kv_cache_dtype is a compile-cache fingerprint field)
            self._kv_scales = self._calibrate_kv_scales() \
                if self._kv_dtype == "int8" else None
        self._device = device
        # tensor-parallel compute (SERVING.md "Tensor-parallel
        # compute"): on a MeshGroup with FLAGS.mesh_tp and evenly
        # dividing dims, the phases lower as ONE shard_map'd partitioned
        # executable and the state is placed by the TP axis grammar.
        # Read ONCE here — a registry fault-in / hot-swap rebuild
        # re-reads the flag; live sessions keep their build's mode.
        self._tp_size = 0
        self._tp_prefill_seq = 0
        group = None
        if device is not None:
            from paddle_tpu.parallel.mesh import (as_mesh_group,
                                                  tp_supported)
            group = as_mesh_group(device)
        if group is not None:
            from paddle_tpu.flags import FLAGS
            if FLAGS.mesh_tp:
                _, H, _, D = self._dims()
                if tp_supported(group.mesh_size, H, D,
                                self.vocab_size, 4 * D):
                    self._tp_size = group.mesh_size
                    self._tp_prefill_seq = max(
                        1, int(FLAGS.mesh_tp_prefill_seq))
                else:
                    warnings.warn(
                        "FLAGS.mesh_tp requested but model dims "
                        "(heads=%d d_model=%d vocab=%d) do not divide "
                        "the %d-member mesh — falling back to the "
                        "shard-at-rest gather path"
                        % (self._dims()[1], self._dims()[3],
                           self.vocab_size, group.mesh_size),
                        RuntimeWarning, stacklevel=2)
        if device is not None:
            if self._tp_size:
                from paddle_tpu.inference.predictor import _put_state_tp
                self._state = _put_state_tp(self._state_host, group)
            else:
                from paddle_tpu.inference.predictor import _put_state
                # a MeshGroup placement shards every param at rest over
                # the mesh (SERVING.md "Mesh replicas"); a plain device
                # is the legacy single-chip pin
                self._state = _put_state(self._state_host, device)
        else:
            self._state = {n: np.asarray(v)
                           for n, v in self._state_host.items()}
        self._fns = {}          # per-instance resolved callables
        self._lock = threading.Lock()
        # prompt lengths past every configured prefill bucket that have
        # already warned (once per size, under _lock — the Predictor
        # batch-bucket overflow parity)
        self._overflow_warned = set()

    # -- meta surface ---------------------------------------------------

    @property
    def device(self):
        return self._device

    @property
    def vocab_size(self):
        return int(self.meta["vocab_size"])

    @property
    def max_seq_len(self):
        return int(self.meta["max_seq_len"])

    @property
    def eos_id(self):
        return int(self.meta["eos_id"])

    @property
    def is_decode(self):
        return True

    @property
    def kv_cache_dtype(self):
        """'float32' or 'int8' — the slot-table cache numerics every
        session of this predictor allocates and the serving layer
        reports (SERVING.md kv_cache_dtype rows)."""
        return self._kv_dtype

    @property
    def _kv_quant(self):
        return self._kv_dtype == "int8"

    @property
    def tp_active(self):
        """True when this predictor's phases compute TENSOR-PARALLEL
        over its mesh group (FLAGS.mesh_tp at build + evenly dividing
        dims) — the serving stats / serving_top TP marker reads this."""
        return bool(self._tp_size)

    @property
    def tp_size(self):
        """Members the partitioned program shards over (0 when compute
        is not tensor-parallel)."""
        return int(self._tp_size)

    def kv_scales(self):
        """The calibrated per-(layer, head) fp32 dequant scales
        [2, L, H] (K row 0, V row 1); None for a float32 cache."""
        if self._kv_scales is None:
            return None
        return np.asarray(self._kv_scales)[..., 0]

    def prefill_buckets(self):
        return tuple(int(b) for b in self.meta["prefill_buckets"])

    def batch_buckets(self):
        """Serving introspection parity with Predictor/AotPredictor:
        for a decode model the 'buckets' are the prompt-length prefill
        buckets."""
        return self.prefill_buckets()

    def prompt_bucket(self, prompt_len):
        """Smallest prefill bucket >= prompt_len (deterministic by
        length — the parity contract rides this).  A prompt past every
        configured bucket but still inside the cache falls through to
        an exact-length one-off prefill compile, warning ONCE per
        overflow size — the same contract as the Predictor batch-bucket
        overflow path (SERVING.md)."""
        buckets = self.prefill_buckets()
        for b in buckets:
            if prompt_len <= b:
                return b
        if prompt_len > self.max_seq_len:
            raise ValueError(
                "prompt of %d tokens exceeds max_seq_len %d"
                % (prompt_len, self.max_seq_len))
        if prompt_len not in self._overflow_warned:
            with self._lock:
                # concurrent lanes racing the same overflow size must
                # produce exactly one warning (the PR 5 warn-once race)
                if prompt_len in self._overflow_warned:
                    return int(prompt_len)
                self._overflow_warned.add(prompt_len)
            from paddle_tpu.inference.predictor import _device_label
            warnings.warn(
                "prompt of %d tokens exceeds every configured prefill "
                "bucket %s on replica device [%s] — falling through to "
                "an unbucketed exact-length prefill compile; extend "
                "prefill_buckets to avoid a compile per distinct "
                "overflow length"
                % (prompt_len, tuple(buckets),
                   _device_label(self._device)), RuntimeWarning,
                stacklevel=3)
        return int(prompt_len)

    def clone_to(self, device):
        return GenerativePredictor(None, device=device, _clone_of=self)

    # -- static byte accounting (ANALYSIS.md resource analysis) ---------

    def kv_cache_bytes(self, n_slots):
        """Closed-form slot-table KV cache footprint for an `n_slots`
        session: K and V, [L, n_slots, S, H, Dh] each at the CACHE
        dtype's width (4 B fp32, 1 B int8 — plus the int8 cache's
        per-(layer, head) fp32 scale table) — the HBM term that bounds
        decode slots (FLAGS.serving_decode_slots) and the number the
        admission fit check adds per replica.  Matches
        analysis/resources.py's `_decode_report` pricing exactly."""
        L, H, Dh, _ = self._dims()
        elem = 1 if self._kv_quant else 4
        scales = 2 * L * H * 4 if self._kv_quant else 0
        return (2 * L * int(n_slots) * self.max_seq_len * H * Dh * elem
                + scales)

    def param_bytes(self):
        """Static weight footprint (host-state nbytes sum)."""
        return sum(int(np.asarray(v).nbytes)
                   for v in self._state_host.values())

    # -- model math -----------------------------------------------------

    def _dims(self):
        m = self.meta
        return (int(m["n_layers"]), int(m["n_heads"]),
                int(m["d_model"]) // int(m["n_heads"]),
                int(m["d_model"]))

    # -- int8 KV cache: quantization epilogues --------------------------

    @staticmethod
    def _quantize_kv(x, scale):
        """Symmetric int8 quantization of fresh K/V rows against the
        calibrated per-head scale: clip(round(x / scale)) as EXACT
        integer values in fp32 (the caller casts to int8, directly or
        after the verify path's one-hot scatter — both land the same
        byte, which is what keeps step and verify rows bit-identical
        and spec-decode acceptance at 1.0 under the quantized cache)."""
        import jax.numpy as jnp
        return jnp.clip(jnp.round(x / scale), -127.0, 127.0)

    def _calibrate_kv_scales(self):
        """Per-(layer, head) symmetric scales for the int8 KV cache:
        amax of |K| / |V| over a deterministic vocab-cycling probe
        prompt run through the fp32 prefill math eagerly on the host
        state, x1.25 headroom for decode-time rows the probe never
        saw, /127.  Deterministic by construction, so every clone /
        replica / reopen of the artifact quantizes identically (the
        bit-stability contract rides this).  Returns [2, L, H, 1]."""
        T = int(min(self.max_seq_len - 1, 64))
        vocab = max(self.vocab_size, 1)
        tokens = ((np.arange(T, dtype=np.int64) * 7 + 1)
                  % vocab).astype(np.int32).reshape(1, T)
        state = {n: np.asarray(v) for n, v in self._state_host.items()}
        _, kc, vc = self._prefill_core(state, tokens, np.int32(T))

        def sc(x):
            amax = np.abs(np.asarray(x)).max(axis=(1, 2, 4))   # [L, H]
            return (np.maximum(amax, 1e-6) * 1.25
                    / 127.0).astype(np.float32)

        return np.stack([sc(kc), sc(vc)])[..., None]

    def _prefill_math(self, state, tokens, true_len, tp=None):
        """The traced prefill phase: `_prefill_core` plus the int8
        cache-write quantization epilogue (zeros quantize to exact
        int8 zeros, so the zero-slot contract is dtype-blind).  Under
        TP the K/V are this member's head shard, so the scale constant
        slices to the resident head block — same per-head scale, same
        quantized byte as the single-device write."""
        import jax.numpy as jnp
        first, kc, vc = self._prefill_core(state, tokens, true_len,
                                           tp=tp)
        if not self._kv_quant:
            return first, kc, vc
        sc = self._kv_scales                     # [2, L, H, 1] np
        if tp is not None:
            sc = tp.head_scales(sc, kc.shape[3])  # [2, L, Hl, 1]
        kq = self._quantize_kv(
            kc, sc[0][:, None, None]).astype(jnp.int8)
        vq = self._quantize_kv(
            vc, sc[1][:, None, None]).astype(jnp.int8)
        return first, kq, vq

    def _tp_seq_parallel(self, bucket):
        """Does this prompt bucket prefill SEQUENCE-parallel under TP?
        Long prompts at a bucket the mesh divides shard the sequence
        axis (ulysses reshard into head-parallel attention, per-layer
        weight all_gathers amortized over the bucket — bit-exact);
        short ones run head/column-parallel like decode (top-1
        contract, no per-layer gathers)."""
        m = self._tp_size
        return bool(m and bucket % m == 0
                    and bucket >= self._tp_prefill_seq)

    def _prefill_core(self, state, tokens, true_len, tp=None):
        """tokens [1, B] int32, true_len scalar int32 -> (first_token
        [] int32, k/v [L, 1, B, H, Dh] fp32 with pad positions zeroed).
        Under TP (`tp` set, inside shard_map) weights are local shards:
        the returned K/V carry this member's HEAD block [L, 1, B, H/m,
        Dh] (the cache's at-rest layout), attention is head-parallel
        (exact per head), and each column->row pair closes with one
        psum; long buckets divert to the bit-exact sequence-parallel
        body instead."""
        import jax.numpy as jnp
        L, H, Dh, D = self._dims()
        B = tokens.shape[1]
        scale = 1.0 / np.sqrt(Dh)
        if tp is not None and self._tp_seq_parallel(B):
            return self._prefill_core_seqpar(state, tokens, true_len,
                                             tp)
        Hl = H if tp is None else H // tp.size
        if tp is None:
            x = state["embed"][tokens] + state["pos"][:B][None]
        else:
            x = tp.embed_lookup(state["embed"], tokens) \
                + state["pos"][:B][None]
        ks, vs = [], []
        for i in range(L):
            p = "l%d_" % i
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ state[p + "wq"]).reshape(1, B, Hl, Dh)
            k = (h @ state[p + "wk"]).reshape(1, B, Hl, Dh)
            v = (h @ state[p + "wv"]).reshape(1, B, Hl, Dh)
            att = _causal_attention(q, k, v, scale).reshape(
                1, B, Hl * Dh)
            wo_out = att @ state[p + "wo"]
            x = x + (wo_out if tp is None else tp.psum(wo_out))
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            mlp = jnp.maximum(h2 @ state[p + "w1"] + state[p + "b1"],
                              0.0) @ state[p + "w2"]
            x = x + (mlp if tp is None else tp.psum(mlp)) \
                + state[p + "b2"]
            ks.append(k)
            vs.append(v)
        logits = _ln(x, state["lnf_g"], state["lnf_b"]) @ state["lm_head"]
        if tp is not None:
            # vocab-sharded logits reassemble (exact data movement)
            # before the replicated argmax
            logits = tp.all_gather(logits, axis=2)
        first = jnp.argmax(logits[0, true_len - 1], axis=-1).astype(
            jnp.int32)
        # zero the pad positions: the slot cache must hold exact zeros
        # past the live length (free() zeroes, writes are length-gated —
        # this keeps prefill on the same contract)
        live = (jnp.arange(B)[None, :, None, None]
                < true_len)[None]            # [1, 1, B, 1, 1]
        kc = jnp.where(live, jnp.stack(ks), 0.0)
        vc = jnp.where(live, jnp.stack(vs), 0.0)
        return first, kc, vc

    def _prefill_core_seqpar(self, state, tokens, true_len, tp):
        """SEQUENCE-parallel TP prefill (parallel/ulysses.py's scheme):
        each member owns B/m prompt positions; per layer the sharded
        weights all_gather back whole (exact data movement, amortized
        over the long bucket — prefill is compute-bound, unlike
        decode), attention rides the ulysses seq<->heads all_to_all
        pair around the SAME `_causal_attention` oracle, and K/V
        all_to_all into the head-sharded cache layout.  Every
        position's math runs with FULL weights in the single-device
        reduction order, so this path is BIT-EXACT vs the oracle — no
        psum ever touches an activation."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.parallel.ulysses import (heads_to_seq,
                                                 seq_to_heads)
        L, H, Dh, D = self._dims()
        B = tokens.shape[1]
        m = tp.size
        Bl = B // m
        scale = 1.0 / np.sqrt(Dh)
        idx = tp.index()
        tok_l = jax.lax.dynamic_slice(tokens, (0, idx * Bl), (1, Bl))
        pos_l = jax.lax.dynamic_slice(
            state["pos"], (idx * Bl, jnp.int32(0)),
            (Bl, state["pos"].shape[1]))
        x = tp.embed_lookup(state["embed"], tok_l) + pos_l[None]
        ks, vs = [], []
        for i in range(L):
            p = "l%d_" % i
            wq = tp.all_gather(state[p + "wq"], axis=1)
            wk = tp.all_gather(state[p + "wk"], axis=1)
            wv = tp.all_gather(state[p + "wv"], axis=1)
            wo = tp.all_gather(state[p + "wo"], axis=0)
            w1 = tp.all_gather(state[p + "w1"], axis=1)
            b1 = tp.all_gather(state[p + "b1"], axis=0)
            w2 = tp.all_gather(state[p + "w2"], axis=0)
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ wq).reshape(1, Bl, H, Dh)
            k = (h @ wk).reshape(1, Bl, H, Dh)
            v = (h @ wv).reshape(1, Bl, H, Dh)
            # seq->heads: full sequence, resident head block (exact)
            qh = seq_to_heads(q, tp.axis)        # [1, B, H/m, Dh]
            kh = seq_to_heads(k, tp.axis)
            vh = seq_to_heads(v, tp.axis)
            atth = _causal_attention(qh, kh, vh, scale)
            att = heads_to_seq(atth, tp.axis).reshape(1, Bl, D)
            x = x + att @ wo
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            x = x + jnp.maximum(h2 @ w1 + b1, 0.0) @ w2 \
                + state[p + "b2"]
            # the cache's at-rest layout IS the post-reshard one: full
            # sequence, this member's heads
            ks.append(kh)
            vs.append(vh)
        xg = tp.all_gather(x, axis=1)            # [1, B, D] whole
        lm = tp.all_gather(state["lm_head"], axis=1)
        logits = _ln(xg, state["lnf_g"], state["lnf_b"]) @ lm
        first = jnp.argmax(logits[0, true_len - 1], axis=-1).astype(
            jnp.int32)
        live = (jnp.arange(B)[None, :, None, None]
                < true_len)[None]            # [1, 1, B, 1, 1]
        kc = jnp.where(live, jnp.stack(ks), 0.0)
        vc = jnp.where(live, jnp.stack(vs), 0.0)
        return first, kc, vc

    def _step_math(self, state, kc, vc, lengths, last_tokens, active,
                   tp=None):
        """One fixed-shape decode step over the whole slot table.
        kc/vc [L, N, S, H, Dh] (fp32, or int8 under the quantized
        cache), lengths [N] i32 (live cached positions), last_tokens
        [N] i32, active [N] bool -> (new_tokens [N] i32, kc', vc').
        Cache writes are gated by `active`, so a freed (zeroed) slot
        stays zero and per-slot independence is exact.  Under int8,
        fresh K/V rows quantize in-graph before landing and the
        attention dequantizes in-register — float KV rows never reach
        the cache arrays.

        Under TP (`tp` set, inside shard_map) kc/vc are this member's
        resident HEAD shard and weights are local column/row shards:
        attention runs the head-sliced decode kernel on the local
        block (exact per head — heads are independent), each
        column->row pair closes with ONE psum, and the vocab-sharded
        logits all_gather before the argmax — params and KV never
        materialize unsharded, per-step HBM traffic per member
        ~1/mesh_size."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas_kernels import (
            decode_attention, decode_attention_head_slice)
        L, H, Dh, D = self._dims()
        N, S = kc.shape[1], kc.shape[2]
        quant = self._kv_quant
        scale = 1.0 / np.sqrt(Dh)
        Hl = H if tp is None else H // tp.size
        if tp is None:
            x = state["embed"][last_tokens] + state["pos"][lengths]
        else:
            x = tp.embed_lookup(state["embed"], last_tokens) \
                + state["pos"][lengths]                         # [N, D]
        write = (jnp.arange(S)[None, :] == lengths[:, None]) \
            & active[:, None]                                   # [N, S]
        wmask = write[:, :, None, None]
        kcs, vcs = [], []
        for i in range(L):
            p = "l%d_" % i
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ state[p + "wq"]).reshape(N, Hl, Dh)
            k_new = (h @ state[p + "wk"]).reshape(N, Hl, Dh)
            v_new = (h @ state[p + "wv"]).reshape(N, Hl, Dh)
            if quant:
                sc_i = self._kv_scales[:, i] if tp is None \
                    else tp.head_scales(self._kv_scales[:, i], Hl)
                k_new = self._quantize_kv(
                    k_new, sc_i[0]).astype(jnp.int8)
                v_new = self._quantize_kv(
                    v_new, sc_i[1]).astype(jnp.int8)
            kci = jnp.where(wmask, k_new[:, None], kc[i])
            vci = jnp.where(wmask, v_new[:, None], vc[i])
            if tp is None:
                att = decode_attention(q, kci, vci, lengths + 1,
                                       scale=scale,
                                       kv_scales=self._kv_scales[:, i]
                                       if quant else None)
            else:
                att = decode_attention_head_slice(
                    q, kci, vci, lengths + 1, tp.index() * Hl, Hl,
                    scale=scale,
                    kv_scales=self._kv_scales[:, i] if quant else None)
            wo_out = att.reshape(N, Hl * Dh) @ state[p + "wo"]
            x = x + (wo_out if tp is None else tp.psum(wo_out))
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            mlp = jnp.maximum(h2 @ state[p + "w1"] + state[p + "b1"],
                              0.0) @ state[p + "w2"]
            x = x + (mlp if tp is None else tp.psum(mlp)) \
                + state[p + "b2"]
            kcs.append(kci)
            vcs.append(vci)
        logits = _ln(x, state["lnf_g"], state["lnf_b"]) @ state["lm_head"]
        if tp is not None:
            logits = tp.all_gather(logits, axis=1)
        new_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return new_tok, jnp.stack(kcs), jnp.stack(vcs)

    def _verify_math(self, state, kc, vc, lengths, tokens, active,
                     tp=None):
        """One speculative VERIFY step over the whole slot table:
        tokens [N, C] = [pending last token, draft d1..dk] (C = k+1),
        -> (g [N, C] target greedy tokens per position, m [N] accepted
        draft counts 0..k, kc', vc').

        Scores all C positions in one fixed-shape launch: the chunk's
        Q/K/V come from ONE batched projection (weights stream once for
        all C positions — the step-latency/bandwidth win), all C rows
        land in the slot cache first (the step path's write-before-
        attend order), and every chunk position then attends through
        the SAME `decode_attention` kernel the plain decode step runs —
        position j is a pseudo-slot over the same S-length cache axis
        masked to length+j+1.  Same kernel, same axis geometry, same
        masking semantics => verify logits round exactly like the
        sequential plain-step logits, which is what makes greedy
        acceptance bit-exact against the fp32-only stream.

        Acceptance and rollback are in-graph: m = longest prefix with
        d_i == g_{i-1}; rows past length+m (the rejected suffix) are
        zeroed before the caches return, so stale draft K/V never
        survives into the committed cache.

        Under TP the same head-parallel discipline as `_step_math`
        applies: local head shards through the head-sliced kernel, one
        psum per pair, logits all_gather — the spec-decode round's
        verify rides the partitioned program unchanged."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas_kernels import (
            decode_attention, decode_attention_head_slice)
        L, H, Dh, D = self._dims()
        N, C = tokens.shape
        S = kc.shape[2]
        quant = self._kv_quant
        scale = 1.0 / np.sqrt(Dh)
        Hl = H if tp is None else H // tp.size
        pos_idx = lengths[:, None] + jnp.arange(C)[None]        # [N, C]
        if tp is None:
            x = state["embed"][tokens] + state["pos"][pos_idx]  # [N,C,D]
        else:
            x = tp.embed_lookup(state["embed"], tokens) \
                + state["pos"][pos_idx]
        write = (jnp.arange(S)[None, None, :]
                 == pos_idx[:, :, None]) & active[:, None, None]
        written = jnp.any(write, axis=1)[:, :, None, None]      # [N,S,1,1]
        qlens = (pos_idx + 1).reshape(N * C).astype(jnp.int32)
        kcs, vcs = [], []
        for i in range(L):
            p = "l%d_" % i
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ state[p + "wq"]).reshape(N, C, Hl, Dh)
            k_new = (h @ state[p + "wk"]).reshape(N, C, Hl, Dh)
            v_new = (h @ state[p + "wv"]).reshape(N, C, Hl, Dh)
            if quant:
                # quantize BEFORE the scatter: the one-hot contraction
                # moves exact fp32 integer values, so the int8 cast
                # lands the same byte a sequential step write would —
                # verify rows == step rows bit-for-bit
                sc_i = self._kv_scales[:, i] if tp is None \
                    else tp.head_scales(self._kv_scales[:, i], Hl)
                k_new = self._quantize_kv(k_new, sc_i[0])
                v_new = self._quantize_kv(v_new, sc_i[1])
            # land all C rows (positions are distinct, so the scatter
            # contraction adds exact zeros around one exact value)
            wf = write.astype(k_new.dtype)
            ksc = jnp.einsum("ncs,nchd->nshd", wf, k_new)
            vsc = jnp.einsum("ncs,nchd->nshd", wf, v_new)
            if quant:
                ksc = ksc.astype(jnp.int8)
                vsc = vsc.astype(jnp.int8)
            kci = jnp.where(written, ksc, kc[i])
            vci = jnp.where(written, vsc, vc[i])
            kx = jnp.broadcast_to(
                kci[:, None],
                (N, C, S, Hl, Dh)).reshape(N * C, S, Hl, Dh)
            vx = jnp.broadcast_to(
                vci[:, None],
                (N, C, S, Hl, Dh)).reshape(N * C, S, Hl, Dh)
            if tp is None:
                att = decode_attention(q.reshape(N * C, Hl, Dh), kx, vx,
                                       qlens, scale=scale,
                                       kv_scales=self._kv_scales[:, i]
                                       if quant else None)
            else:
                att = decode_attention_head_slice(
                    q.reshape(N * C, Hl, Dh), kx, vx, qlens,
                    tp.index() * Hl, Hl, scale=scale,
                    kv_scales=self._kv_scales[:, i] if quant else None)
            wo_out = att.reshape(N, C, Hl * Dh) @ state[p + "wo"]
            x = x + (wo_out if tp is None else tp.psum(wo_out))
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            mlp = jnp.maximum(h2 @ state[p + "w1"] + state[p + "b1"],
                              0.0) @ state[p + "w2"]
            x = x + (mlp if tp is None else tp.psum(mlp)) \
                + state[p + "b2"]
            kcs.append(kci)
            vcs.append(vci)
        logits = _ln(x, state["lnf_g"], state["lnf_b"]) @ state["lm_head"]
        if tp is not None:
            logits = tp.all_gather(logits, axis=2)
        g = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [N, C]
        match = (tokens[:, 1:] == g[:, :C - 1]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1).astype(jnp.int32)
        # rejected suffix: the committed cache keeps rows for the
        # pending token + the m accepted drafts (length + m + 1 rows
        # total); everything this step wrote past that is zeroed
        posS = jnp.arange(S)[None, :]
        stale = (posS >= (lengths + m + 1)[:, None]) \
            & (posS < (lengths + C)[:, None]) & active[:, None]
        stale_m = stale[None, :, :, None, None]
        kall = jnp.stack(kcs)
        vall = jnp.stack(vcs)
        # select, not multiply-by-mask: exact zeros either way for
        # fp32, and int8 caches cannot ride a float multiply
        zero = jnp.zeros((), kall.dtype)
        return (g, m, jnp.where(stale_m, zero, kall),
                jnp.where(stale_m, zero, vall))

    def _fused_step_math(self, n_steps, tp=None):
        """Build the FUSED multi-step decode phase (SERVING.md "Fused
        multi-step decode"): up to `n_steps` plain decode steps run as
        ONE compiled executable — a `lax.while_loop` carrying {KV
        cache, lengths, last_tokens, per-slot running masks} through
        step+argmax+KV-write per trip, with in-graph early exit the
        moment no slot is still running.  Per-trip the body is EXACTLY
        `_step_math` (same kernel, same masking, same write order), so
        a fused stream is bit-identical to `n_steps` sequential
        `decode()` calls — the per-slot independence that makes batched
        decode bit-exact makes fusion bit-exact too.

        Runtime args (the executable stays one fingerprint per
        (n_slots, n_steps) geometry):
          * `budget` [N] i32 — tokens each slot may still emit (its
            max_new / cache-room headroom); a slot stops running when
            its budget is met, without stopping the others;
          * `max_trips` [] i32 — dispatch-wide trip clamp (<= n_steps),
            the serving deadline governor (a lane about to expire runs
            a short window instead of recompiling a new geometry).

        A slot stops running after emitting EOS, exhausting its
        budget, or filling its cache; tokens land in a [N, n_steps]
        block, `emitted[s]` of them valid per slot, in stream order."""
        import jax
        import jax.numpy as jnp
        n_steps = int(n_steps)
        eos = self.eos_id

        def fused(state, kc, vc, lengths, last_tokens, active, budget,
                  max_trips):
            S = kc.shape[2]
            N = kc.shape[1]
            toks0 = jnp.zeros((N, n_steps), jnp.int32)
            emitted0 = jnp.zeros((N,), jnp.int32)
            running0 = active & (budget > 0) \
                & (lengths < jnp.int32(S))
            trips = jnp.minimum(max_trips, jnp.int32(n_steps))

            def cond(carry):
                i, _kc, _vc, _len, _last, _em, _tk, running = carry
                return (i < trips) & jnp.any(running)

            def body(carry):
                i, kc, vc, lengths, last, emitted, toks, running = carry
                tok, kc, vc = self._step_math(state, kc, vc, lengths,
                                              last, running, tp=tp)
                # land this trip's tokens at column i (one-hot select —
                # stopped slots keep their block rows untouched)
                col = (jnp.arange(n_steps)[None, :] == i) \
                    & running[:, None]
                toks = jnp.where(col, tok[:, None], toks)
                adv = running.astype(jnp.int32)
                emitted = emitted + adv
                lengths = lengths + adv
                last = jnp.where(running, tok, last)
                running = running & (tok != jnp.int32(eos)) \
                    & (emitted < budget) & (lengths < jnp.int32(S))
                return (i + 1, kc, vc, lengths, last, emitted, toks,
                        running)

            carry = (jnp.int32(0), kc, vc, lengths, last_tokens,
                     emitted0, toks0, running0)
            (i, kc, vc, lengths, last, emitted, toks,
             _running) = jax.lax.while_loop(cond, body, carry)
            return toks, emitted, i, kc, vc, lengths, last

        return fused

    def _fused_spec_math(self, draft, spec_k, tp=None):
        """Build the FUSED speculative round: k draft decode steps +
        the batched k+1-position verify + in-graph accept / draft-
        rollback / draft-catch-up bookkeeping, all ONE executable (one
        dispatch instead of k draft dispatches + one verify).  The
        draft's state dict rides as a traced ARGUMENT (its weights are
        not baked), and the phase key carries the draft's model
        fingerprint + cache dtype so two different drafts never collide
        on one executable.

        Every sub-phase is the same traced math the host-driven round
        runs (`draft._step_math` per draft trip, `self._verify_math`
        for scoring, the rollback zeroing mirrors `DecodeSession.
        rollback`), so committed streams stay bit-identical to the
        fp32-only plain stream and twin-draft acceptance stays exactly
        1.0."""
        import jax.numpy as jnp
        k = int(spec_k)

        def fused(state, dstate, t_kc, t_vc, t_len, t_last,
                  d_kc, d_vc, d_len, d_last, active):
            N = t_kc.shape[1]
            Sd = d_kc.shape[2]
            adv = active.astype(jnp.int32)
            rows = jnp.arange(N)
            # 1. DRAFT: k steps on the draft table (unrolled — k is a
            # geometry constant of this executable)
            drafts = []
            for _ in range(k):
                dtok, d_kc, d_vc = draft._step_math(
                    dstate, d_kc, d_vc, d_len, d_last, active, tp=tp)
                d_len = d_len + adv
                d_last = jnp.where(active, dtok, d_last)
                drafts.append(dtok)
            # 2. VERIFY: score [pending, d1..dk] in one batched step
            chunk = jnp.stack([t_last] + drafts, axis=1)      # [N, C]
            g, m, t_kc, t_vc = self._verify_math(
                state, t_kc, t_vc, t_len, chunk, active, tp=tp)
            m = jnp.where(active, m, 0)
            # 3. COMMIT: target bookkeeping (mirrors the host round)
            counts = jnp.where(active, m + 1, 0).astype(jnp.int32)
            t_len = t_len + counts
            t_last = jnp.where(active, g[rows, jnp.minimum(m, k)],
                               t_last)
            # draft sync, in-graph: partially-accepted slots roll the
            # rejected rows back (zeroed, length pointer retreats,
            # pending token re-pins to the target's correction)...
            part = active & (m < k)
            nback = jnp.where(part, k - 1 - m, 0)
            newlen = d_len - nback
            posS = jnp.arange(Sd)[None, :]
            stale = (posS >= newlen[:, None]) & (posS < d_len[:, None])
            stale_m = stale[None, :, :, None, None]
            zero = jnp.zeros((), d_kc.dtype)
            d_kc = jnp.where(stale_m, zero, d_kc)
            d_vc = jnp.where(stale_m, zero, d_vc)
            d_len = newlen
            d_last = jnp.where(part, g[rows, jnp.minimum(m, k)], d_last)
            # ...and fully-accepted slots owe the draft one catch-up
            # step (it emitted d_k without ever consuming it), pending
            # token re-pinned to the target's bonus token
            full = active & (m == k)
            _cu, d_kc, d_vc = draft._step_math(
                dstate, d_kc, d_vc, d_len, d_last, full, tp=tp)
            d_len = d_len + full.astype(jnp.int32)
            d_last = jnp.where(full, g[:, k], d_last)
            return (g, m, t_kc, t_vc, t_len, t_last,
                    d_kc, d_vc, d_len, d_last)

        return fused

    # -- compiled-phase resolution (the PR 6 compile-cache ride) --------

    @staticmethod
    def _argsig(spec):
        """Fingerprint encoding of one arg spec: a plain ShapeDtype
        leaf, or a dict of them (the fused-speculative phase passes the
        DRAFT predictor's state dict as a traced argument)."""
        if isinstance(spec, dict):
            return {k: [list(v.shape), str(v.dtype)]
                    for k, v in sorted(spec.items())}
        return [list(spec.shape), str(spec.dtype)]

    def _fingerprint(self, phase_key, arg_specs, extra=None):
        from paddle_tpu import compile_cache as cc
        fp = {
            "kind": "decode_phase",
            "model": self._model_fp,
            "phase": list(phase_key),
            # the cache dtype changes the traced math (quantize-on-
            # write epilogues, baked dequant scales) without changing
            # the prefill arg specs — fingerprinting it keeps fp32 and
            # int8 executables from ever colliding (COMPILE_CACHE.md);
            # rev bumps when the phase math itself changes shape
            "kv_dtype": self._kv_dtype,
            "rev": 2,
            "state": cc._spec_sig(self._state_host),
            "args": [self._argsig(s) for s in arg_specs],
            "env": cc.environment_fingerprint(self._device),
        }
        if extra:
            # tensor-parallel phases fold the mesh shape in: the
            # partitioned module's collectives are specialized to the
            # axis size, so a (2,) and a (4,) executable must never
            # resolve each other's blobs
            fp.update(extra)
        return fp

    def _device_kind(self):
        import jax
        d = self._device
        if d is None:
            devs = jax.devices()
            d = devs[0] if devs else None
        return "%s/%s" % (getattr(d, "platform", "cpu"),
                          getattr(d, "device_kind", ""))

    def _resolve(self, phase_key, math_fn, arg_specs, tp_math=None,
                 draft=None):
        """Persistent-cache-first compile of one phase (same order as
        Predictor._get_aot_fn: in-process shared map -> store hit ->
        fresh export+commit -> legacy jit fallback).  `tp_math` is the
        per-member tensor-parallel body (math_fn with a bound
        _TPContext); when set and the predictor rides a mesh, the phase
        compiles as ONE shard_map'd partitioned program instead of the
        replicate-compute gather wrap.  `draft` (fused-spec only) tells
        the spec builder how the draft's dict-shaped state is actually
        placed."""
        import time as _time
        import jax
        fn = self._fns.get(phase_key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._fns.get(phase_key)
            if fn is not None:
                return fn
            fn = self._resolve_locked(phase_key, math_fn, arg_specs,
                                      _time, jax, tp_math=tp_math,
                                      draft=draft)
            self._fns[phase_key] = fn
            return fn

    def _mesh_group(self):
        from paddle_tpu.parallel.mesh import as_mesh_group
        return as_mesh_group(self._device)

    def _tp_ctx(self):
        return _TPContext(self._tp_size)

    def _tp_math(self, math_fn):
        """The per-member tensor-parallel body for a phase math fn, or
        None when this predictor isn't TP-active (single device, gather
        fallback, or a model the TP grammar can't split)."""
        if not self._tp_size:
            return None
        tp = self._tp_ctx()

        def fn(state, *args):
            return math_fn(state, *args, tp=tp)
        return fn

    def _mesh_specs(self, group, state_spec, arg_specs, jax,
                    draft=None):
        """Attach the at-rest shardings to the phase's arg specs so the
        compiled executable matches what the session actually passes:
        params sharded per `param_sharding` (or `tp_param_sharding`
        when this predictor runs tensor-parallel — AOT executables are
        strict about input placement), 5-D KV slot tables per
        `kv_sharding`, everything else replicated.  Dict-shaped args
        (the fused-speculative phase's DRAFT state) shard per the
        DRAFT's own placement — it rides the same mesh group as its
        target lane but may be TP-placed or gather-placed
        independently."""
        def attach(s, sh):
            return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)

        def params(spec, tp):
            if tp:
                return {k: attach(v, group.tp_param_sharding(k, v.shape))
                        for k, v in spec.items()}
            return {k: attach(v, group.param_sharding(v.shape))
                    for k, v in spec.items()}

        def one(spec):
            if isinstance(spec, dict):
                return params(spec,
                              draft is not None
                              and getattr(draft, "_tp_size", 0))
            if len(spec.shape) == 5:
                return attach(spec, group.kv_sharding(spec.shape))
            return attach(spec, group.replicated())

        state_spec = params(state_spec, self._tp_size)
        return state_spec, tuple(one(s) for s in arg_specs)

    def _tp_shard_map(self, tp_math, plain_math, state_spec, arg_specs,
                      group, jax):
        """Build the partitioned program: ONE shard_map over the
        group's 1-D "model" axis running the per-member body.  Params
        enter under the TP grammar (`tp_param_pspec`), 5-D KV slot
        tables head-sharded (axis 3 — `tp_supported` guarantees heads
        divide, so this coincides with the at-rest `kv_sharding`),
        scalars/token tables replicated.  Output specs come from
        eval_shape of the plain (tp=None) math — the TP body returns
        the same tree, with 5-D caches staying head-sharded and
        everything else fully reduced (psum/all_gather) hence
        replicated."""
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.parallel.mesh import (
            MODEL_AXIS, shard_map_no_rep_check, tp_param_pspec)

        kv_spec = P(None, None, None, MODEL_AXIS, None)

        def pspec_of(spec):
            if isinstance(spec, dict):
                return {k: tp_param_pspec(k, v.shape)
                        for k, v in spec.items()}
            if len(spec.shape) == 5:
                return kv_spec
            return P()

        in_specs = ({n: tp_param_pspec(n, s.shape)
                     for n, s in state_spec.items()},)
        in_specs += tuple(pspec_of(s) for s in arg_specs)
        out_shape = jax.eval_shape(plain_math, state_spec, *arg_specs)
        out_specs = jax.tree_util.tree_map(
            lambda s: kv_spec if len(s.shape) == 5 else P(), out_shape)
        return shard_map_no_rep_check(tp_math, group.mesh(),
                                      in_specs=in_specs,
                                      out_specs=out_specs)

    def _resolve_locked(self, phase_key, math_fn, arg_specs, _time, jax,
                        tp_math=None, draft=None):
        from paddle_tpu import compile_cache as cc
        state_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                              np.asarray(v).dtype)
                      for n, v in self._state_host.items()}
        fp_extra = None
        group = self._mesh_group()
        if group is not None:
            state_spec, arg_specs = self._mesh_specs(
                group, state_spec, arg_specs, jax, draft=draft)
            if tp_math is None:
                # gather-mode meshed phases compile directly against
                # the sharded state (no export: the replicate-compute
                # wrap is a sharding annotation, not program structure).
                # predictor._mesh_wrap keeps streams bit-exact vs a
                # single-device replica; KV outputs re-shard at rest.
                from paddle_tpu.inference.predictor import _mesh_wrap
                return self._jit_fallback(
                    _mesh_wrap(math_fn, group, kv_outputs=True),
                    state_spec, arg_specs)
            # tensor-parallel: the shard_map'd partitioned program IS
            # part of the traced module and sharded ShapeDtypeStructs
            # round-trip through jax.export — so TP phases ride the
            # persistent cache like single-device ones, with the mesh
            # shape folded into the fingerprint (warm boots of a TP
            # server deserialize the partitioned executable).
            math_fn = self._tp_shard_map(tp_math, math_fn, state_spec,
                                         arg_specs, group, jax)
            fp_extra = {"mesh": list(group.shape), "tp": True}
        if cc.cache_enabled() and not (
                self._device is not None
                and self._device.platform != jax.default_backend()):
            skey = (self._device_kind(), phase_key)
            with self._shared_lock:
                ent = self._shared_exports.get(skey)
            if ent is _UNEXPORTABLE:
                return self._jit_fallback(math_fn, state_spec, arg_specs)
            if ent is not None:
                return ent
            from jax import export as jax_export
            cache = cc.default_cache()
            fn = None
            try:
                fp = self._fingerprint(phase_key, arg_specs,
                                       extra=fp_extra)
                blob = cache.get(fp) if cache is not None else None
                if blob is not None:
                    try:
                        t0 = _time.monotonic()
                        exp = jax_export.deserialize(blob)
                        fn = jax.jit(exp.call)
                        cc.note_deserialize_ms(
                            (_time.monotonic() - t0) * 1000.0)
                    except Exception:
                        blob = None
                if fn is None:
                    t0 = _time.monotonic()
                    exp = jax_export.export(jax.jit(math_fn))(
                        state_spec, *arg_specs)
                    cc.note_compile_ms(
                        (_time.monotonic() - t0) * 1000.0)
                    if cache is not None:
                        cache.put(fp, exp.serialize())
                    fn = jax.jit(exp.call)
            except Exception as e:
                with self._shared_lock:
                    already = self._shared_exports.get(skey)
                    self._shared_exports[skey] = _UNEXPORTABLE
                if already is not _UNEXPORTABLE:
                    warnings.warn(
                        "compile cache disabled for decode phase %r "
                        "(export failed: %s: %s) — falling back to "
                        "direct compilation"
                        % (phase_key, type(e).__name__, e),
                        RuntimeWarning, stacklevel=4)
                return self._jit_fallback(math_fn, state_spec, arg_specs)
            with self._shared_lock:
                self._shared_exports[skey] = fn
            return fn
        return self._jit_fallback(math_fn, state_spec, arg_specs)

    @staticmethod
    def _jit_fallback(math_fn, state_spec, arg_specs):
        import jax
        # compile NOW (not on first call) so warm() covers the stall
        return jax.jit(math_fn).lower(state_spec, *arg_specs).compile()

    def prefill_fn(self, bucket):
        import jax
        bucket = int(bucket)
        specs = (jax.ShapeDtypeStruct((1, bucket), np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((), np.dtype(np.int32)))
        return self._resolve(("prefill", bucket), self._prefill_math,
                             specs,
                             tp_math=self._tp_math(self._prefill_math))

    def _cache_np_dtype(self):
        return np.dtype(np.int8 if self._kv_quant else np.float32)

    def step_fn(self, n_slots):
        import jax
        L, H, Dh, _ = self._dims()
        S = self.max_seq_len
        cache = jax.ShapeDtypeStruct((L, int(n_slots), S, H, Dh),
                                     self._cache_np_dtype())
        specs = (cache, cache,
                 jax.ShapeDtypeStruct((int(n_slots),),
                                      np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((int(n_slots),),
                                      np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((int(n_slots),), np.dtype(bool)))
        return self._resolve(("step", int(n_slots)), self._step_math,
                             specs,
                             tp_math=self._tp_math(self._step_math))

    def verify_fn(self, n_slots, spec_k):
        """The speculative-verify executable for a (slot table,
        draft depth) pair: scores k+1 positions per slot in one launch.
        One new compile-cache fingerprint per (n_slots, k) — a warm
        boot of a spec-configured server deserializes it like every
        other phase (COMPILE_CACHE.md)."""
        import jax
        L, H, Dh, _ = self._dims()
        S = self.max_seq_len
        n, C = int(n_slots), int(spec_k) + 1
        cache = jax.ShapeDtypeStruct((L, n, S, H, Dh),
                                     self._cache_np_dtype())
        specs = (cache, cache,
                 jax.ShapeDtypeStruct((n,), np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((n, C), np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((n,), np.dtype(bool)))
        return self._resolve(("verify", n, C), self._verify_math, specs,
                             tp_math=self._tp_math(self._verify_math))

    def fused_step_fn(self, n_slots, n_steps):
        """The fused multi-step decode executable for a (slot table,
        window) geometry: up to `n_steps` tokens per slot per dispatch
        with in-graph early exit (`_fused_step_math`).  One new
        compile-cache fingerprint per (n_slots, n_steps) — warm boots
        of a fused-configured server deserialize it like every other
        phase (COMPILE_CACHE.md)."""
        import jax
        L, H, Dh, _ = self._dims()
        S = self.max_seq_len
        n, T = int(n_slots), int(n_steps)
        if T < 1:
            raise ValueError("fuse window must be >= 1, got %d" % T)
        cache = jax.ShapeDtypeStruct((L, n, S, H, Dh),
                                     self._cache_np_dtype())
        i32 = np.dtype(np.int32)
        specs = (cache, cache,
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((n,), np.dtype(bool)),
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((), i32))
        tp_math = (self._fused_step_math(T, tp=self._tp_ctx())
                   if self._tp_size else None)
        return self._resolve(("fused_step", n, T),
                             self._fused_step_math(T), specs,
                             tp_math=tp_math)

    def fused_spec_fn(self, draft, n_slots, spec_k):
        """The fused speculative-round executable: k draft steps +
        batched verify + in-graph accept/rollback/catch-up as ONE
        dispatch (`_fused_spec_math`).  Keyed per (n_slots, k, draft
        identity) — the draft's model fingerprint and cache dtype ride
        the phase key, so swapping drafts can never resolve a stale
        executable."""
        import jax
        L, H, Dh, _ = self._dims()
        S = self.max_seq_len
        dL, dH, dDh, _ = draft._dims()
        dS = draft.max_seq_len
        n, C = int(n_slots), int(spec_k) + 1
        i32 = np.dtype(np.int32)
        cache = jax.ShapeDtypeStruct((L, n, S, H, Dh),
                                     self._cache_np_dtype())
        dcache = jax.ShapeDtypeStruct((dL, n, dS, dH, dDh),
                                      draft._cache_np_dtype())
        dstate = {name: jax.ShapeDtypeStruct(np.shape(v),
                                             np.asarray(v).dtype)
                  for name, v in draft._state_host.items()}
        specs = (dstate, cache, cache,
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((n,), i32),
                 dcache, dcache,
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((n,), i32),
                 jax.ShapeDtypeStruct((n,), np.dtype(bool)))
        key = ("fused_spec", n, C, draft._model_fp[:16],
               draft._kv_dtype)
        # the fused round partitions only when BOTH sides split under
        # the TP grammar — a gather-placed draft beside a TP target
        # falls back to the replicate-compute wrap (whose specs still
        # reflect each side's actual placement via _mesh_specs)
        tp_math = (self._fused_spec_math(draft, int(spec_k),
                                         tp=self._tp_ctx())
                   if self._tp_size and getattr(draft, "_tp_size", 0)
                   else None)
        return self._resolve(key,
                             self._fused_spec_math(draft, int(spec_k)),
                             specs, tp_math=tp_math, draft=draft)

    def new_session(self, n_slots):
        return DecodeSession(self, n_slots)


class DecodeSession:
    """One slot table: the per-lane KV cache + occupancy bookkeeping.
    NOT thread-safe — a serving lane owns its session exclusively (the
    decode loop is single-threaded per replica by design: the step
    function is one executable over the whole table)."""

    def __init__(self, predictor, n_slots):
        import jax
        import jax.numpy as jnp
        self.predictor = predictor
        self.n_slots = int(n_slots)
        L, H, Dh, _ = predictor._dims()
        S = predictor.max_seq_len
        shape = (L, self.n_slots, S, H, Dh)
        # the cache allocates at the predictor's kv_cache_dtype width:
        # int8 slot tables hold exact int8 zeros when free (QUANTIZE.md
        # "Quantized KV cache" — the zero-slot contract is dtype-blind)
        z = jnp.zeros(shape, jnp.int8 if predictor._kv_quant
                      else jnp.float32)
        if predictor.device is not None:
            from paddle_tpu.parallel.mesh import as_mesh_group
            group = as_mesh_group(predictor.device)
            if group is not None:
                # the slot table shards AT REST across the mesh (heads
                # axis first) — per-device resident KV ~ 1/mesh_size,
                # which is what makes decode slots scale with mesh HBM
                z = jax.device_put(z, group.kv_sharding(shape))
            else:
                z = jax.device_put(z, predictor.device)
        self._kc = z
        self._vc = z
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.last_tokens = np.zeros(self.n_slots, np.int32)
        self.active = np.zeros(self.n_slots, bool)
        self.steps = 0

    # -- occupancy ------------------------------------------------------

    def free_slots(self):
        return [i for i in range(self.n_slots) if not self.active[i]]

    def occupancy(self):
        return int(self.active.sum())

    def cache_bytes(self):
        """MEASURED slot-table footprint: the K + V device arrays'
        nbytes plus the int8 cache's fp32 scale table — what
        bench_serving's --kv_dtype A/B reports against the closed-form
        `GenerativePredictor.kv_cache_bytes`."""
        n = int(self._kc.nbytes) + int(self._vc.nbytes)
        if self.predictor._kv_quant:
            n += int(np.asarray(self.predictor._kv_scales).nbytes)
        return n

    # -- phases ---------------------------------------------------------

    def _put(self, arr):
        if self.predictor.device is not None:
            from paddle_tpu.inference.predictor import _put_feed
            return _put_feed(arr, self.predictor.device)
        return arr

    def prefill(self, slot, tokens):
        """Run the prompt through the bucketed prefill, land its K/V in
        `slot`, and return the first generated token (greedy).  The
        slot must be free (and therefore zeroed)."""
        import jax.lax
        from paddle_tpu.parallel.mesh import check_member_poison
        check_member_poison(self.predictor.device)
        if self.active[slot]:
            raise ValueError("slot %d is occupied" % slot)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.size
        if n < 1:
            raise ValueError("empty prompt")
        bucket = self.predictor.prompt_bucket(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        fn = self.predictor.prefill_fn(bucket)
        first, kc, vc = fn(self.predictor._state, self._put(padded),
                           self._put(np.int32(n)))
        # land the bucket-length K/V at the slot; positions past the
        # bucket are already zero (the slot was zeroed on free)
        at = (0, slot, 0, 0, 0)
        self._kc = jax.lax.dynamic_update_slice(self._kc, kc, at)
        self._vc = jax.lax.dynamic_update_slice(self._vc, vc, at)
        tok = int(first)
        self.lengths[slot] = n
        self.last_tokens[slot] = tok
        self.active[slot] = True
        return tok

    def decode(self):
        """ONE fixed-shape step over the whole slot table; returns the
        np.int32 [n_slots] token vector (only entries of slots active
        at call time are meaningful).  Bumps each active slot's length
        and last token."""
        from paddle_tpu.parallel.mesh import check_member_poison
        check_member_poison(self.predictor.device)
        fn = self.predictor.step_fn(self.n_slots)
        new_tok, self._kc, self._vc = fn(
            self.predictor._state, self._kc, self._vc,
            self._put(self.lengths), self._put(self.last_tokens),
            self._put(self.active))
        toks = np.asarray(new_tok)
        act = self.active
        self.lengths = self.lengths + act.astype(np.int32)
        self.last_tokens = np.where(act, toks, self.last_tokens).astype(
            np.int32)
        self.steps += 1
        return toks

    def decode_fused(self, n_steps, budget=None, max_trips=None):
        """Up to `n_steps` decode steps in ONE dispatch (SERVING.md
        "Fused multi-step decode").  Returns (tokens [n_slots, n_steps]
        int32, counts [n_slots] int32, trips int): slot s emitted
        `counts[s]` tokens this dispatch, `tokens[s, :counts[s]]` in
        stream order; `trips` is how many loop iterations actually ran
        (in-graph early exit — all slots hitting EOS/budget ends the
        window early).  `budget` [n_slots] caps each slot's emissions
        (max_new / cache-room headroom; clipped to [0, n_steps], zero
        for inactive slots); `max_trips` clamps the whole dispatch (the
        serving deadline governor) without changing the compiled
        geometry.  Bit-exact vs `n_steps` sequential `decode()` calls
        — per-slot math is independent and the per-trip body IS the
        plain step math."""
        T = int(n_steps)
        if T < 1:
            raise ValueError("n_steps must be >= 1, got %d" % T)
        from paddle_tpu.parallel.mesh import check_member_poison
        check_member_poison(self.predictor.device)
        act = self.active
        if budget is None:
            b = np.where(act, T, 0).astype(np.int32)
        else:
            b = np.asarray(budget, np.int32).reshape(self.n_slots)
            b = np.clip(np.where(act, b, 0), 0, T).astype(np.int32)
        mt = T if max_trips is None else max(1, min(int(max_trips), T))
        fn = self.predictor.fused_step_fn(self.n_slots, T)
        toks, counts, trips, self._kc, self._vc, lengths, last = fn(
            self.predictor._state, self._kc, self._vc,
            self._put(self.lengths), self._put(self.last_tokens),
            self._put(act), self._put(b), self._put(np.int32(mt)))
        # lengths/last_tokens come back from the device: pure integer
        # bookkeeping, so device round-trip is exact
        self.lengths = np.asarray(lengths).astype(np.int32)
        self.last_tokens = np.asarray(last).astype(np.int32)
        trips = int(trips)
        self.steps += trips
        return np.asarray(toks), np.asarray(counts), trips

    def room(self, slot):
        """Generated tokens this slot can still hold (cache positions
        left)."""
        return int(self.predictor.max_seq_len - self.lengths[slot])

    def free(self, slot):
        """Release a slot: its KV lines are ZEROED before it can be
        reused — a later occupant starts from exact zeros, never from a
        previous request's keys (the no-leakage contract the chaos
        decode-disconnect scenario pins)."""
        import jax.lax
        import jax.numpy as jnp
        L = self._kc.shape[0]
        S, H, Dh = self._kc.shape[2], self._kc.shape[3], self._kc.shape[4]
        z = self._put(jnp.zeros((L, 1, S, H, Dh), self._kc.dtype))
        at = (0, int(slot), 0, 0, 0)
        self._kc = jax.lax.dynamic_update_slice(self._kc, z, at)
        self._vc = jax.lax.dynamic_update_slice(self._vc, z, at)
        self.lengths[slot] = 0
        self.last_tokens[slot] = 0
        self.active[slot] = False

    def rollback(self, slot, n, last_token=None):
        """Roll `slot` back by `n` cached positions: the length pointer
        retreats and the rolled-back KV rows are ZEROED, so the slot is
        bit-identical to one that never advanced past the restored
        length (pinned by tests/test_spec_decode.py).  `last_token`,
        when given, restores the slot's pending token alongside — a
        full rewind needs both, since the pending token is the one
        committed token whose K/V is not in the cache yet.

        The speculative decoder's draft-side sync is built on this: a
        partially-accepted round rolls the draft's rejected rows back
        and re-pins its pending token to the target's correction."""
        import jax.lax
        import jax.numpy as jnp
        slot, n = int(slot), int(n)
        if n < 0:
            raise ValueError("rollback of %d positions" % n)
        length = int(self.lengths[slot])
        if n > length:
            raise ValueError(
                "rollback of %d positions on slot %d with only %d "
                "cached" % (n, slot, length))
        if n > 0:
            L = self._kc.shape[0]
            H, Dh = self._kc.shape[3], self._kc.shape[4]
            z = self._put(jnp.zeros((L, 1, n, H, Dh), self._kc.dtype))
            at = (0, slot, length - n, 0, 0)
            self._kc = jax.lax.dynamic_update_slice(self._kc, z, at)
            self._vc = jax.lax.dynamic_update_slice(self._vc, z, at)
            self.lengths[slot] = length - n
        if last_token is not None:
            self.last_tokens[slot] = np.int32(last_token)

    def slot_is_zero(self, slot):
        """True when the slot's K and V cache lines are exact zeros —
        the test hook for the zero-before-reuse contract."""
        k = np.asarray(self._kc[:, slot])
        v = np.asarray(self._vc[:, slot])
        return bool(not k.any() and not v.any())


class SpeculativeDecodeSession:
    """Draft-and-verify generation over one slot table (SERVING.md
    "Speculative decoding"): pairs the fp32 *target* predictor with a
    cheap *draft* predictor (the int8 twin of the same artifact, or any
    decode artifact sharing its vocab/eos) and advances every occupied
    slot 1..k+1 committed tokens per round:

      1. DRAFT: k batched draft decode steps propose d1..dk per slot
         (the draft keeps its own KV slot table, mirroring the
         committed stream);
      2. VERIFY: the target scores all k+1 positions in ONE fixed-shape
         batched step (`GenerativePredictor.verify_fn`) — acceptance
         and stale-row zeroing happen in-graph;
      3. COMMIT: the longest greedily-agreeing prefix (plus the
         target's correction/bonus token) commits to the target cache;
         the draft rolls its rejected rows back (`DecodeSession.
         rollback`) — or runs one catch-up step after a fully-accepted
         round — so both tables mirror the committed stream again.

    Every committed token is a TARGET argmax, so the stream is
    bit-identical to target-only plain decode; the draft only ever
    changes how many steps that stream costs.  Any draft failure
    (`set_draft_poison`, a dead predictor, an incompatible state)
    degrades the session to target-only plain rounds within the same
    step — `degraded` latches, the stream never stalls or corrupts.

    Duck-types the DecodeSession surface the DecodeBatcher drives
    (prefill/free/room/free_slots/occupancy/decode), plus `step()` —
    the variable-accept round returning (tokens [N, k+1], counts [N]).
    NOT thread-safe, same single-owner contract as DecodeSession."""

    def __init__(self, target, draft, n_slots, spec_k):
        if int(spec_k) < 1:
            raise ValueError("spec_k must be >= 1, got %r" % (spec_k,))
        if draft.vocab_size != target.vocab_size:
            raise ValueError(
                "draft vocab %d != target vocab %d — not a compatible "
                "draft artifact" % (draft.vocab_size, target.vocab_size))
        if draft.eos_id != target.eos_id:
            raise ValueError(
                "draft eos_id %d != target eos_id %d"
                % (draft.eos_id, target.eos_id))
        if draft.max_seq_len < target.max_seq_len:
            raise ValueError(
                "draft max_seq_len %d < target max_seq_len %d — the "
                "draft cache cannot mirror the committed stream"
                % (draft.max_seq_len, target.max_seq_len))
        self.predictor = target
        self.draft_predictor = draft
        self.spec_k = int(spec_k)
        self.n_slots = int(n_slots)
        self.session = target.new_session(n_slots)
        self.draft_session = draft.new_session(n_slots)
        self._degraded = False
        self.degrade_error = None
        # accept telemetry the serving layer rolls up per round
        self.rounds = 0          # verify launches
        self.plain_steps = 0     # fallback/degraded plain rounds
        self.proposed = 0        # draft tokens offered to verify
        self.accepted = 0        # draft tokens accepted
        self.last_spec = False   # did the latest round verify?
        self.last_draft_end = None   # monotonic draft->verify boundary

    # -- DecodeSession surface (the batcher's contract) -----------------

    @property
    def steps(self):
        return self.session.steps

    @property
    def degraded(self):
        return self._degraded

    def free_slots(self):
        return self.session.free_slots()

    def occupancy(self):
        return self.session.occupancy()

    def room(self, slot):
        return self.session.room(slot)

    def slot_is_zero(self, slot):
        return self.session.slot_is_zero(slot)

    def _degrade(self, exc):
        self._degraded = True
        if self.degrade_error is None:
            self.degrade_error = "%s: %s" % (type(exc).__name__, exc)

    def prefill(self, slot, tokens):
        """Prefill BOTH tables; the draft's own first-token prediction
        is discarded — its pending token is re-pinned to the target's
        (the committed stream is always the target's)."""
        first = self.session.prefill(slot, tokens)
        if not self._degraded:
            try:
                _check_draft_poison()
                self.draft_session.prefill(slot, tokens)
                self.draft_session.last_tokens[slot] = np.int32(first)
            except BaseException as e:
                self._degrade(e)
        return first

    def free(self, slot):
        self.session.free(slot)
        if self.draft_session.active[slot]:
            self.draft_session.free(slot)

    def decode(self):
        """Plain target-only step (the greedy_decode/static-baseline
        surface); keeps the draft synced so a later spec round starts
        from a mirrored table."""
        toks, _ = self.step(force_plain=True)
        return toks[:, 0]

    # -- the speculative round ------------------------------------------

    def _draft_catchup(self, mask, pins, draft_delay=0.0):
        """Advance the draft one step for `mask` slots (consuming their
        pending token, landing its KV row) and re-pin their pending
        tokens to the committed stream's (`pins` [N])."""
        ds = self.draft_session
        saved = ds.active
        try:
            _check_draft_poison()
            if draft_delay:
                time.sleep(draft_delay)
            ds.active = mask
            ds.decode()
        except BaseException as e:
            self._degrade(e)
            return
        finally:
            ds.active = saved
        for s in np.nonzero(mask)[0]:
            ds.last_tokens[s] = np.int32(pins[s])

    def step(self, step_delay=0.0, draft_delay=0.0, force_plain=False,
             fused=False):
        """One round over the slot table.  Returns (tokens [N, k+1]
        int32, counts [N] int32): slot s committed `counts[s]` tokens
        this round, `tokens[s, :counts[s]]` in stream order (counts is
        0 for inactive slots, 1 for plain rounds, 1..k+1 for spec
        rounds).  `step_delay`/`draft_delay` are the bench/chaos
        per-launch device-cost stand-ins (GIL-released sleeps before
        the verify/plain step and before each draft step).

        A round runs speculatively unless the session is degraded,
        `force_plain` is set, or some occupied slot lacks the k+1 cache
        rows a verify writes — those rounds fall back to ONE plain
        target step for every slot (progress is never blocked by a
        nearly-full slot), with a draft catch-up step keeping the
        tables mirrored.

        `fused=True` runs the whole round as ONE dispatch (SERVING.md
        "Fused multi-step decode"): k draft steps + verify + accept /
        rollback / catch-up ride `GenerativePredictor.fused_spec_fn`
        instead of k+1 host-driven launches.  Committed streams are
        bit-identical either way — the fused program is the same
        traced math; only the dispatch count changes.  Draft-poison
        chaos still fires per logical draft step (checked host-side
        before the dispatch), degrading to the same plain round."""
        from paddle_tpu.parallel.mesh import check_member_poison
        # a lost mesh member kills the TARGET lane whole (typed, never
        # wedged) — unlike a draft death, which only degrades the round
        check_member_poison(self.predictor.device)
        ts = self.session
        k = self.spec_k
        C = k + 1
        N = self.n_slots
        active = ts.active.copy()
        occupied = np.nonzero(active)[0]
        spec_ok = (not force_plain and not self._degraded
                   and occupied.size > 0
                   and all(ts.room(int(s)) >= C for s in occupied))
        self.last_spec = False
        drafts = []
        if spec_ok and fused:
            # host-side chaos parity: the poison counter advances once
            # per LOGICAL draft step (and the draft-cost stand-in
            # sleeps k times), exactly like the host-driven round — a
            # poisoned draft degrades this round to plain before the
            # fused dispatch ever launches
            try:
                for _ in range(k):
                    _check_draft_poison()
                    if draft_delay:
                        time.sleep(draft_delay)
            except BaseException as e:
                self._degrade(e)
                spec_ok = False
        if spec_ok and fused:
            ds = self.draft_session
            self.last_draft_end = time.monotonic()
            if step_delay:
                time.sleep(step_delay)
            fn = self.predictor.fused_spec_fn(self.draft_predictor,
                                              N, k)
            (g, m, ts._kc, ts._vc, t_len, t_last,
             ds._kc, ds._vc, d_len, d_last) = fn(
                self.predictor._state, self.draft_predictor._state,
                ts._kc, ts._vc, ts._put(ts.lengths),
                ts._put(ts.last_tokens), ds._kc, ds._vc,
                ds._put(ds.lengths), ds._put(ds.last_tokens),
                ts._put(active))
            g = np.asarray(g)
            m = np.where(active, np.asarray(m), 0).astype(np.int32)
            counts = np.where(active, m + 1, 0).astype(np.int32)
            # integer bookkeeping round-trips the device exactly
            ts.lengths = np.asarray(t_len).astype(np.int32)
            ts.last_tokens = np.asarray(t_last).astype(np.int32)
            ds.lengths = np.asarray(d_len).astype(np.int32)
            ds.last_tokens = np.asarray(d_last).astype(np.int32)
            ts.steps += 1
            # the draft table advanced k steps (+1 catch-up when any
            # slot fully accepted), same as the host-driven round
            ds.steps += k + (1 if bool((m[occupied] == k).any()) else 0)
            self.rounds += 1
            self.proposed += k * occupied.size
            self.accepted += int(m[occupied].sum())
            self.last_spec = True
            return g, counts
        if spec_ok:
            ds = self.draft_session
            try:
                for _ in range(k):
                    _check_draft_poison()
                    if draft_delay:
                        time.sleep(draft_delay)
                    drafts.append(np.asarray(ds.decode()))
            except BaseException as e:
                # draft died mid-round: discard its proposals and keep
                # the stream moving with a plain target step THIS round
                self._degrade(e)
                spec_ok = False
        if spec_ok:
            self.last_draft_end = time.monotonic()
            if step_delay:
                time.sleep(step_delay)
            chunk = np.zeros((N, C), np.int32)
            chunk[:, 0] = ts.last_tokens
            for j in range(k):
                chunk[:, j + 1] = drafts[j]
            fn = self.predictor.verify_fn(N, k)
            g, m, ts._kc, ts._vc = fn(
                self.predictor._state, ts._kc, ts._vc,
                ts._put(ts.lengths), ts._put(chunk),
                ts._put(active))
            g = np.asarray(g)
            m = np.where(active, np.asarray(m), 0).astype(np.int32)
            counts = np.where(active, m + 1, 0).astype(np.int32)
            ts.lengths = (ts.lengths + counts).astype(np.int32)
            ts.last_tokens = np.where(
                active, g[np.arange(N), np.minimum(m, k)],
                ts.last_tokens).astype(np.int32)
            ts.steps += 1
            # draft sync: rejected rows roll back; fully-accepted slots
            # owe the draft one catch-up row (it emitted d_k without
            # ever consuming it)
            if not self._degraded:
                for s in occupied:
                    s = int(s)
                    if m[s] < k:
                        self.draft_session.rollback(
                            s, k - 1 - int(m[s]),
                            last_token=int(g[s, m[s]]))
                full = active & (m == k)
                if full.any():
                    self._draft_catchup(full, g[:, k],
                                        draft_delay=draft_delay)
            self.rounds += 1
            self.proposed += k * occupied.size
            self.accepted += int(m[occupied].sum())
            self.last_spec = True
            return g, counts
        # plain fallback round: one target step, every occupied slot
        # advances exactly one token (degraded mode lives here)
        if step_delay:
            time.sleep(step_delay)
        toks1 = ts.decode()
        self.plain_steps += 1
        if not self._degraded and active.any():
            self._draft_catchup(active, toks1, draft_delay=draft_delay)
        out = np.zeros((N, C), np.int32)
        out[:, 0] = toks1
        return out, active.astype(np.int32)


def load_decode_predictor(dirname, kv_cache_dtype=None):
    """Open a `save_decode_model` artifact (fresh-process serving);
    `kv_cache_dtype` overrides the artifact's cache-numerics pin."""
    return GenerativePredictor(dirname, kv_cache_dtype=kv_cache_dtype)


def greedy_decode(predictor, tokens, max_new_tokens, n_slots=1,
                  slot=0, session=None):
    """Single-request reference decode: prefill + step loop on a
    dedicated session — the unbatched oracle the continuous-batching
    parity tests (and bench_serving's bit_exact replay) compare
    against.  Returns (generated_tokens, finish_reason)."""
    sess = session if session is not None \
        else predictor.new_session(n_slots)
    out = []
    reason = "length"
    tok = sess.prefill(slot, tokens)
    out.append(tok)
    eos = predictor.eos_id
    try:
        while len(out) < max_new_tokens and out[-1] != eos:
            if sess.room(slot) <= 0:
                break
            toks = sess.decode()
            out.append(int(toks[slot]))
    finally:
        sess.free(slot)
    if out[-1] == eos:
        reason = "eos"
    return out, reason
