"""Autoregressive generation: decode artifacts, prefill/decode phase
split, and the slot-table KV cache the serving layer batches over.

The one-shot Predictor serves classifier-shaped programs: fixed-shape
in, fixed-shape out, stateless between calls.  Generation breaks that
contract — each request carries growing state (the KV cache) across
many tiny steps, and the chip idles unless many requests decode
TOGETHER.  This module is the inference-side half of the answer
(SERVING.md "Continuous batching & streaming" is the serving half):

* a **decode artifact** (`save_decode_model` / `build_tiny_decode_model`)
  — a directory holding a causal-transformer LM's weights plus a meta
  record (vocab, layers, heads, max_seq_len, eos id, prefill buckets)
  in the typed wire format, detected by `decode_meta.bin` the way the
  AOT predictor is detected by `aot_meta.bin`;
* a **prefill / decode phase split** (`GenerativePredictor`): prefill
  runs the whole prompt through the causal forward once per padded
  *prompt bucket* (each bucket's executable rides the persistent
  compile cache, COMPILE_CACHE.md, so a warm boot deserializes instead
  of retracing), emitting the prompt's K/V and the first generated
  token; decode is ONE fixed-shape step function over the WHOLE slot
  table — XLA compiles it exactly once per (n_slots) geometry, and
  every later step, whatever mix of requests occupies the slots, reuses
  that executable;
* a **slot-indexed KV cache** (`DecodeSession`): [layers, n_slots,
  max_seq_len, heads, head_dim] arrays resident on the session's
  device.  A request owns one slot from prefill to finish; freeing a
  slot ZEROES its cache lines before reuse (no cross-request KV
  leakage — pinned by tests/test_decode_serving.py), and the decode
  step's cache writes are gated by the active mask so a dead slot
  stays zero.  Per-slot math is independent by construction, which is
  what makes batched decode bit-exact vs a single-request session:
  requests joining or leaving the running batch cannot move another
  request's tokens by one bit.

Decode attention gathers K/V from the slot cache through the Pallas
decode kernel (`ops/pallas_kernels.decode_attention` — block geometry
from the shared kernel-tuning registry); sampling is greedy argmax
(deterministic — the parity contract above is exact equality, not
"close").
"""

import hashlib
import json
import os
import threading
import warnings

import numpy as np

__all__ = ["GenerativePredictor", "DecodeSession", "save_decode_model",
           "build_tiny_decode_model", "load_decode_predictor",
           "greedy_decode", "DECODE_META"]

DECODE_META = "decode_meta.bin"
_DECODE_STATE = "decode_state.bin"

# shared-map sentinel, same contract as predictor._UNEXPORTABLE: this
# function cannot ride the export/serialize path — every clone falls
# back to direct jit without retrying the export
_UNEXPORTABLE = object()


def _default_prefill_buckets(max_seq_len):
    """Powers of two up to max_seq_len (min 8): the prompt-length
    buckets prefill compiles for.  Deterministic by prompt length, so
    two decodes of the same prompt always ride the same executable —
    the bit-exactness contract leans on this."""
    buckets, b = [], 8
    while b < max_seq_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_seq_len))
    return buckets


def save_decode_model(dirname, state, meta):
    """Write a decode artifact: `meta` (vocab_size, d_model, n_heads,
    n_layers, max_seq_len, eos_id, dtype, prefill_buckets) +  `state`
    (the weight dict) in the typed wire format — no pickle, same
    discipline as save_aot."""
    from paddle_tpu.native import wire
    os.makedirs(dirname, exist_ok=True)
    meta = dict(meta)
    meta.setdefault("arch", "causal_lm")
    meta.setdefault("version", 1)
    meta.setdefault("dtype", "float32")
    meta.setdefault("prefill_buckets",
                    _default_prefill_buckets(meta["max_seq_len"]))
    with open(os.path.join(dirname, _DECODE_STATE), "wb") as f:
        f.write(wire.encode({n: np.asarray(v) for n, v in state.items()}))
    with open(os.path.join(dirname, DECODE_META), "wb") as f:
        f.write(wire.encode(meta))
    return dirname


def build_tiny_decode_model(dirname, vocab_size=32, d_model=16,
                            n_heads=2, n_layers=2, max_seq_len=64,
                            eos_id=0, seed=7):
    """Deterministic random-weight tiny causal LM — the CPU-smoke /
    test fixture (the decode analogue of bench_serving's `fc` model).
    Same seed -> bit-identical artifact."""
    if d_model % n_heads:
        raise ValueError("d_model %d not divisible by n_heads %d"
                         % (d_model, n_heads))
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(d_model)

    def w(*shape):
        return (rng.randn(*shape) * scale).astype(np.float32)

    state = {"embed": w(vocab_size, d_model),
             "pos": w(max_seq_len, d_model),
             "lnf_g": np.ones(d_model, np.float32),
             "lnf_b": np.zeros(d_model, np.float32),
             "lm_head": w(d_model, vocab_size)}
    for i in range(n_layers):
        p = "l%d_" % i
        state[p + "ln1_g"] = np.ones(d_model, np.float32)
        state[p + "ln1_b"] = np.zeros(d_model, np.float32)
        state[p + "wq"] = w(d_model, d_model)
        state[p + "wk"] = w(d_model, d_model)
        state[p + "wv"] = w(d_model, d_model)
        state[p + "wo"] = w(d_model, d_model)
        state[p + "ln2_g"] = np.ones(d_model, np.float32)
        state[p + "ln2_b"] = np.zeros(d_model, np.float32)
        state[p + "w1"] = w(d_model, 4 * d_model)
        state[p + "b1"] = np.zeros(4 * d_model, np.float32)
        state[p + "w2"] = w(4 * d_model, d_model)
        state[p + "b2"] = np.zeros(d_model, np.float32)
    meta = {"vocab_size": int(vocab_size), "d_model": int(d_model),
            "n_heads": int(n_heads), "n_layers": int(n_layers),
            "max_seq_len": int(max_seq_len), "eos_id": int(eos_id)}
    return save_decode_model(dirname, state, meta)


def _ln(x, g, b):
    import jax.numpy as jnp
    x = x.astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * g + b


def _causal_attention(q, k, v, scale):
    """Prefill attention oracle: [B, T, H, D] causal, same finite-mask
    convention as the kernels."""
    import jax.numpy as jnp
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    mask = jnp.arange(T)[None, :] < jnp.arange(T)[:, None] + 1
    s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)) \
        / jnp.maximum(jnp.sum(p, axis=-1), 1e-20).transpose(0, 2, 1)[
            ..., None]
    return o


class GenerativePredictor:
    """A decode artifact opened for serving: weights + meta + the two
    compiled phases (per-bucket prefill, one fixed-shape decode step
    per slot-table size).  `device` pins state and compute to one
    jax.Device — the serving registry's replica placement; `clone_to`
    shares the artifact read and the in-process export map so N
    same-device-kind replicas deserialize ONE executable each
    (COMPILE_CACHE.md)."""

    def __init__(self, dirname, device=None, _clone_of=None):
        from paddle_tpu.native import wire
        if _clone_of is not None:
            src = _clone_of
            self.meta = src.meta
            self._state_host = src._state_host
            self._shared_exports = src._shared_exports
            self._shared_lock = src._shared_lock
            self._model_fp = src._model_fp
        else:
            with open(os.path.join(dirname, DECODE_META), "rb") as f:
                self.meta = wire.decode(f.read())
            with open(os.path.join(dirname, _DECODE_STATE), "rb") as f:
                self._state_host = wire.decode(f.read())
            # (device_kind, phase-key) -> jitted call, shared BY
            # REFERENCE across clone_to replicas
            self._shared_exports = {}
            self._shared_lock = threading.Lock()
            self._model_fp = hashlib.sha256(json.dumps(
                {k: self.meta[k] for k in sorted(self.meta)},
                sort_keys=True, default=str).encode()).hexdigest()
        self._device = device
        if device is not None:
            import jax
            self._state = {n: jax.device_put(np.asarray(v), device)
                           for n, v in self._state_host.items()}
        else:
            self._state = {n: np.asarray(v)
                           for n, v in self._state_host.items()}
        self._fns = {}          # per-instance resolved callables
        self._lock = threading.Lock()

    # -- meta surface ---------------------------------------------------

    @property
    def device(self):
        return self._device

    @property
    def vocab_size(self):
        return int(self.meta["vocab_size"])

    @property
    def max_seq_len(self):
        return int(self.meta["max_seq_len"])

    @property
    def eos_id(self):
        return int(self.meta["eos_id"])

    @property
    def is_decode(self):
        return True

    def prefill_buckets(self):
        return tuple(int(b) for b in self.meta["prefill_buckets"])

    def batch_buckets(self):
        """Serving introspection parity with Predictor/AotPredictor:
        for a decode model the 'buckets' are the prompt-length prefill
        buckets."""
        return self.prefill_buckets()

    def prompt_bucket(self, prompt_len):
        """Smallest prefill bucket >= prompt_len (deterministic by
        length — the parity contract rides this)."""
        for b in self.prefill_buckets():
            if prompt_len <= b:
                return b
        raise ValueError(
            "prompt of %d tokens exceeds the largest prefill bucket %d "
            "(max_seq_len %d)" % (prompt_len,
                                  self.prefill_buckets()[-1],
                                  self.max_seq_len))

    def clone_to(self, device):
        return GenerativePredictor(None, device=device, _clone_of=self)

    # -- static byte accounting (ANALYSIS.md resource analysis) ---------

    def kv_cache_bytes(self, n_slots):
        """Closed-form slot-table KV cache footprint for an `n_slots`
        session: K and V, [L, n_slots, S, H, Dh] fp32 each — the HBM
        term that bounds decode slots (FLAGS.serving_decode_slots) and
        the number the admission fit check adds per replica."""
        L, H, Dh, _ = self._dims()
        return 2 * L * int(n_slots) * self.max_seq_len * H * Dh * 4

    def param_bytes(self):
        """Static weight footprint (host-state nbytes sum)."""
        return sum(int(np.asarray(v).nbytes)
                   for v in self._state_host.values())

    # -- model math -----------------------------------------------------

    def _dims(self):
        m = self.meta
        return (int(m["n_layers"]), int(m["n_heads"]),
                int(m["d_model"]) // int(m["n_heads"]),
                int(m["d_model"]))

    def _prefill_math(self, state, tokens, true_len):
        """tokens [1, B] int32, true_len scalar int32 -> (first_token
        [] int32, k/v [L, 1, B, H, Dh] with pad positions zeroed)."""
        import jax.numpy as jnp
        L, H, Dh, D = self._dims()
        B = tokens.shape[1]
        scale = 1.0 / np.sqrt(Dh)
        x = state["embed"][tokens] + state["pos"][:B][None]
        ks, vs = [], []
        for i in range(L):
            p = "l%d_" % i
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ state[p + "wq"]).reshape(1, B, H, Dh)
            k = (h @ state[p + "wk"]).reshape(1, B, H, Dh)
            v = (h @ state[p + "wv"]).reshape(1, B, H, Dh)
            att = _causal_attention(q, k, v, scale).reshape(1, B, D)
            x = x + att @ state[p + "wo"]
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            x = x + jnp.maximum(h2 @ state[p + "w1"] + state[p + "b1"],
                                0.0) @ state[p + "w2"] + state[p + "b2"]
            ks.append(k)
            vs.append(v)
        logits = _ln(x, state["lnf_g"], state["lnf_b"]) @ state["lm_head"]
        first = jnp.argmax(logits[0, true_len - 1], axis=-1).astype(
            jnp.int32)
        # zero the pad positions: the slot cache must hold exact zeros
        # past the live length (free() zeroes, writes are length-gated —
        # this keeps prefill on the same contract)
        live = (jnp.arange(B)[None, :, None, None]
                < true_len)[None]            # [1, 1, B, 1, 1]
        kc = jnp.where(live, jnp.stack(ks), 0.0)
        vc = jnp.where(live, jnp.stack(vs), 0.0)
        return first, kc, vc

    def _step_math(self, state, kc, vc, lengths, last_tokens, active):
        """One fixed-shape decode step over the whole slot table.
        kc/vc [L, N, S, H, Dh], lengths [N] i32 (live cached positions),
        last_tokens [N] i32, active [N] bool -> (new_tokens [N] i32,
        kc', vc').  Cache writes are gated by `active`, so a freed
        (zeroed) slot stays zero and per-slot independence is exact."""
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas_kernels import decode_attention
        L, H, Dh, D = self._dims()
        N, S = kc.shape[1], kc.shape[2]
        scale = 1.0 / np.sqrt(Dh)
        x = state["embed"][last_tokens] + state["pos"][lengths]  # [N, D]
        write = (jnp.arange(S)[None, :] == lengths[:, None]) \
            & active[:, None]                                   # [N, S]
        wmask = write[:, :, None, None]
        kcs, vcs = [], []
        for i in range(L):
            p = "l%d_" % i
            h = _ln(x, state[p + "ln1_g"], state[p + "ln1_b"])
            q = (h @ state[p + "wq"]).reshape(N, H, Dh)
            k_new = (h @ state[p + "wk"]).reshape(N, H, Dh)
            v_new = (h @ state[p + "wv"]).reshape(N, H, Dh)
            kci = jnp.where(wmask, k_new[:, None], kc[i])
            vci = jnp.where(wmask, v_new[:, None], vc[i])
            att = decode_attention(q, kci, vci, lengths + 1,
                                   scale=scale)
            x = x + att.reshape(N, D) @ state[p + "wo"]
            h2 = _ln(x, state[p + "ln2_g"], state[p + "ln2_b"])
            x = x + jnp.maximum(h2 @ state[p + "w1"] + state[p + "b1"],
                                0.0) @ state[p + "w2"] + state[p + "b2"]
            kcs.append(kci)
            vcs.append(vci)
        logits = _ln(x, state["lnf_g"], state["lnf_b"]) @ state["lm_head"]
        new_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return new_tok, jnp.stack(kcs), jnp.stack(vcs)

    # -- compiled-phase resolution (the PR 6 compile-cache ride) --------

    def _fingerprint(self, phase_key, arg_specs):
        from paddle_tpu import compile_cache as cc
        return {
            "kind": "decode_phase",
            "model": self._model_fp,
            "phase": list(phase_key),
            "state": cc._spec_sig(self._state_host),
            "args": [[list(s.shape), str(s.dtype)] for s in arg_specs],
            "env": cc.environment_fingerprint(self._device),
        }

    def _device_kind(self):
        import jax
        d = self._device
        if d is None:
            devs = jax.devices()
            d = devs[0] if devs else None
        return "%s/%s" % (getattr(d, "platform", "cpu"),
                          getattr(d, "device_kind", ""))

    def _resolve(self, phase_key, math_fn, arg_specs):
        """Persistent-cache-first compile of one phase (same order as
        Predictor._get_aot_fn: in-process shared map -> store hit ->
        fresh export+commit -> legacy jit fallback)."""
        import time as _time
        import jax
        fn = self._fns.get(phase_key)
        if fn is not None:
            return fn
        with self._lock:
            fn = self._fns.get(phase_key)
            if fn is not None:
                return fn
            fn = self._resolve_locked(phase_key, math_fn, arg_specs,
                                      _time, jax)
            self._fns[phase_key] = fn
            return fn

    def _resolve_locked(self, phase_key, math_fn, arg_specs, _time, jax):
        from paddle_tpu import compile_cache as cc
        state_spec = {n: jax.ShapeDtypeStruct(np.shape(v),
                                              np.asarray(v).dtype)
                      for n, v in self._state_host.items()}
        if cc.cache_enabled() and not (
                self._device is not None
                and self._device.platform != jax.default_backend()):
            skey = (self._device_kind(), phase_key)
            with self._shared_lock:
                ent = self._shared_exports.get(skey)
            if ent is _UNEXPORTABLE:
                return self._jit_fallback(math_fn, state_spec, arg_specs)
            if ent is not None:
                return ent
            from jax import export as jax_export
            cache = cc.default_cache()
            fn = None
            try:
                fp = self._fingerprint(phase_key, arg_specs)
                blob = cache.get(fp) if cache is not None else None
                if blob is not None:
                    try:
                        t0 = _time.monotonic()
                        exp = jax_export.deserialize(blob)
                        fn = jax.jit(exp.call)
                        cc.note_deserialize_ms(
                            (_time.monotonic() - t0) * 1000.0)
                    except Exception:
                        blob = None
                if fn is None:
                    t0 = _time.monotonic()
                    exp = jax_export.export(jax.jit(math_fn))(
                        state_spec, *arg_specs)
                    cc.note_compile_ms(
                        (_time.monotonic() - t0) * 1000.0)
                    if cache is not None:
                        cache.put(fp, exp.serialize())
                    fn = jax.jit(exp.call)
            except Exception as e:
                with self._shared_lock:
                    already = self._shared_exports.get(skey)
                    self._shared_exports[skey] = _UNEXPORTABLE
                if already is not _UNEXPORTABLE:
                    warnings.warn(
                        "compile cache disabled for decode phase %r "
                        "(export failed: %s: %s) — falling back to "
                        "direct compilation"
                        % (phase_key, type(e).__name__, e),
                        RuntimeWarning, stacklevel=4)
                return self._jit_fallback(math_fn, state_spec, arg_specs)
            with self._shared_lock:
                self._shared_exports[skey] = fn
            return fn
        return self._jit_fallback(math_fn, state_spec, arg_specs)

    @staticmethod
    def _jit_fallback(math_fn, state_spec, arg_specs):
        import jax
        # compile NOW (not on first call) so warm() covers the stall
        return jax.jit(math_fn).lower(state_spec, *arg_specs).compile()

    def prefill_fn(self, bucket):
        import jax
        bucket = int(bucket)
        specs = (jax.ShapeDtypeStruct((1, bucket), np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((), np.dtype(np.int32)))
        return self._resolve(("prefill", bucket), self._prefill_math,
                             specs)

    def step_fn(self, n_slots):
        import jax
        L, H, Dh, _ = self._dims()
        S = self.max_seq_len
        cache = jax.ShapeDtypeStruct((L, int(n_slots), S, H, Dh),
                                     np.dtype(np.float32))
        specs = (cache, cache,
                 jax.ShapeDtypeStruct((int(n_slots),),
                                      np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((int(n_slots),),
                                      np.dtype(np.int32)),
                 jax.ShapeDtypeStruct((int(n_slots),), np.dtype(bool)))
        return self._resolve(("step", int(n_slots)), self._step_math,
                             specs)

    def new_session(self, n_slots):
        return DecodeSession(self, n_slots)


class DecodeSession:
    """One slot table: the per-lane KV cache + occupancy bookkeeping.
    NOT thread-safe — a serving lane owns its session exclusively (the
    decode loop is single-threaded per replica by design: the step
    function is one executable over the whole table)."""

    def __init__(self, predictor, n_slots):
        import jax
        import jax.numpy as jnp
        self.predictor = predictor
        self.n_slots = int(n_slots)
        L, H, Dh, _ = predictor._dims()
        S = predictor.max_seq_len
        shape = (L, self.n_slots, S, H, Dh)
        z = jnp.zeros(shape, jnp.float32)
        if predictor.device is not None:
            z = jax.device_put(z, predictor.device)
        self._kc = z
        self._vc = z
        self.lengths = np.zeros(self.n_slots, np.int32)
        self.last_tokens = np.zeros(self.n_slots, np.int32)
        self.active = np.zeros(self.n_slots, bool)
        self.steps = 0

    # -- occupancy ------------------------------------------------------

    def free_slots(self):
        return [i for i in range(self.n_slots) if not self.active[i]]

    def occupancy(self):
        return int(self.active.sum())

    # -- phases ---------------------------------------------------------

    def _put(self, arr):
        import jax
        if self.predictor.device is not None:
            return jax.device_put(arr, self.predictor.device)
        return arr

    def prefill(self, slot, tokens):
        """Run the prompt through the bucketed prefill, land its K/V in
        `slot`, and return the first generated token (greedy).  The
        slot must be free (and therefore zeroed)."""
        import jax.lax
        if self.active[slot]:
            raise ValueError("slot %d is occupied" % slot)
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = tokens.size
        if n < 1:
            raise ValueError("empty prompt")
        bucket = self.predictor.prompt_bucket(n)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = tokens
        fn = self.predictor.prefill_fn(bucket)
        first, kc, vc = fn(self.predictor._state, self._put(padded),
                           self._put(np.int32(n)))
        # land the bucket-length K/V at the slot; positions past the
        # bucket are already zero (the slot was zeroed on free)
        at = (0, slot, 0, 0, 0)
        self._kc = jax.lax.dynamic_update_slice(self._kc, kc, at)
        self._vc = jax.lax.dynamic_update_slice(self._vc, vc, at)
        tok = int(first)
        self.lengths[slot] = n
        self.last_tokens[slot] = tok
        self.active[slot] = True
        return tok

    def decode(self):
        """ONE fixed-shape step over the whole slot table; returns the
        np.int32 [n_slots] token vector (only entries of slots active
        at call time are meaningful).  Bumps each active slot's length
        and last token."""
        fn = self.predictor.step_fn(self.n_slots)
        new_tok, self._kc, self._vc = fn(
            self.predictor._state, self._kc, self._vc,
            self._put(self.lengths), self._put(self.last_tokens),
            self._put(self.active))
        toks = np.asarray(new_tok)
        act = self.active
        self.lengths = self.lengths + act.astype(np.int32)
        self.last_tokens = np.where(act, toks, self.last_tokens).astype(
            np.int32)
        self.steps += 1
        return toks

    def room(self, slot):
        """Generated tokens this slot can still hold (cache positions
        left)."""
        return int(self.predictor.max_seq_len - self.lengths[slot])

    def free(self, slot):
        """Release a slot: its KV lines are ZEROED before it can be
        reused — a later occupant starts from exact zeros, never from a
        previous request's keys (the no-leakage contract the chaos
        decode-disconnect scenario pins)."""
        import jax.lax
        import jax.numpy as jnp
        L = self._kc.shape[0]
        S, H, Dh = self._kc.shape[2], self._kc.shape[3], self._kc.shape[4]
        z = self._put(jnp.zeros((L, 1, S, H, Dh), jnp.float32))
        at = (0, int(slot), 0, 0, 0)
        self._kc = jax.lax.dynamic_update_slice(self._kc, z, at)
        self._vc = jax.lax.dynamic_update_slice(self._vc, z, at)
        self.lengths[slot] = 0
        self.last_tokens[slot] = 0
        self.active[slot] = False

    def slot_is_zero(self, slot):
        """True when the slot's K and V cache lines are exact zeros —
        the test hook for the zero-before-reuse contract."""
        k = np.asarray(self._kc[:, slot])
        v = np.asarray(self._vc[:, slot])
        return bool(not k.any() and not v.any())


def load_decode_predictor(dirname):
    """Open a `save_decode_model` artifact (fresh-process serving)."""
    return GenerativePredictor(dirname)


def greedy_decode(predictor, tokens, max_new_tokens, n_slots=1,
                  slot=0, session=None):
    """Single-request reference decode: prefill + step loop on a
    dedicated session — the unbatched oracle the continuous-batching
    parity tests (and bench_serving's bit_exact replay) compare
    against.  Returns (generated_tokens, finish_reason)."""
    sess = session if session is not None \
        else predictor.new_session(n_slots)
    out = []
    reason = "length"
    tok = sess.prefill(slot, tokens)
    out.append(tok)
    eos = predictor.eos_id
    try:
        while len(out) < max_new_tokens and out[-1] != eos:
            if sess.room(slot) <= 0:
                break
            toks = sess.decode()
            out.append(int(toks[slot]))
    finally:
        sess.free(slot)
    if out[-1] == eos:
        reason = "eos"
    return out, reason
