"""Image preprocessing helpers (reference python/paddle/utils/
image_util.py) — shared implementation with the v2 image module."""

from ..v2.image import *          # noqa: F401,F403
from ..v2 import image as _img

__all__ = list(getattr(_img, "__all__", []))
