"""User utilities (reference python/paddle/utils/: dump_config, plot,
merge_model, image_util). The config-dump and model-merge tools operate
on this build's Program/topology serialization instead of the
TrainerConfig protobuf."""

from ..v2.plot import Ploter
from . import image_util   # noqa: F401
from .dump_config import dump_config, dump_v2_config
from .merge_model import merge_v2_model
from . import retry       # noqa: F401
from .retry import RetryPolicy

__all__ = ["dump_config", "Ploter", "dump_v2_config", "merge_v2_model",
           "image_util", "retry", "RetryPolicy"]
